"""DeepSpeedCPUAdam — host-resident fused Adam for ZeRO-Offload.

Python binding over the native kernel (csrc/cpu_adam.cpp; reference:
deepspeed/ops/adam/cpu_adam.py:12-134 + csrc/adam/cpu_adam.cpp).  Operates
in place on numpy fp32 buffers (host RAM — the whole point of offload) and
optionally emits a bf16/fp16 copy of the updated params in the same pass,
the analogue of the reference's fused fp16 copy-back
(``step(fp16_param_groups=...)``, cpu_adam.py:116-125).

A pure-numpy fallback keeps the feature usable when no C++ toolchain is
present; selection is explicit and reported (ds_report-style).
"""
from __future__ import annotations

import ctypes
from typing import Callable, Dict, Optional, Tuple, Union

import numpy as np

from .op_builder import OpBuilderError, load_cpu_ops

ScalarOrSchedule = Union[float, Callable]

_LOWP_NONE, _LOWP_BF16, _LOWP_FP16 = 0, 1, 2


def lowp_np_dtype(out_dtype: Optional[str]):
    """None | 'bfloat16' | 'float16' → numpy dtype (single source for the
    mapping used by the kernel binding and the offload tier)."""
    if out_dtype is None:
        return None
    if out_dtype == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    if out_dtype == "float16":
        return np.dtype(np.float16)
    raise ValueError(f"unsupported low-precision dtype {out_dtype!r}")


def is_adam_float(dtype) -> bool:
    """True for dtypes the offload tiers fp32-promote and Adam-step;
    False for passthrough buffers (ints, bools) that keep their dtype
    untouched.  Single source for the promote-vs-passthrough rule —
    ml_dtypes floats (bfloat16, float8_*) are NOT np.floating subdtypes,
    so the numpy predicate alone would silently route them down the
    passthrough path."""
    dt = np.dtype(dtype)
    return (np.issubdtype(dt, np.floating)
            or dt.name.startswith(("bfloat", "float8", "float4", "float6")))


def lowp_np_kind(out_dtype: Optional[str]) -> int:
    """None | 'bfloat16' | 'float16' → the kernel's lowp selector (the
    mapping ``step_leaves`` and the disk tier share)."""
    return {None: _LOWP_NONE, "bfloat16": _LOWP_BF16,
            "float16": _LOWP_FP16}[out_dtype]


def _np_ptr(a: np.ndarray, typ):
    return a.ctypes.data_as(typ)


class DeepSpeedCPUAdam:
    """Fused host Adam over a pytree of numpy fp32 leaves.

    ``step(params, grads, out_dtype=None)`` updates params/moments in place
    and returns the low-precision upload copies (or None).
    """

    def __init__(self, lr: ScalarOrSchedule = 1e-3,
                 betas: Tuple[float, float] = (0.9, 0.999),
                 eps: float = 1e-8,
                 weight_decay: float = 0.0,
                 adamw_mode: bool = True,
                 bias_correction: bool = True,
                 use_native: Optional[bool] = None):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.bias_correction = bias_correction
        self.step_count = 0
        if use_native is None:
            try:
                self._lib = load_cpu_ops()
            except OpBuilderError:
                self._lib = None
        elif use_native:
            self._lib = load_cpu_ops()  # raises if unavailable
        else:
            self._lib = None
        self._state: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}

    @property
    def is_native(self) -> bool:
        return self._lib is not None

    # ------------------------------------------------------------------
    def _moments(self, idx: int, leaf: np.ndarray):
        if idx not in self._state:
            self._state[idx] = (np.zeros_like(leaf), np.zeros_like(leaf))
        return self._state[idx]

    def _lr_now(self) -> float:
        if callable(self.lr):
            return float(self.lr(self.step_count))
        return float(self.lr)

    def step(self, params, grads, out_dtype=None, leaf_get=None):
        """params: pytree of numpy fp32 leaves (updated IN PLACE).
        grads: matching pytree whose leaves may be numpy OR jax Arrays —
        each leaf goes through ``leaf_get`` inside the loop, so callers
        can start async D2H copies for all leaves and have later
        transfers overlap earlier leaves' Adam compute.  ``leaf_get``
        (default np.asarray to fp32) lets the offload tier substitute a
        watchdogged pull that converts a mid-training link stall into a
        clean error instead of an un-interruptible native hang.
        out_dtype: None | 'bfloat16' | 'float16' — fused low-precision
        copies returned as a matching pytree of reinterpreted uint16
        views."""
        import jax
        _, treedef = jax.tree.flatten(params)
        outs = []
        for _i, out in self.step_leaves(params, grads, out_dtype=out_dtype,
                                        leaf_get=leaf_get):
            outs.append(out)
        return (jax.tree.unflatten(treedef, outs)
                if out_dtype is not None else None)

    def apply_leaf(self, flat_p, flat_g, m, v, lr, lowp_kind):
        """ONE leaf's fused Adam against caller-provided flat fp32
        buffers (params/moments updated IN PLACE; ``self.step_count``
        must already be advanced by the caller).  The single kernel
        entry both ``step_leaves`` and the disk offload tier
        (runtime/disk_offload.py) call — which is what makes the disk
        tier's update BITWISE the host tier's: same native call, same
        numpy fallback, no third implementation.  Returns the uint16
        low-precision output buffer (empty when ``lowp_kind`` is
        ``_LOWP_NONE``)."""
        out = (np.empty(flat_p.shape, np.uint16)
               if lowp_kind else np.empty(0, np.uint16))
        if self._lib is not None:
            fp = ctypes.POINTER(ctypes.c_float)
            u16 = ctypes.POINTER(ctypes.c_uint16)
            self._lib.ds_cpu_adam_step(
                flat_p.size, _np_ptr(flat_p, fp),
                _np_ptr(flat_g, fp),
                _np_ptr(m, fp), _np_ptr(v, fp),
                lr, self.betas[0], self.betas[1], self.eps,
                self.weight_decay, int(self.adamw_mode),
                int(self.bias_correction), self.step_count,
                _np_ptr(out, u16), lowp_kind)
        else:
            self._numpy_step(flat_p, flat_g, m, v, lr, out, lowp_kind)
        return out

    def step_leaves(self, params, grads, out_dtype=None, leaf_get=None,
                    leaf_span=None):
        """Per-leaf generator form of ``step``: yields ``(i, out_leaf)``
        the moment leaf ``i``'s master/moment blocks are written — the
        hook the streaming offload pipeline consumes to start leaf
        ``i``'s H2D upload while the Adam loop continues on leaf ``i+1``
        (runtime/offload.py).  ``out_leaf`` is the low-precision view
        when ``out_dtype`` is set (the leaf itself for non-fp32
        passthrough state), None otherwise.  ``leaf_span`` (optional):
        ``leaf_span(i)`` returns a context manager bracketing leaf i's
        compute — telemetry's per-leaf Adam spans, which the overlap
        tests read against the per-leaf H2D spans.  The step counter
        increments once, when iteration starts."""
        import contextlib
        import jax
        if leaf_get is None:
            leaf_get = lambda a: np.asarray(a, dtype=np.float32)  # noqa: E731
        self.step_count += 1
        lr = self._lr_now()
        p_leaves = jax.tree.leaves(params)
        g_leaves = jax.tree.leaves(grads)
        assert len(p_leaves) == len(g_leaves)
        lowp_kind = lowp_np_kind(out_dtype)
        for i, (p, g) in enumerate(zip(p_leaves, g_leaves)):
            if p.dtype != np.float32:
                # non-floating state (step counters, int buffers): no Adam
                yield i, (p if lowp_kind else None)
                continue
            # the span brackets leaf i's COMPUTE only (grad pull + Adam
            # kernel) — the yield happens outside it, so consumer time
            # (the pipeline's upload submit) never inflates it
            with (leaf_span(i) if leaf_span is not None
                  else contextlib.nullcontext()):
                assert p.flags.c_contiguous, (
                    f"leaf {i} is not C-contiguous; reshape(-1) would "
                    "update a copy and silently drop the result — pass a "
                    "contiguous master buffer")
                m, v = self._moments(i, p)
                flat_p = p.reshape(-1)
                flat_g = np.ascontiguousarray(
                    np.asarray(leaf_get(g), dtype=np.float32).reshape(-1))
                out = self.apply_leaf(flat_p, flat_g, m.reshape(-1),
                                      v.reshape(-1), lr, lowp_kind)
                out_leaf = (out.view(lowp_np_dtype(out_dtype))
                            .reshape(p.shape) if lowp_kind else None)
            yield i, out_leaf

    # ------------------------------------------------------------------
    def _numpy_step(self, p, g, m, v, lr, out, lowp_kind):
        b1, b2 = self.betas
        if not self.adamw_mode and self.weight_decay > 0:
            g = g + self.weight_decay * p
        m *= b1
        m += (1 - b1) * g
        v *= b2
        v += (1 - b2) * g * g
        c1 = c2 = 1.0
        if self.bias_correction:
            c1 = 1 - b1 ** self.step_count
            c2 = 1 - b2 ** self.step_count
        update = (m / c1) / (np.sqrt(v) / np.sqrt(c2) + self.eps)
        if self.adamw_mode and self.weight_decay > 0:
            update = update + self.weight_decay * p
        p -= lr * update
        if lowp_kind == _LOWP_BF16:
            out[:] = p.astype(lowp_np_dtype("bfloat16")).view(np.uint16)
        elif lowp_kind == _LOWP_FP16:
            out[:] = p.astype(np.float16).view(np.uint16)

    # ------------------------------------------------------------------
    def state_dict(self):
        return {"step": self.step_count,
                "moments": {str(k): (m.copy(), v.copy())
                            for k, (m, v) in self._state.items()}}

    def load_state_dict(self, sd):
        self.step_count = int(sd["step"])
        self._state = {int(k): (np.array(m), np.array(v))
                       for k, (m, v) in sd["moments"].items()}
