"""Model-surgery helpers for sparse attention (reference:
deepspeed/ops/sparse_attention/sparse_attention_utils.py:13-225).

The reference mutates HuggingFace torch models in place; here the helpers
are functional — they return new arrays/param trees — which is the JAX way
and keeps them usable inside jit-free setup code.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np


class SparseAttentionUtils:
    @staticmethod
    def extend_position_embedding(pos_emb: jnp.ndarray,
                                  max_position: int) -> jnp.ndarray:
        """Extend a [T0, D] position-embedding table to ``max_position``
        rows by tiling the original table (the reference's scheme of
        repeating the base embeddings, sparse_attention_utils.py:53-88)."""
        T0, D = pos_emb.shape
        if max_position <= T0:
            return pos_emb[:max_position]
        reps = -(-max_position // T0)  # ceil
        return jnp.tile(pos_emb, (reps, 1))[:max_position]

    @staticmethod
    def pad_to_block_size(block_size: int,
                          input_ids: jnp.ndarray,
                          attention_mask: Optional[jnp.ndarray] = None,
                          token_type_ids: Optional[jnp.ndarray] = None,
                          position_ids: Optional[jnp.ndarray] = None,
                          inputs_embeds: Optional[jnp.ndarray] = None,
                          pad_token_id: int = 0,
                          ) -> Tuple[int, tuple]:
        """Right-pad sequence tensors so seq_len % block_size == 0
        (reference sparse_attention_utils.py:173-210).  Padded positions
        get mask 0 so they are ignored by the attention.

        Returns (pad_len, (input_ids, attention_mask, token_type_ids,
        position_ids, inputs_embeds)) with None entries passed through.
        """
        seq_len = input_ids.shape[-1] if input_ids is not None \
            else inputs_embeds.shape[-2]
        pad_len = (block_size - seq_len % block_size) % block_size
        if pad_len == 0:
            return 0, (input_ids, attention_mask, token_type_ids,
                       position_ids, inputs_embeds)

        def pad_tok(x, value=0):
            if x is None:
                return None
            cfg = [(0, 0)] * (x.ndim - 1) + [(0, pad_len)]
            return jnp.pad(x, cfg, constant_values=value)

        input_ids = pad_tok(input_ids, pad_token_id)
        attention_mask = pad_tok(attention_mask, 0)
        token_type_ids = pad_tok(token_type_ids, 0)
        if position_ids is not None:
            # continue the position sequence into the padding
            last = position_ids[..., -1:]
            extra = last + jnp.arange(1, pad_len + 1)
            position_ids = jnp.concatenate([position_ids, extra], axis=-1)
        if inputs_embeds is not None:
            cfg = [(0, 0)] * (inputs_embeds.ndim - 2) + [(0, pad_len), (0, 0)]
            inputs_embeds = jnp.pad(inputs_embeds, cfg)
        return pad_len, (input_ids, attention_mask, token_type_ids,
                         position_ids, inputs_embeds)

    @staticmethod
    def unpad_sequence_output(pad_len: int,
                              sequence_output: jnp.ndarray) -> jnp.ndarray:
        """Drop the padding added by pad_to_block_size (reference
        sparse_attention_utils.py:212-225)."""
        if pad_len == 0:
            return sequence_output
        return sequence_output[..., :-pad_len, :] \
            if sequence_output.ndim >= 2 else sequence_output[:-pad_len]
