"""Standalone block-sparse MatMul (sdd / dsd / dds) and Softmax ops.

The reference exposes its Triton block-sparse kernels as reusable ops —
``MatMul(layout, block, mode)`` and ``Softmax(layout, block)``
(reference: deepspeed/ops/sparse_attention/matmul.py:16, softmax.py) —
which its attention composes as sdd -> softmax -> dsd.  This repo's
attention runs a fused Pallas kernel instead
(ops/pallas/block_sparse_attention.py), so these classes restore the
*general-purpose* surface for users composing their own sparse programs.

TPU-first formulation: the sparse operand is block-COO — active-block
values ``[..., nnz, block, block]`` ordered row-major over a trace-time
numpy index — and every mode is a gather + ONE batched matmul (XLA tiles
batched [block x K x block] contractions straight onto the MXU) plus a
segment-sum scatter where a sparse output accumulates.  No per-block
Python loops, static shapes, differentiable end to end through jnp
autodiff (the reference needs hand-written backward Triton passes;
here dsd/dds ARE each other's VJPs automatically).

Layout is a 2-D ``[nb_rows, nb_cols]`` 0/1 array: the standalone surface
is per-matrix (multi-head attention layouts are head-uniform in every
stock config — pass ``layout[0]``; genuinely per-head programs vmap over
the head axis with per-head instances).
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["MatMul", "Softmax"]


def _as_layout2d(layout) -> np.ndarray:
    lay = np.asarray(layout)
    if lay.ndim == 3:
        if lay.shape[0] != 1 and not (lay == lay[:1]).all():
            raise ValueError(
                "standalone sparse ops take a single 2-D layout; this "
                "3-D layout differs across heads — vmap per-head "
                "instances instead")
        lay = lay[0]
    if lay.ndim != 2:
        raise ValueError(f"layout must be 2-D [nb, nb], got {lay.shape}")
    return (lay != 0)


class _BlockIndex:
    """Trace-time row-major block-COO index of a 0/1 layout."""

    def __init__(self, layout):
        self.mask = _as_layout2d(layout)
        self.nb_r, self.nb_c = self.mask.shape
        r, c = np.nonzero(self.mask)
        order = np.lexsort((c, r))          # row-major
        self.rows = r[order].astype(np.int32)
        self.cols = c[order].astype(np.int32)
        self.nnz = len(self.rows)
        if self.nnz == 0:
            raise ValueError("layout has no active blocks")


class MatMul:
    """Block-sparse matmul in one of the reference's three modes.

    mode 'sdd':  C_sparse = A_dense @ B_dense   (only active blocks)
        a: [..., M, K], b: [..., K, N] -> [..., nnz, block, block]
    mode 'dsd':  C_dense  = A_sparse @ B_dense
        a: [..., nnz, block, block], b: [..., K, N] -> [..., M, N]
    mode 'dds':  C_dense  = A_dense @ B_sparse
        a: [..., M, K], b: [..., nnz, block, block] -> [..., M, N]

    ``trans_a`` / ``trans_b`` transpose the *dense* operand(s) before the
    product (the reference flag surface); a transposed sparse operand is
    expressed by transposing the layout and swapping to the dual mode.
    """

    def __init__(self, layout, block: int, mode: str,
                 trans_a: bool = False, trans_b: bool = False):
        if mode not in ("sdd", "dsd", "dds"):
            raise ValueError(f"mode must be sdd|dsd|dds, got {mode!r}")
        if mode != "sdd" and (trans_a if mode == "dsd" else trans_b):
            raise ValueError(
                "transposing the sparse operand: transpose the layout "
                "and use the dual mode instead")
        self.index = _BlockIndex(layout)
        self.block = int(block)
        self.mode = mode
        self.trans_a = trans_a
        self.trans_b = trans_b

    @property
    def layout(self) -> np.ndarray:
        return self.index.mask

    def _blockify(self, x, nb: int, what: str):
        """[..., nb*block, D] -> [..., nb, block, D]"""
        if x.shape[-2] != nb * self.block:
            raise ValueError(
                f"{what} dim {x.shape[-2]} != {nb} blocks x {self.block}")
        return x.reshape(*x.shape[:-2], nb, self.block, x.shape[-1])

    def __call__(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        idx, blk = self.index, self.block
        rows = jnp.asarray(idx.rows)
        cols = jnp.asarray(idx.cols)
        if self.mode == "sdd":
            if self.trans_a:
                a = jnp.swapaxes(a, -1, -2)
            if self.trans_b:
                b = jnp.swapaxes(b, -1, -2)
            ab = self._blockify(a, idx.nb_r, "a rows")          # [..., nbr, blk, K]
            bb = self._blockify(jnp.swapaxes(b, -1, -2),
                                idx.nb_c, "b cols")             # [..., nbc, blk, K]
            ga = jnp.take(ab, rows, axis=-3)                    # [..., nnz, blk, K]
            gb = jnp.take(bb, cols, axis=-3)                    # [..., nnz, blk, K]
            return jnp.einsum("...zik,...zjk->...zij", ga, gb)
        if self.mode == "dsd":
            if self.trans_b:
                b = jnp.swapaxes(b, -1, -2)
            bb = self._blockify(b, idx.nb_c, "b rows")          # [..., nbc, blk, N]
            gb = jnp.take(bb, cols, axis=-3)                    # [..., nnz, blk, N]
            part = jnp.einsum("...zij,...zjn->...zin", a, gb)   # [..., nnz, blk, N]
            # scatter-add on the nnz axis IN PLACE (a leading-axis
            # segment_sum needs moveaxis transposes that trip XLA CPU's
            # algebraic simplifier — RET_CHECK crash observed)
            out = jnp.zeros((*part.shape[:-3], idx.nb_r,
                             blk, part.shape[-1]), part.dtype)
            out = out.at[..., rows, :, :].add(part)             # [..., nbr, blk, N]
            return out.reshape(*out.shape[:-3],
                               idx.nb_r * blk, out.shape[-1])
        # dds
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        ab = self._blockify(jnp.swapaxes(a, -1, -2),
                            idx.nb_r, "a cols")                 # [..., nbr, blk, M]
        ga = jnp.take(ab, rows, axis=-3)                        # [..., nnz, blk, M]
        part = jnp.einsum("...zkm,...zkj->...zmj", ga, b)       # [..., nnz, M, blk]
        out = jnp.zeros((*part.shape[:-3], idx.nb_c,
                         part.shape[-2], blk), part.dtype)
        out = out.at[..., cols, :, :].add(part)                 # [..., nbc, M, blk]
        out = jnp.swapaxes(out, -3, -2)                         # [..., M, nbc, blk]
        return out.reshape(*out.shape[:-2], idx.nb_c * blk)     # [..., M, N]


class Softmax:
    """Row softmax over a block-sparse matrix in block-COO values form.

    x: [..., nnz, block, block] (the sdd output) -> same shape, where each
    scores row (a row inside a row-block, spanning that row-block's active
    column blocks) is softmaxed over the ACTIVE columns only — inactive
    blocks are exactly zero, matching the reference's sparse softmax
    (softmax.py there) and the fused kernel's masked-row semantics
    (fully-inactive rows -> zeros, not NaN).

    ``scale`` multiplies scores first; ``key_padding_mask`` /
    ``attn_mask`` are additive fp masks ([..., N] / [M, N]) applied before
    normalization, mirroring the reference's argument surface.
    """

    def __init__(self, layout, block: int):
        self.index = _BlockIndex(layout)
        self.block = int(block)

    def __call__(self, x: jnp.ndarray, scale: float = 1.0,
                 key_padding_mask: jnp.ndarray = None,
                 attn_mask: jnp.ndarray = None) -> jnp.ndarray:
        idx, blk = self.index, self.block
        rows = jnp.asarray(idx.rows)
        cols = jnp.asarray(idx.cols)
        x = x * scale
        if attn_mask is not None:
            mb = attn_mask.reshape(idx.nb_r, blk, idx.nb_c, blk)
            mb = jnp.swapaxes(mb, 1, 2)                         # [nbr, nbc, blk, blk]
            x = x + mb[idx.rows, idx.cols]
        if key_padding_mask is not None:
            if key_padding_mask.ndim not in (1, 2):
                raise ValueError(
                    "key_padding_mask must be [N] or [batch, N]")
            kb = key_padding_mask.reshape(
                *key_padding_mask.shape[:-1], idx.nb_c, blk)
            kb = jnp.take(kb, cols, axis=-2)        # [(B,) nnz, blk]
            # align with x [..., nnz, blk_rows, blk_cols]: the mask hits
            # the COLUMN axis and is constant over rows; a batched mask's
            # B axis must line up with x's LEADING axis (head/extra axes
            # sit between and get broadcast singletons)
            if key_padding_mask.ndim == 1:
                kb = kb[..., :, None, :]            # [nnz, 1, blk]
            else:
                # axes between B and nnz (e.g. the head axis)
                extra = (x.ndim - 3) - (key_padding_mask.ndim - 1)
                if extra < 0:
                    raise ValueError(
                        f"batched key_padding_mask {key_padding_mask.shape} "
                        f"does not fit values of shape {x.shape}")
                kb = kb.reshape(kb.shape[0], *([1] * extra),
                                kb.shape[-2], 1, kb.shape[-1])
            x = x + kb
        # row-wise logsumexp across this row-block's active blocks via
        # in-place max/sum scatters on the nnz axis (leading-axis segment
        # ops need moveaxis transposes that trip XLA CPU's algebraic
        # simplifier — RET_CHECK crash observed)
        mx = jnp.max(x, axis=-1)                                # [..., nnz, blk]
        fill = jnp.asarray(-1e30, x.dtype)  # -inf in fp16: handled below
        row_max = jnp.full((*x.shape[:-3], idx.nb_r, blk),
                           -1e30, x.dtype)
        row_max = row_max.at[..., rows, :].max(mx)              # [..., nbr, blk]
        # A row whose active columns are ALL masked to -inf never raises
        # row_max above the fill, and in fp16 the fill itself IS -inf —
        # subtracting it would give -inf - -inf = NaN.  Dead rows get a
        # zero shift instead, making exp underflow to 0; the denominator
        # guard below then emits zeros, matching the fused kernel's
        # zeros-for-dead-rows semantics.
        safe_max = jnp.where(row_max <= fill, jnp.zeros_like(row_max),
                             row_max)
        p = jnp.exp(x - jnp.take(safe_max, rows, axis=-2)[..., None])
        row_sum = jnp.zeros_like(row_max).at[..., rows, :].add(
            jnp.sum(p, axis=-1))
        denom = jnp.take(row_sum, rows, axis=-2)[..., None]
        return p / jnp.where(denom == 0.0, 1.0, denom)
