"""Block-sparsity layout generators.

Same config surface as the reference family (reference:
deepspeed/ops/sparse_attention/sparsity_config.py — Dense :63, Fixed :94,
Variable :243, BigBird :421, BSLongformer :544), re-expressed as vectorized
numpy over block-index grids instead of per-element loops.  A layout is an
int64 array [num_heads, num_blocks, num_blocks]; entry (h, r, c) == 1 means
query block r of head h attends to key block c.

Differences from the reference, on purpose:
  - layouts are numpy (they are *static metadata* consumed at trace time by
    the XLA/Pallas kernels, never device tensors);
  - random layouts take an explicit ``seed`` (the reference uses the global
    ``random`` module state, sparsity_config.py:330, which makes layouts
    irreproducible across ranks — a real hazard under SPMD where every host
    must trace the identical layout).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base: shared fields + layout allocation (reference
    sparsity_config.py:9-61)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(
                f"Sequence length {seq_len} must be divisible by block "
                f"size {self.block}")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks),
                        dtype=np.int64)

    def check_and_propagate_first_head_layout(self,
                                              layout: np.ndarray
                                              ) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout


class DenseSparsityConfig(SparsityConfig):
    """All blocks active — kept for comparison/debug (reference :63-94)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout



def _block_grid(num_blocks: int):
    """(row, col) index grids for vectorized masking."""
    return np.meshgrid(np.arange(num_blocks), np.arange(num_blocks),
                       indexing="ij")


def _set_random_layout(h: int, layout: np.ndarray, num_random_blocks: int,
                       seed: int) -> np.ndarray:
    """Mark ``num_random_blocks`` random key blocks per row (shared by
    Variable and BigBird configs; reference sparsity_config.py:314-332,
    452-473 duplicates this too — here it lives once)."""
    nb = layout.shape[1]
    if nb < num_random_blocks:
        raise ValueError(
            f"num_random_blocks {num_random_blocks} must be < "
            f"number of block rows {nb}")
    rng = np.random.default_rng(seed + h)
    for row in range(nb):
        cols = rng.choice(nb, size=num_random_blocks, replace=False)
        layout[h, row, cols] = 1
    return layout


def _set_sliding_window_layout(h: int, layout: np.ndarray,
                               num_sliding_window_blocks: int) -> np.ndarray:
    """Banded local window of width ``num_sliding_window_blocks`` (shared
    by BigBird and BSLongformer configs)."""
    nb = layout.shape[1]
    if nb < num_sliding_window_blocks:
        raise ValueError(
            f"num_sliding_window_blocks {num_sliding_window_blocks}"
            f" must be < number of block rows {nb}")
    w = num_sliding_window_blocks // 2
    row, col = _block_grid(nb)
    layout[h][np.abs(row - col) <= w] = 1
    return layout


class FixedSparsityConfig(SparsityConfig):
    """Fixed local windows + periodic global blocks (Sparse Transformers,
    arXiv:1904.10509; reference :94-241)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4,
                 num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"num_local_blocks {num_local_blocks} must be divisible by "
                f"num_global_blocks {num_global_blocks}")
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only uni/bi-directional attention is supported")
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                "horizontal global attention requires bidirectional mode")
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "multiple global patterns require different_layout_per_head")
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError(
                f"num_different_global_patterns "
                f"{num_different_global_patterns} cannot exceed "
                f"{num_local_blocks // num_global_blocks}")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def set_local_layout(self, h: int, layout: np.ndarray) -> np.ndarray:
        nb = layout.shape[1]
        row, col = _block_grid(nb)
        same_window = (row // self.num_local_blocks
                       == col // self.num_local_blocks)
        if self.attention == "unidirectional":
            same_window &= col <= row
        layout[h][same_window] = 1
        return layout

    def set_global_layout(self, h: int, layout: np.ndarray) -> np.ndarray:
        nb = layout.shape[1]
        lb, gb = self.num_local_blocks, self.num_global_blocks
        first = lb - (1 + h % self.num_different_global_patterns) * gb
        end = nb - (nb % lb)
        for i in range(first, end, lb):
            first_row = 0 if self.attention == "bidirectional" else i
            layout[h, first_row:, i:i + gb] = 1
            if self.horizontal_global_attention:
                layout[h, i:i + gb, :] = 1
        if end < nb:  # short trailing window
            start = min(end + first, nb - gb)
            first_row = 0 if self.attention == "bidirectional" else start
            layout[h, first_row:, start:start + gb] = 1
            if self.horizontal_global_attention:
                layout[h, start:start + gb, :] = 1
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_local_layout(h, layout)
            self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Fixed's generalization: per-window sizes, explicit global block
    indices/ranges, optional random blocks (reference :243-419)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None
                                     else [0])
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    "global_block_indices and global_block_end_indices must "
                    "have equal length")
            for s, e in zip(self.global_block_indices,
                            global_block_end_indices):
                if s >= e:
                    raise ValueError(
                        f"global block start {s} must be < end {e}")
        self.global_block_end_indices = global_block_end_indices
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only uni/bi-directional attention is supported")
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                "horizontal global attention requires bidirectional mode")
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed

    def set_random_layout(self, h: int, layout: np.ndarray) -> np.ndarray:
        return _set_random_layout(h, layout, self.num_random_blocks,
                                  self.seed)

    def set_local_layout(self, h: int, layout: np.ndarray) -> np.ndarray:
        nb = layout.shape[1]
        start = 0
        for size in self.local_window_blocks:
            end = min(start + size, nb)
            self._fill_window(h, layout, start, end)
            start += size
        # tail: repeat the last window size
        size = self.local_window_blocks[-1]
        while start < nb:
            end = min(start + size, nb)
            self._fill_window(h, layout, start, end)
            start += size
        return layout

    def _fill_window(self, h, layout, start, end):
        for row in range(start, end):
            hi = row + 1 if self.attention == "unidirectional" else end
            layout[h, row, start:hi] = 1

    def set_global_layout(self, h: int, layout: np.ndarray) -> np.ndarray:
        nb = layout.shape[1]
        if self.global_block_end_indices is None:
            for idx in self.global_block_indices:
                if idx < nb:
                    if self.horizontal_global_attention:
                        layout[h, idx, :] = 1
                    first_row = (0 if self.attention == "bidirectional"
                                 else idx)
                    layout[h, first_row:, idx] = 1
        else:
            for s, e in zip(self.global_block_indices,
                            self.global_block_end_indices):
                if s < nb:
                    e = min(e, nb)
                    if self.horizontal_global_attention:
                        layout[h, s:e, :] = 1
                    first_row = 0 if self.attention == "bidirectional" else s
                    layout[h, first_row:, s:e] = 1
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_random_layout(h, layout)
            self.set_local_layout(h, layout)
            self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """Random + sliding-window + global blocks (BigBird, arXiv:2007.14062;
    reference :421-543)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1,
                 seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.seed = seed

    def set_random_layout(self, h: int, layout: np.ndarray) -> np.ndarray:
        return _set_random_layout(h, layout, self.num_random_blocks,
                                  self.seed)

    def set_sliding_window_layout(self, h: int,
                                  layout: np.ndarray) -> np.ndarray:
        return _set_sliding_window_layout(
            h, layout, self.num_sliding_window_blocks)

    def set_global_layout_itc(self, h: int,
                              layout: np.ndarray) -> np.ndarray:
        nb = layout.shape[1]
        if nb < self.num_global_blocks:
            raise ValueError(
                f"num_global_blocks {self.num_global_blocks} must be < "
                f"number of block rows {nb}")
        layout[h, :self.num_global_blocks, :] = 1
        layout[h, :, :self.num_global_blocks] = 1
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_random_layout(h, layout)
            self.set_sliding_window_layout(h, layout)
            self.set_global_layout_itc(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + designated global blocks
    (arXiv:2004.05150; reference :544-663)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None
                                     else [0])
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    "global_block_indices and global_block_end_indices must "
                    "have equal length")
            for s, e in zip(self.global_block_indices,
                            global_block_end_indices):
                if s >= e:
                    raise ValueError(
                        f"global block start {s} must be < end {e}")
        self.global_block_end_indices = global_block_end_indices

    def set_sliding_window_layout(self, h: int,
                                  layout: np.ndarray) -> np.ndarray:
        return _set_sliding_window_layout(
            h, layout, self.num_sliding_window_blocks)

    def set_global_layout(self, h: int, layout: np.ndarray) -> np.ndarray:
        nb = layout.shape[1]
        if self.global_block_end_indices is None:
            for idx in self.global_block_indices:
                if idx < nb:
                    layout[h, idx, :] = 1
                    layout[h, :, idx] = 1
        else:
            for s, e in zip(self.global_block_indices,
                            self.global_block_end_indices):
                if s < nb:
                    e = min(e, nb)
                    layout[h, s:e, :] = 1
                    layout[h, :, s:e] = 1
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_sliding_window_layout(h, layout)
            self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)
