"""BERT-style sparse self-attention block (reference:
deepspeed/ops/sparse_attention/bert_sparse_self_attention.py:1-78).

Functional JAX flavor of the reference's drop-in BERT layer: Q/K/V linear
projections + block-sparse attention with the incoming attention mask used
as a key-padding mask ('add' mode, matching the reference default where the
HF mask is already additive -10000.0 style).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from .sparse_self_attention import SparseSelfAttention
from .sparsity_config import FixedSparsityConfig, SparsityConfig


@dataclasses.dataclass(frozen=True)
class BertSelfAttentionConfig:
    hidden_size: int
    num_attention_heads: int

    @property
    def attention_head_size(self) -> int:
        if self.hidden_size % self.num_attention_heads != 0:
            raise ValueError(
                f"hidden size {self.hidden_size} is not a multiple of "
                f"attention heads {self.num_attention_heads}")
        return self.hidden_size // self.num_attention_heads


class BertSparseSelfAttention:
    """``__call__(params, hidden_states, attention_mask)`` →
    context [B, T, hidden]."""

    def __init__(self, config: BertSelfAttentionConfig,
                 sparsity_config: Optional[SparsityConfig] = None):
        self.config = config
        self.sparse_attn = SparseSelfAttention(
            sparsity_config or FixedSparsityConfig(
                num_heads=config.num_attention_heads),
            key_padding_mask_mode="add")

    def init(self, rng):
        d = self.config.hidden_size
        keys = jax.random.split(rng, 3)
        std = 0.02
        mk = lambda k: {"w": jax.random.normal(k, (d, d), jnp.float32) * std,
                        "b": jnp.zeros((d,), jnp.float32)}
        return {"query": mk(keys[0]), "key": mk(keys[1]),
                "value": mk(keys[2])}

    def _split_heads(self, x):
        B, T, _ = x.shape
        H = self.config.num_attention_heads
        Dh = self.config.attention_head_size
        return x.reshape(B, T, H, Dh).transpose(0, 2, 1, 3)

    def __call__(self, params, hidden_states, attention_mask=None):
        proj = lambda p: hidden_states @ p["w"].astype(hidden_states.dtype) \
            + p["b"].astype(hidden_states.dtype)
        q = self._split_heads(proj(params["query"]))
        k = self._split_heads(proj(params["key"]))
        v = self._split_heads(proj(params["value"]))
        ctx = self.sparse_attn(q, k, v, key_padding_mask=attention_mask)
        B, H, T, Dh = ctx.shape
        return ctx.transpose(0, 2, 1, 3).reshape(B, T, H * Dh)
