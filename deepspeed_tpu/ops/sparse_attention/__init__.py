"""Block-sparse attention — the reference's long-sequence feature slot
(reference: deepspeed/ops/sparse_attention/)."""
from .sparsity_config import (BigBirdSparsityConfig,
                              BSLongformerSparsityConfig,
                              DenseSparsityConfig, FixedSparsityConfig,
                              SparsityConfig, VariableSparsityConfig)
from .sparse_self_attention import SparseSelfAttention, build_lut
from .bert_sparse_self_attention import (BertSelfAttentionConfig,
                                         BertSparseSelfAttention)
from .sparse_attention_utils import SparseAttentionUtils
from .matmul import MatMul, Softmax

__all__ = [
    "BigBirdSparsityConfig", "BSLongformerSparsityConfig",
    "DenseSparsityConfig", "FixedSparsityConfig", "SparsityConfig",
    "VariableSparsityConfig", "SparseSelfAttention", "build_lut",
    "BertSelfAttentionConfig", "BertSparseSelfAttention",
    "SparseAttentionUtils", "MatMul", "Softmax",
]
