"""Block-sparse self-attention — TPU-native.

The reference builds this from Triton sdd/dsd matmuls + a block-sparse
softmax kernel with natively-built lookup tables (reference:
deepspeed/ops/sparse_attention/sparse_self_attention.py:83-142, matmul.py,
softmax.py, csrc/sparse_attention/utils.cpp).  Here the layout is compiled
into a *gathered block* computation: for every query block row we gather
its active key/value blocks (a static LUT padded to the row-max count) and
run a dense blockwise attention over just those.  Compute and memory are
O(T · max_active_blocks · block) — the same asymptotics as the Triton
kernels — and everything lowers onto the MXU as batched [block × block]
matmuls.  The LUT is static metadata: XLA sees constant gather indices and
a fixed loop structure, nothing data-dependent.

Semantics preserved from the reference forward
(sparse_self_attention.py:83-142 → softmax.py):
  scores = (Q·Kᵀ) * scale  (only active blocks)
  scores += rpe                      (if given)
  key_padding_mask: 'add' → scores += mask;  'mul' → -inf where mask == 0
  attn_mask:        same two modes
  softmax over the active blocks of each row, then context = probs · V.
Inactive blocks are exactly zero probability — tokens whose *entire* row
is masked out produce zeros, matching the sparse kernel's behavior.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .sparsity_config import FixedSparsityConfig, SparsityConfig

_NEG_INF = float(np.finfo(np.float32).min)


def build_lut(layout: np.ndarray,
              use_native: Optional[bool] = None
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Layout [H, nb, nb] → (cols [H, nb, width], valid [H, nb, width]).

    ``cols[h, r]`` lists the active key-block indices of query-block row r
    (padded with 0), ``valid`` flags real entries.  ``width`` is the max
    active count over all heads/rows — the TPU analogue of the reference's
    ``segment_blocks`` lookup-table build (csrc/sparse_attention/
    utils.cpp:14): the native C++ pass (csrc/sparse_lut.cpp) when the
    host-ops library is up, numpy otherwise (trace-time metadata either
    way).  ``use_native=None`` (default) uses the library only if some
    other component (the offload tier) already built/loaded it — sparse
    attention alone never pays a g++ compile for microseconds of metadata;
    ``True`` forces a build (raising OpBuilderError if the toolchain is
    missing), ``False`` forces numpy.
    """
    H, nb, _ = layout.shape
    if use_native or (use_native is None):
        from ..op_builder import cpu_ops_loaded, load_cpu_ops
        import ctypes
        from ..cpu_adam import _np_ptr
        # use_native=True: build/raise loudly (OpBuilderError when the
        # toolchain is missing — the caller explicitly forced native);
        # auto: only a library someone else already loaded
        lib = load_cpu_ops() if use_native else cpu_ops_loaded()
        if lib is not None:
            lay = np.ascontiguousarray(layout, dtype=np.int32)
            i32p = ctypes.POINTER(ctypes.c_int32)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            width = int(lib.ds_lut_width(H, nb, _np_ptr(lay, i32p)))
            cols = np.zeros((H, nb, width), dtype=np.int32)
            valid = np.zeros((H, nb, width), dtype=np.uint8)
            lib.ds_build_lut(H, nb, _np_ptr(lay, i32p), width,
                             _np_ptr(cols, i32p), _np_ptr(valid, u8p))
            return cols, valid.astype(bool)
    width = max(int(layout.sum(-1).max()), 1)
    cols = np.zeros((H, nb, width), dtype=np.int32)
    valid = np.zeros((H, nb, width), dtype=bool)
    for h in range(H):
        for r in range(nb):
            (active,) = np.nonzero(layout[h, r])
            cols[h, r, :len(active)] = active
            valid[h, r, :len(active)] = True
    return cols, valid


@partial(jax.jit, static_argnames=("block", "kp_mode", "am_mode"))
def _sparse_attn(q, k, v, cols, valid, rpe, key_padding_mask, attn_mask,
                 scale, block: int, kp_mode: str, am_mode: str):
    """q,k,v: [B,H,T,D]; cols/valid: [H, nb, W]; returns [B,H,T,D]."""
    B, H, T, D = q.shape
    nb = T // block
    W = cols.shape[-1]

    qb = q.reshape(B, H, nb, block, D)
    kb = k.reshape(B, H, nb, block, D)
    vb = v.reshape(B, H, nb, block, D)

    def per_head(qh, kh, vh, cols_h, valid_h, am_h):
        # qh: [B, nb, blk, D]; gather active key/value blocks per row
        kg = kh[:, cols_h]            # [B, nb, W, blk, D]
        vg = vh[:, cols_h]
        scores = jnp.einsum("brqd,brwkd->brqwk", qh, kg,
                            preferred_element_type=jnp.float32) * scale
        if rpe is not None:
            # rpe [T, T] → per (row, w) block: rpe[row*blk:, col*blk:]
            rpe_b = rpe.reshape(nb, block, nb, block)
            rpe_g = rpe_b[np.arange(nb)[:, None], :, cols_h, :]  # [nb,W,blk,blk]
            scores = scores + jnp.transpose(
                rpe_g, (0, 2, 1, 3))[None].astype(jnp.float32)
        if am_h is not None:
            am_b = am_h.reshape(nb, block, nb, block)
            am_g = am_b[np.arange(nb)[:, None], :, cols_h, :]
            am_g = jnp.transpose(am_g, (0, 2, 1, 3))[None]  # [1,nb,blk,W,blk]
            if am_mode == "add":
                scores = scores + am_g.astype(jnp.float32)
            else:  # mul
                scores = jnp.where(am_g != 0, scores, _NEG_INF)
        if key_padding_mask is not None:
            # [B, T] → gathered [B, nb, W, blk] → [B,nb,1,W,blk]
            kp_b = key_padding_mask.reshape(B, nb, block)
            kp_g = kp_b[:, cols_h]                      # [B, nb, W, blk]
            kp_g = kp_g[:, :, None, :, :]
            if kp_mode == "add":
                scores = scores + kp_g.astype(jnp.float32)
            else:
                scores = jnp.where(kp_g != 0, scores, _NEG_INF)
        # mask LUT padding
        scores = jnp.where(valid_h[None, :, None, :, None], scores,
                           _NEG_INF)
        flat = scores.reshape(B, nb, block, W * block)
        # guard fully-masked rows (all -inf → zeros, not NaN)
        m = jnp.max(flat, axis=-1, keepdims=True)
        e = jnp.exp(flat - jax.lax.stop_gradient(m))
        e = jnp.where(flat <= _NEG_INF / 2, 0.0, e)
        s = jnp.sum(e, axis=-1, keepdims=True)
        probs = jnp.where(s > 0, e / jnp.maximum(s, 1e-30), 0.0)
        probs = probs.reshape(B, nb, block, W, block).astype(q.dtype)
        return jnp.einsum("brqwk,brwkd->brqd", probs, vg)

    # vmap over heads: one compiled per-head subgraph regardless of H
    out = jax.vmap(per_head, in_axes=(1, 1, 1, 0, 0, None),
                   out_axes=1)(qb, kb, vb, cols, valid, attn_mask)
    return out.reshape(B, H, T, D)  # [B, H, nb, blk, D] → [B, H, T, D]


class SparseSelfAttention:
    """Drop-in for the reference module (reference
    sparse_self_attention.py:13): ``forward(q, k, v, rpe=None,
    key_padding_mask=None, attn_mask=None)`` over [B, H, T, Dh] tensors.
    LUTs are cached per sequence length.
    """

    def __init__(self, sparsity_config: Optional[SparsityConfig] = None,
                 key_padding_mask_mode: str = "add",
                 attn_mask_mode: str = "mul",
                 max_seq_length: int = 2048):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(
            num_heads=4)
        if key_padding_mask_mode not in ("add", "mul"):
            raise ValueError("key_padding_mask_mode must be 'add' or 'mul'")
        if attn_mask_mode not in ("add", "mul"):
            raise ValueError("attn_mask_mode must be 'add' or 'mul'")
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self.max_seq_length = max_seq_length
        self._lut_cache = {}

    def get_lut(self, seq_len: int):
        if seq_len not in self._lut_cache:
            layout = self.sparsity_config.make_layout(seq_len)
            self._lut_cache[seq_len] = build_lut(layout)
        return self._lut_cache[seq_len]

    def _get_kernel_luts(self, seq_len: int):
        """Per-seq-len cache of (layout, kernel LUTs) for the Pallas hot
        path — the layout build + LUT scans are O(H·nb²) Python work that
        must not run per forward call."""
        if not hasattr(self, "_kernel_lut_cache"):
            self._kernel_lut_cache = {}
        if seq_len not in self._kernel_lut_cache:
            from ..pallas.block_sparse_attention import build_kernel_luts
            layout = np.asarray(self.sparsity_config.make_layout(seq_len))
            self._kernel_lut_cache[seq_len] = (
                layout, build_kernel_luts(layout))
        return self._kernel_lut_cache[seq_len]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None,
                 attn_mask=None):
        B, H, T, D = query.shape
        if query.shape != key.shape or key.shape != value.shape:
            raise NotImplementedError(
                "only self-attention is supported (q/k/v same shape)")
        if H != self.sparsity_config.num_heads:
            raise ValueError(
                f"input has {H} heads but sparsity config was built for "
                f"{self.sparsity_config.num_heads}")
        block = self.sparsity_config.block
        if rpe is None and key_padding_mask is None and attn_mask is None \
                and T % block == 0:
            # hot path: the fused Pallas kernel (LUT-driven online-softmax
            # over active blocks only — the Triton sdd/softmax/dsd trio as
            # one kernel; see ops/pallas/block_sparse_attention.py).
            # rpe/mask features stay on the gathered-block XLA path below.
            from ..pallas.block_sparse_attention import (
                block_sparse_attention)
            layout, luts = self._get_kernel_luts(T)
            return block_sparse_attention(query, key, value, layout, block,
                                          luts=luts)
        cols, valid = self.get_lut(T)
        scale = float(D) ** -0.5
        return _sparse_attn(query, key, value, jnp.asarray(cols),
                            jnp.asarray(valid), rpe, key_padding_mask,
                            attn_mask, scale,
                            block=block,
                            kp_mode=self.key_padding_mask_mode,
                            am_mode=self.attn_mask_mode)

    forward = __call__
