"""Native-op build system (the L1 layer).

The reference JIT-builds CUDA extensions with ninja + torch.utils.cpp_ext
and version-match asserts (reference: op_builder/builder.py:146-216).  The
TPU build has exactly one native surface — host-side C++ ops (CPU Adam for
ZeRO-Offload) — compiled here with the system g++ into a shared library
bound via ctypes (no pybind11 in this image).  Pallas kernels need no
build step; they ship as Python.

Build artifacts are cached under ``deepspeed_tpu/ops/_build/`` keyed by a
source hash, so the compile happens once per source change (the analogue of
the reference's ninja dependency tracking).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from pathlib import Path
from typing import Optional

_REPO_ROOT = Path(__file__).resolve().parents[2]
_CSRC = _REPO_ROOT / "csrc"
_BUILD_DIR = Path(__file__).resolve().parent / "_build"

_compile_error: Optional[str] = None
_lib: Optional[ctypes.CDLL] = None


class OpBuilderError(RuntimeError):
    pass


def _source_hash(sources) -> str:
    h = hashlib.sha256()
    for s in sources:
        h.update(Path(s).read_bytes())
    return h.hexdigest()[:16]


def build_cpu_ops(verbose: bool = False) -> Path:
    """Compile every csrc/*.cpp → _build/libds_cpu_ops_<hash>.so (the glob
    keeps new sources and the cache hash in sync automatically)."""
    sources = sorted(_CSRC.glob("*.cpp"))
    if not sources:
        raise OpBuilderError(
            f"no native sources under {_CSRC} — wheel installs ship "
            "without csrc/; use a source checkout (or the sdist) for the "
            "native host ops")
    tag = _source_hash(sources)
    out = _BUILD_DIR / f"libds_cpu_ops_{tag}.so"
    if out.exists():
        return out
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)
    # compile to a process-unique temp path and rename into place: a
    # concurrent builder must never dlopen a half-written .so
    tmp = out.with_suffix(f".tmp{os.getpid()}")
    cmd = ["g++", "-O3", "-march=native", "-fopenmp", "-shared", "-fPIC",
           "-o", str(tmp)] + [str(s) for s in sources]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:  # no g++ etc.
        raise OpBuilderError(f"native build failed to launch: {e}") from e
    if proc.returncode != 0:
        raise OpBuilderError(
            f"native build failed:\n{' '.join(cmd)}\n{proc.stderr}")
    os.replace(tmp, out)
    if verbose:
        print(f"[deepspeed_tpu] built {out.name}")
    return out


def load_cpu_ops() -> ctypes.CDLL:
    """Build (if needed) and dlopen the host-ops library.  Raises
    OpBuilderError when the toolchain is unavailable — callers choose the
    numpy fallback explicitly (mirrors the reference's op-compatibility
    gating, op_builder/builder.py + env_report)."""
    global _lib, _compile_error
    if _lib is not None:
        return _lib
    if _compile_error is not None:
        raise OpBuilderError(_compile_error)
    try:
        path = build_cpu_ops()
        lib = ctypes.CDLL(str(path))
    except (OpBuilderError, OSError) as e:
        _compile_error = str(e)
        raise OpBuilderError(_compile_error) from None

    i64, f32 = ctypes.c_int64, ctypes.c_float
    fp = ctypes.POINTER(ctypes.c_float)
    u16p = ctypes.POINTER(ctypes.c_uint16)
    try:
        lib.ds_cpu_adam_step.argtypes = [
            i64, fp, fp, fp, fp, f32, f32, f32, f32, f32,
            ctypes.c_int, ctypes.c_int, i64, u16p, ctypes.c_int]
        lib.ds_cpu_adam_step.restype = None
        lib.ds_f32_to_bf16.argtypes = [i64, fp, u16p]
        lib.ds_f32_to_bf16.restype = None
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.ds_lut_width.argtypes = [i64, i64, i32p]
        lib.ds_lut_width.restype = i64
        lib.ds_build_lut.argtypes = [i64, i64, i32p, i64, i32p, u8p]
        lib.ds_build_lut.restype = None
        lib.ds_cpu_ops_version.restype = ctypes.c_int
    except AttributeError as e:
        # a partial csrc/ compiles but misses symbols — this must stay
        # LOUD everywhere (plain RuntimeError, deliberately NOT
        # OpBuilderError: callers treat that as "toolchain unavailable"
        # and would silently demote the whole offload tier to numpy)
        raise RuntimeError(
            f"native library {path.name} is incomplete: {e}; csrc/ is "
            "missing sources") from None
    _lib = lib
    return lib


def cpu_ops_loaded():
    """The already-loaded library, or None — never triggers a build.
    For callers whose fast paths are optional (the sparse LUT build) and
    must not pay a g++ compile on first use."""
    return _lib


def cpu_ops_available() -> bool:
    try:
        load_cpu_ops()
        return True
    except OpBuilderError:
        return False


def cpu_ops_status() -> str:
    """ds_report-style one-liner.  The diagnostic report must DESCRIBE a
    broken library (the incomplete-csrc RuntimeError), not die on it —
    only here; runtime callers still get the loud error."""
    try:
        if cpu_ops_available():
            return ("cpu_ops ... compatible "
                    f"(v{load_cpu_ops().ds_cpu_ops_version()})")
        return f"cpu_ops ... NOT compatible ({_compile_error})"
    except RuntimeError as e:
        return f"cpu_ops ... BROKEN ({e})"
