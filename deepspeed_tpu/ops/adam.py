"""Fused Adam/AdamW — optax-compatible, single-kernel-per-step on TPU.

Replaces the reference's multi-tensor-apply CUDA Adam
(reference: csrc/adam/multi_tensor_adam.cu:163, ops/adam/fused_adam.py:15).
On TPU the "fusion" is XLA's: the whole tree-mapped update compiles into a
few fused loops over HBM, so no hand-written kernel is needed — the value
preserved here is the exact update rule and the knob surface (adam_w_mode,
bias_correction, per-group lr) rather than kernel plumbing.
"""
from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import optax

ScalarOrSchedule = Union[float, Callable[[jnp.ndarray], jnp.ndarray]]


class FusedAdamState(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates
    nu: optax.Updates


def _lr_at(lr: ScalarOrSchedule, count):
    return lr(count) if callable(lr) else jnp.asarray(lr, jnp.float32)


def adam_moments(grads, mu, nu, b1: float, b2: float):
    """One EMA step of the first/second moments (shared by the device
    optimizer and the engine's XLA host-offload section so both paths use
    identical numerics)."""
    mu2 = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, mu, grads)
    nu2 = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * (g * g), nu, grads)
    return mu2, nu2


def adam_direction(mu, nu, c1, c2, eps: float):
    """Bias-corrected update direction m̂/(√v̂+eps); c1/c2 are the bias
    correction denominators (pass 1.0 to disable)."""
    def d(m, v):
        return (m / c1) / (jnp.sqrt(v / c2) + eps)
    return jax.tree.map(d, mu, nu)


def fused_adam(lr: ScalarOrSchedule = 1e-3,
               betas: Tuple[float, float] = (0.9, 0.999),
               eps: float = 1e-8,
               weight_decay: float = 0.0,
               adam_w_mode: bool = True,
               bias_correction: bool = True,
               weight_decay_mask: Optional[Callable] = None
               ) -> optax.GradientTransformation:
    """AdamW (``adam_w_mode=True``, decoupled decay) or classic Adam with L2
    folded into the gradient (``adam_w_mode=False``) — the same truth table
    as the reference wrapper (ops/adam/fused_adam.py:15-60 there).

    ``weight_decay_mask(params) -> bool pytree`` optionally exempts leaves
    (e.g. biases / LayerNorm scales) from decay.
    """
    b1, b2 = betas

    def init_fn(params):
        mu = jax.tree.map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        nu = jax.tree.map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        return FusedAdamState(count=jnp.zeros([], jnp.int32), mu=mu, nu=nu)

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adam requires params for weight decay")
        count = state.count + 1
        step_lr = _lr_at(lr, count)

        if weight_decay != 0.0 and not adam_w_mode:
            decay_mask = (weight_decay_mask(params) if weight_decay_mask
                          else jax.tree.map(lambda _: True, params))
            grads = jax.tree.map(
                lambda g, p, m: g + weight_decay * p if m else g,
                grads, params, decay_mask)

        mu, nu = adam_moments(grads, state.mu, state.nu, b1, b2)

        if bias_correction:
            c1 = 1 - b1 ** count.astype(jnp.float32)
            c2 = 1 - b2 ** count.astype(jnp.float32)
        else:
            c1 = c2 = jnp.asarray(1.0, jnp.float32)

        updates = adam_direction(mu, nu, c1, c2, eps)

        if weight_decay != 0.0 and adam_w_mode:
            decay_mask = (weight_decay_mask(params) if weight_decay_mask
                          else jax.tree.map(lambda _: True, params))
            updates = jax.tree.map(
                lambda u, p, m: u + weight_decay * p.astype(u.dtype) if m else u,
                updates, params, decay_mask)

        updates = jax.tree.map(lambda u: -step_lr * u, updates)
        return updates, FusedAdamState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


# reference-parity namespace: deepspeed.ops.adam exposes FusedAdam and
# DeepSpeedCPUAdam (ops/adam/__init__.py there).  Canonical aliases live
# HERE; ops/__init__.py re-exports them.
FusedAdam = fused_adam
from .cpu_adam import DeepSpeedCPUAdam  # noqa: E402,F401
