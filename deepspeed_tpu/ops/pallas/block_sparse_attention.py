"""Block-sparse attention as a Pallas TPU kernel (forward + backward).

The reference implements block-sparse attention as three Triton kernels —
sdd/dsd matmuls and a block-sparse softmax — driven by lookup tables built
natively (reference: deepspeed/ops/sparse_attention/matmul.py:16,
trsrc/matmul.tr:1, trsrc/softmax_fwd.tr:1, csrc/sparse_attention/
utils.cpp:14).  The TPU equivalent is ONE fused kernel per pass: for each
query-block row the grid walks that row's active key blocks via a
scalar-prefetched LUT (SMEM-resident, read inside the BlockSpec index maps
— the Pallas analogue of the Triton kernels' pointer tables), maintaining
an online-softmax accumulator in VMEM exactly like the flash kernel.
Scores never touch HBM; compute and HBM traffic are O(T · W · block)
where W is the row-max active-block count.

LUT padding repeats each row's LAST valid column instead of zero: padded
grid steps revisit the block already in VMEM, so Pallas elides the
HBM→VMEM copy and padding costs no bandwidth (same trick as the causal
clamp in flash_attention._fwd).

Backward follows flash-attention-2: probabilities are recomputed per
block from the saved log-sum-exp; dQ walks the row LUT, dK/dV walk the
TRANSPOSED LUT (for each key block, the query rows attending to it).

Granularity note: sparsity is block-granular (an active block attends
fully), matching the reference kernels — intra-block causal/padding
masking arrives via attn_mask/key_padding_mask, which the gather-einsum
path (sparse_self_attention.py) handles; SparseSelfAttention dispatches
there when masks/rpe are present.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _use_interpret() -> bool:
    from .runtime import use_interpret
    return use_interpret()


def build_kernel_luts(layout: np.ndarray
                      ) -> Tuple[np.ndarray, np.ndarray,
                                 np.ndarray, np.ndarray]:
    """Layout [H, nb, nb] → (cols, nvalid, rows_t, nvalid_t).

    ``cols[h, r]`` lists query-row r's active key blocks, padded by
    REPEATING the last valid entry (revisit ⇒ no refetch); ``nvalid[h, r]``
    is the true count.  ``rows_t``/``nvalid_t`` are the transposed LUT
    (per key block, the query rows attending to it) for the dK/dV pass.
    Rows/cols with no active blocks get one self-referential padding entry
    with nvalid 0.  Trace-time numpy, like the reference's native
    segment_blocks build (csrc/sparse_attention/utils.cpp:14).

    Head dedup: the LUTs ride in SMEM (scalar prefetch, ~1 MB on v5e), and
    at long seq a per-head LUT overflows it — e.g. bigbird seq 16k/block 64
    is 12x256x~170 int32 ≈ 2 MB, the exact AOT failure this guard exists
    for.  Every stock SparsityConfig is head-uniform unless
    ``different_layout_per_head`` is set, so identical head planes collapse
    to one and the kernels index plane ``h % lut_heads``.
    """
    if layout.shape[0] > 1 and bool((layout == layout[:1]).all()):
        layout = layout[:1]
    H, nb, _ = layout.shape
    W = max(int(layout.sum(-1).max()), 1)
    Wt = max(int(layout.sum(-2).max()), 1)
    cols = np.zeros((H, nb, W), np.int32)
    nvalid = np.zeros((H, nb), np.int32)
    rows_t = np.zeros((H, nb, Wt), np.int32)
    nvalid_t = np.zeros((H, nb), np.int32)
    for h in range(H):
        for r in range(nb):
            (active,) = np.nonzero(layout[h, r])
            n = len(active)
            nvalid[h, r] = n
            if n:
                cols[h, r, :n] = active
                cols[h, r, n:] = active[-1]
            else:
                cols[h, r, :] = r  # harmless self block, compute skipped
        for c in range(nb):
            (active,) = np.nonzero(layout[h, :, c])
            n = len(active)
            nvalid_t[h, c] = n
            if n:
                rows_t[h, c, :n] = active
                rows_t[h, c, n:] = active[-1]
            else:
                rows_t[h, c, :] = c
    return cols, nvalid, rows_t, nvalid_t


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(cols_ref, nvalid_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, sm_scale, heads, lut_heads,
                block, width):
    bh, iq, w = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    h = (bh % heads) if lut_heads > 1 else 0

    @pl.when(w == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(w < nvalid_ref[h, iq])
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(
            alpha * l_scr[:, 0:1] + jnp.sum(p, axis=1, keepdims=True),
            l_scr.shape)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(w == width - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        # rows with zero active blocks output zeros (acc is zeros), same
        # as the gather path's fully-masked-row guard
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = m_scr[:, 0] + jnp.log(l_safe[:, 0])
        lse = jnp.where(l[:, 0] == 0.0, NEG_INF, lse)
        lse_ref[0, 0] = jnp.broadcast_to(lse[None, :], (8, block))


def _sparse_fwd(q, k, v, cols, nvalid, *, sm_scale, heads, block,
                interpret):
    bh, t, d = q.shape
    nb = t // block
    width = cols.shape[-1]
    lut_h = cols.shape[0]

    def q_im(b, i, w, cols_ref, nv_ref):
        return (b, i, 0)

    def kv_im(b, i, w, cols_ref, nv_ref):
        h = (b % heads) if lut_h > 1 else 0
        return (b, cols_ref[h, i, w], 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nb, width),
        in_specs=[
            pl.BlockSpec((1, block, d), q_im),
            pl.BlockSpec((1, block, d), kv_im),
            pl.BlockSpec((1, block, d), kv_im),
        ],
        out_specs=[
            pl.BlockSpec((1, block, d), q_im),
            pl.BlockSpec((1, 1, 8, block),
                         lambda b, i, w, *_: (b, i, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, 128), jnp.float32),
            pltpu.VMEM((block, 128), jnp.float32),
            pltpu.VMEM((block, d), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, heads=heads,
                          lut_heads=lut_h, block=block, width=width),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, nb, 8, block), jnp.float32),
        ],
        interpret=interpret,
    )(cols, nvalid, q, k, v)
    return out, lse[:, :, 0, :].reshape(bh, t)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(cols_ref, nvalid_ref, q_ref, k_ref, v_ref, do_ref,
                   lse_ref, delta_ref, dq_ref, dq_scr,
                   *, sm_scale, heads, lut_heads, block, width):
    bh, iq, w = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    h = (bh % heads) if lut_heads > 1 else 0

    @pl.when(w == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(w < nvalid_ref[h, iq])
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = jnp.transpose(lse_ref[0, 0, 0:1, :])
        delta = jnp.transpose(delta_ref[0, 0, 0:1, :])
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(w == width - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(rows_ref, nvalid_ref, q_ref, k_ref, v_ref, do_ref,
                    lse_ref, delta_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                    *, sm_scale, heads, lut_heads, block, width):
    bh, ic, w = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    h = (bh % heads) if lut_heads > 1 else 0

    @pl.when(w == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(w < nvalid_ref[h, ic])
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = jnp.transpose(lse_ref[0, 0, 0:1, :])
        delta = jnp.transpose(delta_ref[0, 0, 0:1, :])
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        p = jnp.exp(s - lse)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(w == width - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _sparse_bwd(q, k, v, out, lse, do, cols, nvalid, rows_t, nvalid_t,
                *, sm_scale, heads, block, interpret):
    bh, t, d = q.shape
    nb = t // block
    width = cols.shape[-1]
    width_t = rows_t.shape[-1]
    lut_h = cols.shape[0]
    lut_ht = rows_t.shape[0]
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)

    def _rows(x):
        r = x.reshape(bh, nb, 1, block)
        return jnp.broadcast_to(r, (bh, nb, 8, block))

    lsep = _rows(lse)
    deltap = _rows(delta)

    def q_im(b, i, w, *_):
        return (b, i, 0)

    def kv_im(b, i, w, cols_ref, nv_ref):
        h = (b % heads) if lut_h > 1 else 0
        return (b, cols_ref[h, i, w], 0)

    def row_im(b, i, w, *_):
        return (b, i, 0, 0)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, heads=heads,
                          lut_heads=lut_h, block=block, width=width),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, nb, width),
            in_specs=[
                pl.BlockSpec((1, block, d), q_im),
                pl.BlockSpec((1, block, d), kv_im),
                pl.BlockSpec((1, block, d), kv_im),
                pl.BlockSpec((1, block, d), q_im),
                pl.BlockSpec((1, 1, 8, block), row_im),
                pl.BlockSpec((1, 1, 8, block), row_im),
            ],
            out_specs=pl.BlockSpec((1, block, d), q_im),
            scratch_shapes=[pltpu.VMEM((block, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        interpret=interpret,
    )(cols, nvalid, q, k, v, do, lsep, deltap)

    # dK/dV: walk the transposed LUT — q/do/lse/delta blocks come from the
    # query rows attending to key block ic
    def qrow_im(b, i, w, rows_ref, nv_ref):
        h = (b % heads) if lut_ht > 1 else 0
        return (b, rows_ref[h, i, w], 0)

    def qrow_stat_im(b, i, w, rows_ref, nv_ref):
        h = (b % heads) if lut_ht > 1 else 0
        return (b, rows_ref[h, i, w], 0, 0)

    def kvself_im(b, i, w, *_):
        return (b, i, 0)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, heads=heads,
                          lut_heads=lut_ht, block=block, width=width_t),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, nb, width_t),
            in_specs=[
                pl.BlockSpec((1, block, d), qrow_im),
                pl.BlockSpec((1, block, d), kvself_im),
                pl.BlockSpec((1, block, d), kvself_im),
                pl.BlockSpec((1, block, d), qrow_im),
                pl.BlockSpec((1, 1, 8, block), qrow_stat_im),
                pl.BlockSpec((1, 1, 8, block), qrow_stat_im),
            ],
            out_specs=[pl.BlockSpec((1, block, d), kvself_im),
                       pl.BlockSpec((1, block, d), kvself_im)],
            scratch_shapes=[pltpu.VMEM((block, d), jnp.float32),
                            pltpu.VMEM((block, d), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((bh, t, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, t, d), v.dtype)],
        interpret=interpret,
    )(rows_t, nvalid_t, q, k, v, do, lsep, deltap)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _sparse(q, k, v, cols, nvalid, rows_t, nvalid_t, sm_scale, heads,
            block, interpret):
    out, _ = _sparse_fwd(q, k, v, cols, nvalid, sm_scale=sm_scale,
                         heads=heads, block=block, interpret=interpret)
    return out


def _sparse_vjp_fwd(q, k, v, cols, nvalid, rows_t, nvalid_t, sm_scale,
                    heads, block, interpret):
    out, lse = _sparse_fwd(q, k, v, cols, nvalid, sm_scale=sm_scale,
                           heads=heads, block=block, interpret=interpret)
    return out, (q, k, v, out, lse, cols, nvalid, rows_t, nvalid_t)


def _sparse_vjp_bwd(sm_scale, heads, block, interpret, res, do):
    q, k, v, out, lse, cols, nvalid, rows_t, nvalid_t = res
    dq, dk, dv = _sparse_bwd(
        q, k, v, out, lse, do, cols, nvalid, rows_t, nvalid_t,
        sm_scale=sm_scale, heads=heads, block=block, interpret=interpret)
    return dq, dk, dv, None, None, None, None


_sparse.defvjp(_sparse_vjp_fwd, _sparse_vjp_bwd)


def block_sparse_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                           layout: np.ndarray, block: int,
                           sm_scale: Optional[float] = None,
                           interpret: Optional[bool] = None,
                           luts: Optional[Tuple] = None) -> jnp.ndarray:
    """Block-sparse attention over [B, H, T, Dh] with a [H, nb, nb] 0/1
    layout (differentiable).  T must be a multiple of ``block`` (use the
    reference's pad-to-block model surgery otherwise,
    sparse_attention_utils.py there).  ``luts`` optionally supplies
    prebuilt ``build_kernel_luts(layout)`` output (callers in a hot loop
    should cache it — SparseSelfAttention does)."""
    B, H, T, D = q.shape
    if T % block:
        raise ValueError(f"seq len {T} not a multiple of block {block}")
    nb = T // block
    if layout.shape != (H, nb, nb):
        raise ValueError(
            f"layout {layout.shape} != (H={H}, nb={nb}, nb={nb})")
    if sm_scale is None:
        sm_scale = float(D) ** -0.5
    if interpret is None:
        interpret = _use_interpret()
    if luts is None:
        luts = build_kernel_luts(np.asarray(layout))
    cols, nvalid, rows_t, nvalid_t = (jnp.asarray(a) for a in luts)
    # The LUTs are scalar-prefetched into SMEM (~1 MB/core on v5e); an
    # oversized LUT fails AOT compile with an opaque allocator error, so
    # reject it here with the actual remedies.  Reachable only with
    # different_layout_per_head at very long seq (head-uniform layouts
    # dedup to one plane in build_kernel_luts).
    smem_need = max(cols.nbytes + nvalid.nbytes,
                    rows_t.nbytes + nvalid_t.nbytes)
    if not interpret and smem_need > 900_000:
        raise ValueError(
            f"block-sparse LUT needs {smem_need} B of SMEM (~1 MB budget "
            f"per TPU core): layout [{layout.shape[0]} heads x {nb} x {nb} "
            f"blocks]. Use a larger sparsity block, a head-uniform layout "
            f"(different_layout_per_head=False), or flash attention.")
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    out = _sparse(qf, kf, vf, cols, nvalid, rows_t, nvalid_t,
                  sm_scale, H, block, interpret)
    return out.reshape(B, H, T, D)
