"""Single-query flash attention over a slot KV cache (the decode path).

The training kernel (flash_attention.py) masks with STATIC lengths; the
serving engine needs the opposite shape: one new query token per slot
against that slot's cached keys, with PER-SLOT live lengths that change
every tick and therefore must be TRACED — no static length may leak
into the program or the one-compiled-decode-program contract
(docs/serving.md, jaxlint JL005) is gone.

Layout: the cache is slot-major ``[S, H, T, Dh]`` and the kernel runs a
``(S·H, k_blocks)`` grid — each grid row streams one (slot, head)'s key
blocks through VMEM with the same online-softmax accumulator as the
training kernel.  The single query travels as an 8-row sublane
broadcast (TPU block shapes need (8, 128k) tiles — the lse trick from
the training kernel); the per-slot length travels the same way as a
broadcast int32 tile, indexed per grid row.  Keys at or beyond a slot's
live length are hard-masked with the validity floor, and a slot with
length 0 (a free slot riding along in the static batch) outputs exact
zeros — the mis-masking discipline the training kernel's kv_length arm
enforces, here with traced lengths.

Compute for blocks entirely beyond a slot's length is skipped
(``pl.when``), but their HBM->VMEM streaming is not: block index maps
are grid-index functions and cannot read traced lengths, so a short
slot still pays full-cache bandwidth.  The PAGED kernel below
(:func:`decode_attention_paged`) closes exactly that gap with the
scalar-prefetch grid of PagedAttention (PAPERS.md): the per-slot page
table rides as a ``PrefetchScalarGridSpec`` operand, block index maps
read it to gather the slot's pages per k-block, and a slot streams
only the pages it owns — the KV layout becomes ``[P, H, page_len, Dh]``
(a flat pool) instead of one ``max_seq_len`` stride per slot.

``impl='dense'`` is the interpretable reference fallback on both
entry points: the same masking semantics in plain jnp (the paged arm
gathers with ``jnp.take``), the differential-test oracle and the
serving engine's CPU path.

MULTI-QUERY arm (speculative decoding, docs/serving.md): the verify
half of draft-verify speculation scores ``W = k+1`` new tokens per
slot in ONE pass, so both entry points grow a ``*_multi`` twin taking
``W`` query rows and PER-QUERY live lengths ``[S, W]`` — query ``i``
(absolute position ``base + i``) attends every key below
``lengths[s, i]`` = ``base + i + 1``.  The kernels reuse the sublane
dimension the single query only broadcast into: up to 8 query rows
ride one tile (W padded up to a sublane multiple), each with its own
length mask, same grid, same streaming.  The dense multi reference is
DEFINED as W stacked single-query calls — fp32-bitwise against
sequential decode ticks by construction, the parity anchor the
widened program is verified against (tests/test_spec_decode.py).

FUSED-DEQUANT arms (``serving.quantization.kv='int8'``, docs/
serving.md "quantized serving"): both paged entry points accept the
int8 pool's per-row scale sidecars ``k_scale``/``v_scale``
[P, H, page_len].  Because the scale is per KEY ROW, dequant folds
into the score/prob columns — ``q·(k8·sk) == (q·k8)·sk`` and
``p·(v8·sv) == (p·sv)·v8`` — so the kernel streams int8 pages from
HBM (the bandwidth halving) and never materializes an fp page.  The
scale rows ride the same page-table indirection as the blocks they
scale; ``impl='dense'`` dequantizes the gathered view
(:func:`dequantize_paged`) — the interpretable definition of the
quantize→dequant semantics the fused arms are verified against
(tests/test_quant_serve.py).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF, _pad_seq


def _use_interpret() -> bool:
    from .runtime import use_interpret
    return use_interpret()


def decode_attention_reference(q, k, v, lengths, sm_scale=None):
    """Dense jnp reference: q [S, H, Dh] against k/v [S, H, T, Dh]
    masked to per-slot ``lengths`` [S] (int32).  Rows with length 0
    return exact zeros.  Deliberately mirrors ``ops.attention.
    causal_attention`` op for op (finfo.min mask fill, jax.nn.softmax,
    probs cast to q.dtype before the value matmul) so a dense-path
    decode step is fp32-BITWISE against the training forward — the
    parity bar of tests/test_inference.py."""
    S, H, T, Dh = k.shape
    scale = _default_scale(Dh) if sm_scale is None else sm_scale
    s = jnp.einsum("shd,shtd->sht", q, k,
                   preferred_element_type=jnp.float32) * scale
    valid = (jnp.arange(T, dtype=jnp.int32)[None, None, :]
             < lengths.astype(jnp.int32)[:, None, None])
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, jnp.float32)
    s = jnp.where(valid, s, neg)
    probs = jax.nn.softmax(s, axis=-1)
    # all-masked rows (free slots): softmax renormalizes over masked
    # keys — hard-zero them instead of silently attending
    probs = jnp.where(lengths[:, None, None] > 0, probs, 0.0)
    probs = probs.astype(q.dtype)
    return jnp.einsum("sht,shtd->shd", probs, v)


def _default_scale(d: int) -> float:
    """1/sqrt(d) computed in fp32 — the exact constant
    ``causal_attention`` uses, so dense decode vs training forward stays
    bitwise (the python-float ``d ** -0.5`` can differ by 1 ulp)."""
    import numpy as np
    return float(np.float32(1.0) / np.sqrt(np.float32(d)))


def _decode_kernel(q_ref, k_ref, v_ref, len_ref, o_ref,
                   m_scr, l_scr, acc_scr,
                   *, sm_scale: float, block_k: int):
    jk = pl.program_id(1)
    nk = pl.num_programs(1)
    length = len_ref[0][0, 0]                           # this row's slot

    @pl.when(jk == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # whole k block at or beyond the live length: nothing to do
    @pl.when(jk * block_k < length)
    def _compute():
        q = q_ref[0]                                    # [8, d] broadcast
        k = k_ref[0]                                    # [bk, d]
        v = v_ref[0]                                    # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # [8, bk]
        k_ids = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) \
            + jk * block_k
        s = jnp.where(k_ids < length, s, NEG_INF)
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # at least one key of this block is live (the pl.when guard), so
        # m_new is a real score and the masked keys' exp underflows to 0
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(
            alpha * l_scr[:, 0:1] + jnp.sum(p, axis=1, keepdims=True),
            l_scr.shape)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(jk == nk - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        # length 0 → no block ran → l == 0 → exact zeros (free slots)
        o_ref[0] = jnp.where(l == 0.0, 0.0,
                             acc_scr[:] / l_safe).astype(o_ref.dtype)


def _decode_pallas(q, k, v, lengths, *, sm_scale, block_k, interpret):
    S, H, T, Dh = k.shape
    block_k = min(block_k, max(T, 8))
    kf = _pad_seq(k.reshape(S * H, T, Dh), block_k, 1)
    vf = _pad_seq(v.reshape(S * H, T, Dh), block_k, 1)
    nk = kf.shape[1] // block_k
    # single query as an 8-row sublane broadcast (TPU tile rule)
    qf = jnp.broadcast_to(q.reshape(S * H, 1, Dh), (S * H, 8, Dh))
    # per-slot lengths as a broadcast (8, 128) int32 tile per slot —
    # the same sublane-broadcast trick as the training kernel's key
    # mask (_kmask_args); index map picks row g's slot with a static
    # division (grid-index arithmetic only)
    len_op = jnp.broadcast_to(
        lengths.astype(jnp.int32).reshape(S, 1, 1), (S, 8, 128))
    out = pl.pallas_call(
        functools.partial(_decode_kernel, sm_scale=sm_scale,
                          block_k=block_k),
        grid=(S * H, nk),
        in_specs=[
            pl.BlockSpec((1, 8, Dh), lambda g, j: (g, 0, 0)),
            pl.BlockSpec((1, block_k, Dh), lambda g, j: (g, j, 0)),
            pl.BlockSpec((1, block_k, Dh), lambda g, j: (g, j, 0)),
            pl.BlockSpec((1, 8, 128), lambda g, j: (g // H, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 8, Dh), lambda g, j: (g, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((S * H, 8, Dh), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((8, 128), jnp.float32),
            pltpu.VMEM((8, 128), jnp.float32),
            pltpu.VMEM((8, Dh), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, len_op)
    return out[:, 0, :].reshape(S, H, Dh)


def decode_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     lengths: jnp.ndarray,
                     sm_scale: Optional[float] = None,
                     block_k: int = 256,
                     impl: str = "pallas",
                     interpret: Optional[bool] = None) -> jnp.ndarray:
    """Single-query attention over a slot KV cache (not differentiable —
    the decode path never backprops).

    q: [S, H, Dh] — one new query token per slot.
    k, v: [S, H, T, Dh] — the slot cache; positions >= lengths[s] are
        garbage (evicted requests, uninitialized tail) and are
        hard-masked.
    lengths: [S] int32, TRACED — per-slot live KV length including the
        position this query's K/V was just written to.  0 = free slot →
        exact-zero output.

    ``impl``: 'pallas' (the kernel; interpret mode off-TPU) or 'dense'
    (the jnp reference — the serving engine's CPU fallback and the
    test oracle).
    """
    assert q.ndim == 3 and k.ndim == 4, (q.shape, k.shape)
    S, H, T, Dh = k.shape
    assert q.shape == (S, H, Dh), (q.shape, k.shape)
    if sm_scale is None:
        sm_scale = _default_scale(Dh)
    if impl == "dense":
        return decode_attention_reference(q, k, v, lengths,
                                          sm_scale=sm_scale)
    if impl != "pallas":
        raise ValueError(
            f"decode_attention impl={impl!r}: expected 'pallas' or "
            "'dense'")
    if interpret is None:
        interpret = _use_interpret()
    return _decode_pallas(q, k, v, lengths.astype(jnp.int32),
                          sm_scale=sm_scale, block_k=block_k,
                          interpret=interpret)


# ---------------------------------------------------------------------------
# paged decode attention: page-table indirection over a flat pool
# ---------------------------------------------------------------------------


def paged_gather(pool: jnp.ndarray,
                 page_table: jnp.ndarray) -> jnp.ndarray:
    """Materialize a slot-major dense view of the page pool:
    ``pool [P, H, page_len, Dh]`` gathered through
    ``page_table [S, max_pages]`` -> ``[S, H, max_pages*page_len, Dh]``.

    Position ``p`` of slot ``s`` is row ``p % page_len`` of page
    ``page_table[s, p // page_len]`` — the layout contract every paged
    consumer (kernel, reference, prefill) shares.  ``jnp.take`` keeps
    the page table traced, so this is recompilation-free."""
    g = jnp.take(pool, page_table, axis=0)  # [S, M, H, page_len, Dh]
    S, M, H, L, Dh = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(S, H, M * L, Dh)


def paged_gather_scales(scales: jnp.ndarray,
                        page_table: jnp.ndarray) -> jnp.ndarray:
    """The scale-sidecar twin of :func:`paged_gather`:
    ``scales [P, H, page_len]`` -> ``[S, H, max_pages*page_len]`` —
    row ``p`` of the gathered view carries the scale its int8 K/V row
    was quantized with."""
    g = jnp.take(scales, page_table, axis=0)  # [S, M, H, page_len]
    S, M, H, L = g.shape
    return g.transpose(0, 2, 1, 3).reshape(S, H, M * L)


def dequantize_paged(pool: jnp.ndarray, scales: jnp.ndarray,
                     page_table: jnp.ndarray) -> jnp.ndarray:
    """Gather + dequantize an int8 pool dense: the interpretable
    definition of what a quantized page MEANS (``stored value = int8 *
    its row scale``) — the semantics anchor the fused kernels are
    verified against (tests/test_quant_serve.py)."""
    from ...inference.quantize import dequantize_rows
    return dequantize_rows(paged_gather(pool, page_table),
                           paged_gather_scales(scales, page_table))


def _scale_tile(scales: jnp.ndarray) -> jnp.ndarray:
    """Scale sidecar ``[P, H, page_len]`` as a lane-packed VMEM
    operand ``[P, H, 8, 128]``: lane ``r`` of every sublane holds row
    ``r``'s scale (page_len <= 128 — enforced eagerly by the serving
    config, re-checked here for direct kernel users).  The same
    broadcast-tile idiom the kernels already use for traced lengths —
    the fused arms read one (1, 1, 8, 128) block per (page, head)
    through the scalar-prefetch page table, exactly like the int8 data
    block it scales.

    COST NOTE: this operand is rebuilt inside every compiled call (a
    pad + sublane broadcast over the whole pool, 2·P·H·4KiB per layer
    per tick) — transient bandwidth, not HBM capacity; the sidecar the
    cache STORES stays the compact ``[P, H, page_len]`` (storing the
    kernel layout would cost 8-128x the sidecar bytes and eat the
    capacity win this arm exists for).  The hardware refinement
    (docs/serving.md) is to pack the scale row into a spare lane of
    the int8 page so it streams with the data it scales."""
    Pp, Hh, pl = scales.shape
    if pl > 128:
        raise ValueError(
            f"quantized pages support page_len <= 128 (one scale lane "
            f"per row), got page_len={pl}")
    lanes = jnp.pad(scales.astype(jnp.float32),
                    ((0, 0), (0, 0), (0, 128 - pl)))
    return jnp.broadcast_to(lanes[:, :, None, :], (Pp, Hh, 8, 128))


def _decode_paged_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                         sm_scale: float, page_len: int, heads: int):
    # fused-dequant arm (int8 pages): two extra scale-tile refs ride
    # between the pool blocks and the output.  The python-level branch
    # keeps the fp arm's trace byte-identical to the pre-quant kernel.
    quant = len(rest) > 4
    if quant:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    jk = pl.program_id(1)
    nk = pl.num_programs(1)
    slot = pl.program_id(0) // heads
    length = len_ref[slot]

    @pl.when(jk == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # whole page at or beyond the live length: nothing to do (its table
    # entry points at the scratch page — valid storage, dead data)
    @pl.when(jk * page_len < length)
    def _compute():
        q = q_ref[0]                                    # [8, d] broadcast
        k = k_ref[0, 0]                                 # [page_len, d]
        v = v_ref[0, 0]                                 # [page_len, d]
        if quant:
            # dequant folds into the score/prob columns: the scale is
            # per KEY ROW, so q·(k8*sk) == (q·k8)*sk and p·(v8*sv) ==
            # (p*sv)·v8 — the int8 page never materializes in fp
            k = k.astype(jnp.float32)
            v = v.astype(jnp.float32)
            ks_row = ks_ref[0, 0][0:1, :page_len]       # [1, page_len]
            vs_row = vs_ref[0, 0][0:1, :page_len]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if quant:
            s = s * ks_row
        k_ids = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) \
            + jk * page_len
        s = jnp.where(k_ids < length, s, NEG_INF)
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(
            alpha * l_scr[:, 0:1] + jnp.sum(p, axis=1, keepdims=True),
            l_scr.shape)
        pv = (p * vs_row) if quant else p
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            pv.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(jk == nk - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = jnp.where(l == 0.0, 0.0,
                             acc_scr[:] / l_safe).astype(o_ref.dtype)


def _decode_paged_pallas(q, k_pages, v_pages, page_table, lengths, *,
                         sm_scale, interpret, k_scale=None, v_scale=None):
    P, H, page_len, Dh = k_pages.shape
    S, max_pages = page_table.shape
    quant = k_scale is not None
    qf = jnp.broadcast_to(q.reshape(S * H, 1, Dh), (S * H, 8, Dh))
    pt_flat = page_table.astype(jnp.int32).reshape(-1)

    def page_block(g, j, pt, ln, H=H, M=max_pages):
        # THE paged move: the block for grid cell (g, j) is whatever
        # page the slot's table names — a short slot streams only the
        # pages it owns (plus scratch no-ops)
        return (pt[(g // H) * M + j], g % H, 0, 0)

    in_specs = [
        pl.BlockSpec((1, 8, Dh), lambda g, j, pt, ln: (g, 0, 0)),
        pl.BlockSpec((1, 1, page_len, Dh), page_block),
        pl.BlockSpec((1, 1, page_len, Dh), page_block),
    ]
    operands = [qf, k_pages, v_pages]
    if quant:
        # the scale rows ride the SAME page-table indirection as the
        # int8 blocks they dequantize, as lane-packed (8, 128) tiles
        in_specs += [pl.BlockSpec((1, 1, 8, 128), page_block),
                     pl.BlockSpec((1, 1, 8, 128), page_block)]
        operands += [_scale_tile(k_scale), _scale_tile(v_scale)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S * H, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 8, Dh), lambda g, j, pt, ln: (g, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((8, 128), jnp.float32),
            pltpu.VMEM((8, 128), jnp.float32),
            pltpu.VMEM((8, Dh), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_paged_kernel, sm_scale=sm_scale,
                          page_len=page_len, heads=H),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S * H, 8, Dh),
                                       jnp.float32 if quant else q.dtype),
        interpret=interpret,
    )(pt_flat, lengths.astype(jnp.int32), *operands)
    return out[:, 0, :].reshape(S, H, Dh).astype(q.dtype)


def _check_quant_args(k_pages, k_scale, v_scale, what: str):
    """The fused-dequant contract both paged entry points share: the
    two scale sidecars come together and only over an int8 pool."""
    if (k_scale is None) != (v_scale is None):
        raise ValueError(
            f"{what}: k_scale and v_scale must be passed together "
            "(the fused-dequant arm scales both pools)")
    if k_scale is not None and k_pages.dtype != jnp.int8:
        raise ValueError(
            f"{what}: scale operands imply an int8 page pool, got "
            f"dtype {k_pages.dtype}")


def decode_attention_paged(q: jnp.ndarray, k_pages: jnp.ndarray,
                           v_pages: jnp.ndarray,
                           page_table: jnp.ndarray,
                           lengths: jnp.ndarray,
                           sm_scale: Optional[float] = None,
                           impl: str = "pallas",
                           interpret: Optional[bool] = None,
                           k_scale: Optional[jnp.ndarray] = None,
                           v_scale: Optional[jnp.ndarray] = None
                           ) -> jnp.ndarray:
    """Single-query attention over a PAGED KV pool (docs/serving.md).

    q: [S, H, Dh] — one new query token per slot.
    k_pages, v_pages: [P, H, page_len, Dh] — the flat page pool; a
        slot's position ``p`` lives at row ``p % page_len`` of page
        ``page_table[s, p // page_len]``.
    page_table: [S, max_pages] int32, TRACED — dead entries must hold a
        valid page id (the engine fills them with the scratch page 0);
        their data is masked, their streaming is a no-op read.
    lengths: [S] int32, TRACED — per-slot live KV length including the
        position this query's K/V was just written to.  0 = free slot
        -> exact-zero output.
    k_scale, v_scale: [P, H, page_len] fp32, TRACED — the quantized
        pool's per-row scale sidecars (serving.quantization.kv='int8';
        the pool is then int8 and dequant fuses into the kernel).
        None = the fp pool, byte-identical to the pre-quant programs.

    ``impl='dense'`` gathers the pool dense with ``jnp.take`` and runs
    :func:`decode_attention_reference` — values identical to the
    pre-page slot layout, the CPU-bitwise parity anchor; on the quant
    arm it dequantizes the gathered view first
    (:func:`dequantize_paged` — the semantics the fused kernel is
    verified against).  ``'pallas'`` is the scalar-prefetch kernel
    (interpret mode off-TPU)."""
    assert q.ndim == 3 and k_pages.ndim == 4, (q.shape, k_pages.shape)
    P, H, page_len, Dh = k_pages.shape
    S, max_pages = page_table.shape
    assert q.shape == (S, H, Dh), (q.shape, k_pages.shape)
    _check_quant_args(k_pages, k_scale, v_scale, "decode_attention_paged")
    if sm_scale is None:
        sm_scale = _default_scale(Dh)
    if impl == "dense":
        if k_scale is not None:
            kg = dequantize_paged(k_pages, k_scale, page_table)
            vg = dequantize_paged(v_pages, v_scale, page_table)
        else:
            kg = paged_gather(k_pages, page_table)
            vg = paged_gather(v_pages, page_table)
        return decode_attention_reference(q, kg, vg, lengths,
                                          sm_scale=sm_scale)
    if impl != "pallas":
        raise ValueError(
            f"decode_attention_paged impl={impl!r}: expected 'pallas' "
            "or 'dense'")
    if interpret is None:
        interpret = _use_interpret()
    return _decode_paged_pallas(q, k_pages, v_pages,
                                page_table.astype(jnp.int32),
                                lengths.astype(jnp.int32),
                                sm_scale=sm_scale, interpret=interpret,
                                k_scale=k_scale, v_scale=v_scale)


# ---------------------------------------------------------------------------
# multi-query decode attention: the speculative verify arm
# ---------------------------------------------------------------------------


def decode_attention_multi_reference(q, k, v, lengths, sm_scale=None):
    """W stacked single-query references: ``q [S, H, W, Dh]`` against
    ``k/v [S, H, T, Dh]`` with PER-QUERY lengths ``[S, W]`` — query
    ``i`` is exactly ``decode_attention_reference(q[:, :, i], ...,
    lengths[:, i])``, so a verify pass is fp32-BITWISE against the W
    sequential decode ticks it replaces (the parity anchor of
    tests/test_spec_decode.py).  W is small and static (k+1 <= 9), so
    the unrolled loop stays one trace."""
    W = q.shape[2]
    outs = [decode_attention_reference(q[:, :, i], k, v, lengths[:, i],
                                       sm_scale=sm_scale)
            for i in range(W)]
    return jnp.stack(outs, axis=2)                      # [S, H, W, Dh]


def _rows_pad(w: int) -> int:
    """Query rows padded to the TPU sublane multiple (min one tile)."""
    return max(8, -(-w // 8) * 8)


def _multi_len_op(lengths: jnp.ndarray, wp: int) -> jnp.ndarray:
    """Per-query lengths [S, W] as a broadcast [S, Wp, 128] int32 tile
    (padding rows get length 0 -> exact-zero outputs, sliced away)."""
    S, W = lengths.shape
    lens = jnp.zeros((S, wp), jnp.int32)
    lens = lens.at[:, :W].set(lengths.astype(jnp.int32))
    return jnp.broadcast_to(lens[:, :, None], (S, wp, 128))


def _pad_queries(q: jnp.ndarray, wp: int) -> jnp.ndarray:
    """[S, H, W, Dh] -> [S*H, Wp, Dh] with zero padding rows."""
    S, H, W, Dh = q.shape
    qf = q.reshape(S * H, W, Dh)
    if wp > W:
        qf = jnp.pad(qf, ((0, 0), (0, wp - W), (0, 0)))
    return qf


def _decode_multi_kernel(q_ref, len_ref, k_ref, v_ref, o_ref,
                         m_scr, l_scr, acc_scr,
                         *, sm_scale: float, block_k: int):
    jk = pl.program_id(1)
    nk = pl.num_programs(1)
    row_lens = len_ref[0][:, 0:1]                       # [Wp, 1]

    @pl.when(jk == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # the block computes when ANY row still has live keys in it; rows
    # whose own length ends earlier mask themselves below
    @pl.when(jk * block_k < jnp.max(row_lens))
    def _compute():
        q = q_ref[0]                                    # [Wp, d]
        k = k_ref[0]                                    # [bk, d]
        v = v_ref[0]                                    # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [Wp, bk]
        k_ids = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) \
            + jk * block_k
        mask = k_ids < row_lens
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # unlike the single-query kernel, a ROW can be fully masked in
        # a block another row keeps live: its m_new stays NEG_INF and
        # exp(NEG_INF - NEG_INF) would be 1, so p is masked explicitly
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(
            alpha * l_scr[:, 0:1] + jnp.sum(p, axis=1, keepdims=True),
            l_scr.shape)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(jk == nk - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        # length-0 rows (inactive slots, padding rows) -> exact zeros
        o_ref[0] = jnp.where(l == 0.0, 0.0,
                             acc_scr[:] / l_safe).astype(o_ref.dtype)


def _decode_multi_pallas(q, k, v, lengths, *, sm_scale, block_k,
                         interpret):
    S, H, T, Dh = k.shape
    W = q.shape[2]
    wp = _rows_pad(W)
    block_k = min(block_k, max(T, 8))
    kf = _pad_seq(k.reshape(S * H, T, Dh), block_k, 1)
    vf = _pad_seq(v.reshape(S * H, T, Dh), block_k, 1)
    nk = kf.shape[1] // block_k
    qf = _pad_queries(q, wp)
    len_op = _multi_len_op(lengths, wp)
    out = pl.pallas_call(
        functools.partial(_decode_multi_kernel, sm_scale=sm_scale,
                          block_k=block_k),
        grid=(S * H, nk),
        in_specs=[
            pl.BlockSpec((1, wp, Dh), lambda g, j: (g, 0, 0)),
            pl.BlockSpec((1, wp, 128), lambda g, j, H=H: (g // H, 0, 0)),
            pl.BlockSpec((1, block_k, Dh), lambda g, j: (g, j, 0)),
            pl.BlockSpec((1, block_k, Dh), lambda g, j: (g, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, wp, Dh), lambda g, j: (g, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((S * H, wp, Dh), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((wp, 128), jnp.float32),
            pltpu.VMEM((wp, 128), jnp.float32),
            pltpu.VMEM((wp, Dh), jnp.float32),
        ],
        interpret=interpret,
    )(qf, len_op, kf, vf)
    return out[:, :W, :].reshape(S, H, W, Dh)


def decode_attention_multi(q: jnp.ndarray, k: jnp.ndarray,
                           v: jnp.ndarray, lengths: jnp.ndarray,
                           sm_scale: Optional[float] = None,
                           block_k: int = 256,
                           impl: str = "pallas",
                           interpret: Optional[bool] = None
                           ) -> jnp.ndarray:
    """Multi-query attention over the slot KV cache — the speculative
    ``verify_step``'s widened decode (docs/serving.md).

    q: [S, H, W, Dh] — W new query tokens per slot (the pending token
        + its k draft proposals; W = k+1).
    k, v: [S, H, T, Dh] — the slot cache with ALL W new rows already
        written (write-then-attend, exactly the decode contract).
    lengths: [S, W] int32, TRACED — per-QUERY live length including the
        query's own position (row ``i`` of an active slot at base
        length L is ``L + i + 1``); 0 = masked row -> exact zeros.

    ``impl='dense'`` is W stacked single-query references (bitwise the
    sequential ticks being replaced); ``'pallas'`` packs the W rows
    into the sublane dimension of the single-query kernel's tiles."""
    assert q.ndim == 4 and k.ndim == 4, (q.shape, k.shape)
    S, H, T, Dh = k.shape
    W = q.shape[2]
    assert q.shape == (S, H, W, Dh), (q.shape, k.shape)
    assert lengths.shape == (S, W), (lengths.shape, q.shape)
    if sm_scale is None:
        sm_scale = _default_scale(Dh)
    if impl == "dense":
        return decode_attention_multi_reference(q, k, v, lengths,
                                                sm_scale=sm_scale)
    if impl != "pallas":
        raise ValueError(
            f"decode_attention_multi impl={impl!r}: expected 'pallas' "
            "or 'dense'")
    if interpret is None:
        interpret = _use_interpret()
    return _decode_multi_pallas(q, k, v, lengths.astype(jnp.int32),
                                sm_scale=sm_scale, block_k=block_k,
                                interpret=interpret)


def _decode_paged_multi_kernel(pt_ref, q_ref, len_ref, k_ref, v_ref,
                               *rest, sm_scale: float, page_len: int):
    # fused-dequant arm: see _decode_paged_kernel — same two scale-tile
    # refs, same python-level branch keeping the fp trace unchanged
    quant = len(rest) > 4
    if quant:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    jk = pl.program_id(1)
    nk = pl.num_programs(1)
    row_lens = len_ref[0][:, 0:1]                       # [Wp, 1]

    @pl.when(jk == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(jk * page_len < jnp.max(row_lens))
    def _compute():
        q = q_ref[0]                                    # [Wp, d]
        k = k_ref[0, 0]                                 # [page_len, d]
        v = v_ref[0, 0]
        if quant:
            k = k.astype(jnp.float32)
            v = v.astype(jnp.float32)
            ks_row = ks_ref[0, 0][0:1, :page_len]       # [1, page_len]
            vs_row = vs_ref[0, 0][0:1, :page_len]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if quant:
            s = s * ks_row
        k_ids = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) \
            + jk * page_len
        mask = k_ids < row_lens
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(
            alpha * l_scr[:, 0:1] + jnp.sum(p, axis=1, keepdims=True),
            l_scr.shape)
        pv = (p * vs_row) if quant else p
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            pv.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(jk == nk - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = jnp.where(l == 0.0, 0.0,
                             acc_scr[:] / l_safe).astype(o_ref.dtype)


def _decode_paged_multi_pallas(q, k_pages, v_pages, page_table, lengths,
                               *, sm_scale, interpret, k_scale=None,
                               v_scale=None):
    P, H, page_len, Dh = k_pages.shape
    S, max_pages = page_table.shape
    W = q.shape[2]
    wp = _rows_pad(W)
    quant = k_scale is not None
    qf = _pad_queries(q, wp)
    len_op = _multi_len_op(lengths, wp)
    pt_flat = page_table.astype(jnp.int32).reshape(-1)

    def page_block(g, j, pt, H=H, M=max_pages):
        return (pt[(g // H) * M + j], g % H, 0, 0)

    # only the page table needs scalar prefetch (it feeds the index
    # maps); the per-query lengths ride as an ordinary VMEM tile
    in_specs = [
        pl.BlockSpec((1, wp, Dh), lambda g, j, pt: (g, 0, 0)),
        pl.BlockSpec((1, wp, 128),
                     lambda g, j, pt, H=H: (g // H, 0, 0)),
        pl.BlockSpec((1, 1, page_len, Dh), page_block),
        pl.BlockSpec((1, 1, page_len, Dh), page_block),
    ]
    operands = [qf, len_op, k_pages, v_pages]
    if quant:
        in_specs += [pl.BlockSpec((1, 1, 8, 128), page_block),
                     pl.BlockSpec((1, 1, 8, 128), page_block)]
        operands += [_scale_tile(k_scale), _scale_tile(v_scale)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(S * H, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, wp, Dh), lambda g, j, pt: (g, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((wp, 128), jnp.float32),
            pltpu.VMEM((wp, 128), jnp.float32),
            pltpu.VMEM((wp, Dh), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_paged_multi_kernel, sm_scale=sm_scale,
                          page_len=page_len),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S * H, wp, Dh),
                                       jnp.float32 if quant else q.dtype),
        interpret=interpret,
    )(pt_flat, *operands)
    return out[:, :W, :].reshape(S, H, W, Dh).astype(q.dtype)


def decode_attention_paged_multi(q: jnp.ndarray, k_pages: jnp.ndarray,
                                 v_pages: jnp.ndarray,
                                 page_table: jnp.ndarray,
                                 lengths: jnp.ndarray,
                                 sm_scale: Optional[float] = None,
                                 impl: str = "pallas",
                                 interpret: Optional[bool] = None,
                                 k_scale: Optional[jnp.ndarray] = None,
                                 v_scale: Optional[jnp.ndarray] = None
                                 ) -> jnp.ndarray:
    """Multi-query attention over the PAGED KV pool — the paged twin of
    :func:`decode_attention_multi` (same per-query ``lengths [S, W]``
    contract) with the page pool/table layout of
    :func:`decode_attention_paged`, including its fused-dequant arm
    (``k_scale``/``v_scale`` [P, H, page_len] over an int8 pool).
    ``impl='dense'`` gathers the pool with ``jnp.take`` (dequantizing
    on the quant arm) then runs the stacked single-query reference —
    values identical to the unpaged multi arm on the same logical
    cache; ``'pallas'`` is the scalar-prefetch kernel with W query
    rows per tile (interpret mode off-TPU)."""
    assert q.ndim == 4 and k_pages.ndim == 4, (q.shape, k_pages.shape)
    P, H, page_len, Dh = k_pages.shape
    S, max_pages = page_table.shape
    W = q.shape[2]
    assert q.shape == (S, H, W, Dh), (q.shape, k_pages.shape)
    assert lengths.shape == (S, W), (lengths.shape, q.shape)
    _check_quant_args(k_pages, k_scale, v_scale,
                      "decode_attention_paged_multi")
    if sm_scale is None:
        sm_scale = _default_scale(Dh)
    if impl == "dense":
        if k_scale is not None:
            kg = dequantize_paged(k_pages, k_scale, page_table)
            vg = dequantize_paged(v_pages, v_scale, page_table)
        else:
            kg = paged_gather(k_pages, page_table)
            vg = paged_gather(v_pages, page_table)
        return decode_attention_multi_reference(q, kg, vg, lengths,
                                                sm_scale=sm_scale)
    if impl != "pallas":
        raise ValueError(
            f"decode_attention_paged_multi impl={impl!r}: expected "
            "'pallas' or 'dense'")
    if interpret is None:
        interpret = _use_interpret()
    return _decode_paged_multi_pallas(q, k_pages, v_pages,
                                      page_table.astype(jnp.int32),
                                      lengths.astype(jnp.int32),
                                      sm_scale=sm_scale,
                                      interpret=interpret,
                                      k_scale=k_scale, v_scale=v_scale)
