"""Pallas TPU kernels — the framework's replacement for the reference's
hand-written CUDA kernel layer (reference: csrc/).

Each kernel ships with a pure-jnp reference implementation and a
differential test, mirroring the reference's kernel-vs-HuggingFace test
strategy (reference: tests/unit/test_cuda_forward.py).
"""
from .flash_attention import flash_attention  # noqa: F401
from .decode_attention import (decode_attention,  # noqa: F401
                               decode_attention_reference)
