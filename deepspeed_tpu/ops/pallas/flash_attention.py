"""Flash attention as a Pallas TPU kernel (forward + backward).

This is the TPU-native replacement for the reference's fused attention
path inside the CUDA transformer layer (reference:
csrc/transformer/softmax_kernels.cu + strided-batch GEMMs composed in
csrc/transformer/ds_transformer_cuda.cpp:99-121, whose fused softmax is
capped at seq 1024 — ds_transformer_cuda.cpp:124).  The Pallas kernel has
no sequence cap: scores are never materialised in HBM; an online-softmax
accumulator streams over key blocks in VMEM, so memory is O(T·D) instead
of O(T²), and both matmuls per block hit the MXU.

Layout: grid = (batch·heads, q_blocks, k_blocks) with the k axis
innermost; VMEM scratch (running max `m`, normaliser `l`, output
accumulator) persists across the k iterations of one q block.  The
backward pass recomputes probabilities per block from the saved
log-sum-exp (classic flash-attention-2 style) in two kernels: one
accumulating dQ over k blocks, one accumulating dK/dV over q blocks.

Numerics: softmax statistics and all accumulators are fp32 regardless of
input dtype (matching the reference kernel's fp32 softmax accumulation
for fp16 inputs).

Attention-probability dropout runs INSIDE the kernel (the reference
fuses dropout into its CUDA attention the same way,
csrc/transformer/dropout_kernels.cu composed at
ds_transformer_cuda.cpp:99-121): the keep mask is a counter-based hash
of (batch·head, q position, k position, seed), so the backward kernels
regenerate bit-identical masks from the same coordinates instead of
storing an O(T²) mask — dropout costs no extra HBM.  The same hash,
evaluated in plain jnp over full index grids, is the differential-test
oracle (tests compare kernel fwd+grads against a dense reference using
the exact same mask).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# additive-mask drop value for boolean key masks: large enough that the
# dropped probability underflows to 0 after the lse subtraction, finite
# so masked-out score arithmetic never produces inf - inf = nan
NEG_MASK = -1e9
# a row whose running max never rose above this had NO genuinely valid
# key (real scores are O(|q||k|/sqrt(d)) — nowhere near -5e8): every key
# was dropped by the additive mask (<= NEG_MASK) or the validity floor
# (NEG_INF).  Such rows are HARD-ZEROED at finalize instead of silently
# renormalizing over masked keys (the mis-masking hazard: an all-masked
# key_mask row, or kv_length=0, previously attended to the max-scoring
# MASKED key / the mean of V).  Their lse is set to +DEAD_LSE so the
# backward kernels' p = exp(s - lse) underflows to exactly 0 — zero
# gradients, consistent with the zero output.
DEAD_ROW_THRESH = NEG_MASK * 0.5
DEAD_LSE = 1e30


def _use_interpret() -> bool:
    from .runtime import use_interpret
    return use_interpret()


def _pad_seq(x, block, axis):
    t = x.shape[axis]
    pad = (-t) % block
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)



def _fmix32(x):
    """murmur3 finalizer — a cheap, well-mixed u32→u32 bijection (not
    cryptographic; dropout only needs decorrelation)."""
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> jnp.uint32(13))
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> jnp.uint32(16))
    return x


def dropout_keep_mask(q_ids, k_ids, bh, seed, rate: float):
    """Counter-based keep mask: u32 hash of (bh, q position, k position,
    seed) compared against rate.  Pure jnp on index arrays, so the SAME
    function serves the forward kernel, both backward kernels (bit-equal
    regeneration — no stored mask), and the dense test oracle.  All of
    q_ids/k_ids/bh broadcast; returns bool of the broadcast shape."""
    x = (q_ids.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
         + k_ids.astype(jnp.uint32))
    x = x ^ (jnp.uint32(bh) * jnp.uint32(0x85EBCA6B))
    x = _fmix32(x ^ jnp.uint32(seed))
    # round() (not int() truncation) so the realized drop probability is
    # unbiased to the nearest 2^-32; rates within 2^-32 of 1.0 still
    # saturate at 2^32-1 (a keep probability of exactly 0 would need a
    # 33-bit threshold — irrelevant at practical dropout rates).
    thresh = jnp.uint32(min(round(rate * 2.0 ** 32), 2 ** 32 - 1))
    return x >= thresh


def dense_keep_mask(B, H, Tq, Tk, seed, rate: float, bh_ids=None):
    """Full-array keep mask [B, H, Tq, Tk] — the dense-layout evaluation
    of the kernel's hash (single source of the broadcast recipe, used by
    the model's dense fallback, Ulysses' dense debug path, and the test
    oracle).  ``bh_ids``: optional [B·H] global batch·head ids."""
    if bh_ids is None:
        bh_ids = jnp.arange(B * H, dtype=jnp.uint32)
    return dropout_keep_mask(
        jnp.arange(Tq, dtype=jnp.uint32)[None, None, :, None],
        jnp.arange(Tk, dtype=jnp.uint32)[None, None, None, :],
        jnp.asarray(bh_ids, jnp.uint32).reshape(B, H, 1, 1),
        seed, rate)


def _block_keep(iq, ik, b, seed, *, rate, block_q, block_k):
    """Keep mask for one (q-block, k-block) tile, from global positions."""
    q_ids = jax.lax.broadcasted_iota(jnp.uint32, (block_q, block_k), 0) \
        + jnp.uint32(iq * block_q)
    k_ids = jax.lax.broadcasted_iota(jnp.uint32, (block_q, block_k), 1) \
        + jnp.uint32(ik * block_k)
    return dropout_keep_mask(q_ids, k_ids, b, seed, rate)



def _grid_bh(bh_ref, period: int, stride: int):
    """Global batch-head id of this grid row:
    ``base + (g // period) * stride + (g % period)`` with g the bh grid
    index.  The affine form (one traced (1,1) scalar base + two STATIC
    ints) replaces a per-row id array operand: TPU lowering rejects
    sub-(8,128) blocked operands outright, and an SMEM array read
    indexed by program_id does not lower in interpret mode — while a
    (1,1) scalar operand works everywhere (same mechanics as the seed).
    Every caller's ids are affine: default contiguous arange(B*H) is
    (0, B*H, 0); Ulysses' global ids b*H + idx*Hn + j are
    (idx*Hn, Hn, H) — see parallel/sequence.py."""
    g = pl.program_id(0)
    return (bh_ref[0, 0] + jnp.uint32(g // period) * jnp.uint32(stride)
            + jnp.uint32(g % period))


def _masked_scores(q, k, iq, ik, *, sm_scale, causal, block_q, block_k,
                   seq_len, kmask=None):
    """Scaled q·kᵀ for one (q-block, k-block) tile with padding + causal
    masking — the single source of the mask math shared by the forward
    and both backward kernels (they must stay bit-identical or forward
    and backward silently disagree).  ``kmask``: optional [1, block_k]
    fp32 additive key mask (0 keep / large-negative drop — the HF
    convention), applied before the validity floor."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale      # [bq, bk]
    if kmask is not None:
        s = s + kmask
    k_ids = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    k_global = k_ids + ik * block_k
    valid = k_global < seq_len
    if causal:
        q_ids = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        valid = jnp.logical_and(valid, k_global <= q_ids + iq * block_q)
    return jnp.where(valid, s, NEG_INF)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, seed_ref, bh_ref, kmask_ref,
                o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, sm_scale: float, causal: bool, block_q: int,
                block_k: int, seq_len: int, dropout_rate: float,
                bh_period: int, bh_stride: int, use_kmask: bool):
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    # program_id must be read OUTSIDE pl.when branches: interpret-mode
    # lowering only rewrites it in the top-level kernel body (closures
    # capture the value fine) — same reason iq/ik live up here.
    bh_row = _grid_bh(bh_ref, bh_period, bh_stride)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Whole k block strictly above the causal diagonal → nothing to do.
    run = True
    if causal:
        run = (ik * block_k) <= (iq * block_q + block_q - 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0]                                   # [bq, d]
        k = k_ref[0]                                   # [bk, d]
        v = v_ref[0]                                   # [bk, d]
        # row 0 of the 8-row sublane-broadcast mask tile (see _kmask_args)
        km = kmask_ref[0][0:1, :] if use_kmask else None
        s = _masked_scores(q, k, iq, ik, sm_scale=sm_scale, causal=causal,
                           block_q=block_q, block_k=block_k,
                           seq_len=seq_len, kmask=km)

        m_prev = m_scr[:, 0:1]                          # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)       # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                          # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)                 # [bq, 1]
        # dropout scales probabilities AFTER normalisation; since the
        # final o = acc/l is linear in acc, masking p here (and keeping
        # the normaliser l on the UNdropped p) is exactly
        # dropout(softmax(s)) @ v
        l_new = alpha * l_scr[:, 0:1] + jnp.sum(p, axis=1, keepdims=True)
        pd = p
        if dropout_rate > 0.0:
            keep = _block_keep(iq, ik, bh_row, seed_ref[0, 0],
                               rate=dropout_rate, block_q=block_q,
                               block_k=block_k)
            pd = p * keep.astype(p.dtype) / (1.0 - dropout_rate)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            pd.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        # dead rows (every key masked — all-masked key_mask row, or all
        # keys beyond kv_length) hard-zero instead of renormalizing over
        # masked keys; their lse goes to +DEAD_LSE so backward p
        # underflows to 0 and the gradients are zero too
        dead = m_scr[:, 0:1] <= DEAD_ROW_THRESH         # [bq, 1]
        o_ref[0] = jnp.where(dead, 0.0,
                             acc_scr[:] / l_safe).astype(o_ref.dtype)
        # lse output is q-blocked with a sublane-padded layout
        # [bh, nq, 8, block_q]: every store is a whole (8, block_q) tile at
        # lane offset 0.  Mosaic rejects dynamic lane offsets that are not
        # provably 128-aligned (iq*block_q is not, for block_q < 128), and
        # TPU block shapes need their last two dims (sublane, lane) to be
        # (8k, 128k) or the full array dims — the 8-row broadcast buys both.
        lse = jnp.where(dead[:, 0], DEAD_LSE,
                        m_scr[:, 0] + jnp.log(l_safe[:, 0]))  # [bq]
        lse_ref[0, 0] = jnp.broadcast_to(lse[None, :], (8, block_q))


def _seed_arr(seed):
    """Seed as a (1, 1) uint32 operand (traced — a new step's seed does
    not recompile); every grid step maps to the same block."""
    return jnp.asarray(seed, jnp.uint32).reshape(1, 1)


# Scalar operands ((1,1) uint32 seed / bh base) live in SMEM as FULL
# arrays: the TPU lowering's (8,128)/equal-dims tile rule applies to any
# blocked spec, so per-row blocked id arrays are rejected on real TPUs
# even in SMEM (found on hardware, round 3 — interpret mode accepts
# them, which is why tests never caught it).  Batch-head ids therefore
# travel as ONE scalar base + static affine params (see _grid_bh).
_SEED_SPEC = pl.BlockSpec(memory_space=pltpu.SMEM)
_BH_SPEC = _SEED_SPEC


def _kmask_args(kmask, bh, tk_p, block_k, k_block_of):
    """(operand, spec) for the additive key-mask input.

    TPU blocked operands need their last two dims to satisfy the
    (8, 128)-tile rule, so a per-key mask row travels as an 8-row
    sublane broadcast [bh, 8, tk_p] with block (1, 8, block_k) — the
    same layout trick as the lse output (see _fwd_kernel._finalize).
    ``k_block_of(b, i, j)`` maps grid indices to the k-block index
    (shared with the K/V specs so causal revisit elision applies).
    When no mask is used a single zero tile with a constant index map is
    passed: it is fetched once and never refetched, and the kernel's
    static use_kmask flag skips the math entirely."""
    if kmask is None:
        op = jnp.zeros((1, 8, block_k), jnp.float32)
        spec = pl.BlockSpec((1, 8, block_k), lambda b, i, j: (0, 0, 0))
        return op, spec, False
    km = _pad_seq(kmask.astype(jnp.float32), block_k, 1)       # [bh, tk_p]
    op = jnp.broadcast_to(km[:, None, :], (bh, 8, km.shape[1]))
    spec = pl.BlockSpec(
        (1, 8, block_k), lambda b, i, j: (b, 0, k_block_of(b, i, j)))
    return op, spec, True


def _fwd(q, k, v, seed, bh_base, kmask, *, sm_scale, causal, block_q,
         block_k, dropout_rate, bh_period, bh_stride, interpret,
         kv_length=None):
    bh, t, d = q.shape
    tk = k.shape[1]
    # live-KV clamp: keys >= kv_length are hard-masked via the validity
    # floor (the KV-cache decode hazard — a cache tail past the live
    # length must never be attended); rows left with no valid key zero
    seq_len = tk if kv_length is None else int(kv_length)
    block_q = min(block_q, max(t, 8))
    block_k = min(block_k, max(tk, 8))
    qp = _pad_seq(q, block_q, 1)
    kp = _pad_seq(k, block_k, 1)
    vp = _pad_seq(v, block_k, 1)
    tq_p, tk_p = qp.shape[1], kp.shape[1]
    nq, nk = tq_p // block_q, tk_p // block_k

    if causal:
        def k_block_of(b, i, j):
            return jnp.minimum(j, (i * block_q + block_q - 1) // block_k)
    else:
        def k_block_of(b, i, j):
            return j
    kmask_op, kmask_spec, use_kmask = _kmask_args(
        kmask, bh, tk_p, block_k, k_block_of)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_len=seq_len,
        dropout_rate=dropout_rate, bh_period=bh_period,
        bh_stride=bh_stride, use_kmask=use_kmask)
    # clamp the K/V block index at the causal diagonal: skipped
    # (fully-masked) grid steps revisit the previous block, and Pallas
    # elides the HBM→VMEM copy for revisited blocks — without this the
    # pipeline streams every K/V block even though pl.when skips the
    # compute (≈2× attention HBM traffic at long T)
    def kv_im(b, i, j):
        return (b, k_block_of(b, i, j), 0)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_im),
            pl.BlockSpec((1, block_k, d), kv_im),
            _SEED_SPEC,
            _BH_SPEC,
            kmask_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, 8, block_q), lambda b, i, j: (b, i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq_p, d), q.dtype),
            jax.ShapeDtypeStruct((bh, nq, 8, block_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp, _seed_arr(seed), _seed_arr(bh_base), kmask_op)
    return out[:, :t], lse[:, :, 0, :].reshape(bh, tq_p)[:, :t]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   seed_ref, bh_ref, kmask_ref, dq_ref, dq_scr,
                   *, sm_scale, causal, block_q, block_k, seq_len,
                   dropout_rate, bh_period, bh_stride, use_kmask):
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    bh_row = _grid_bh(bh_ref, bh_period, bh_stride)  # see _fwd_kernel

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = True
    if causal:
        run = (ik * block_k) <= (iq * block_q + block_q - 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = jnp.transpose(lse_ref[0, 0, 0:1, :])      # [bq, 1]
        delta = jnp.transpose(delta_ref[0, 0, 0:1, :])  # [bq, 1]

        km = kmask_ref[0][0:1, :] if use_kmask else None
        s = _masked_scores(q, k, iq, ik, sm_scale=sm_scale, causal=causal,
                           block_q=block_q, block_k=block_k,
                           seq_len=seq_len, kmask=km)
        p = jnp.exp(s - lse)                            # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # [bq, bk]
        if dropout_rate > 0.0:
            # dS = P ∘ (mask/(1-r) ∘ (dO·Vᵀ) − Δ); Δ = rowsum(dO ∘ O)
            # already absorbs the dropped terms (O was built from the
            # dropped probabilities)
            keep = _block_keep(iq, ik, bh_row, seed_ref[0, 0],
                               rate=dropout_rate, block_q=block_q,
                               block_k=block_k)
            dp = dp * keep.astype(dp.dtype) / (1.0 - dropout_rate)
        ds = p * (dp - delta) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    seed_ref, bh_ref, kmask_ref, dk_ref, dv_ref,
                    dk_scr, dv_scr,
                    *, sm_scale, causal, block_q, block_k, seq_len,
                    dropout_rate, bh_period, bh_stride, use_kmask):
    ik, iq = pl.program_id(1), pl.program_id(2)
    bh_row = _grid_bh(bh_ref, bh_period, bh_stride)  # see _fwd_kernel
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True
    if causal:
        run = (ik * block_k) <= (iq * block_q + block_q - 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = jnp.transpose(lse_ref[0, 0, 0:1, :])      # [bq, 1]
        delta = jnp.transpose(delta_ref[0, 0, 0:1, :])  # [bq, 1]

        km = kmask_ref[0][0:1, :] if use_kmask else None
        s = _masked_scores(q, k, iq, ik, sm_scale=sm_scale, causal=causal,
                           block_q=block_q, block_k=block_k,
                           seq_len=seq_len, kmask=km)
        p = jnp.exp(s - lse)                            # [bq, bk]
        pd = p
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _block_keep(iq, ik, bh_row, seed_ref[0, 0],
                               rate=dropout_rate, block_q=block_q,
                               block_k=block_k)
            scale = keep.astype(p.dtype) / (1.0 - dropout_rate)
            pd = p * scale      # dropped probabilities (forward's P̃)
            dp = dp * scale
        # dV += P̃ᵀ · dO
        dv_scr[:] += jax.lax.dot_general(
            pd.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale                # [bq, bk]
        # dK += dSᵀ · Q
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(q, k, v, out, lse, do, seed, bh_base, kmask, *, sm_scale,
         causal, block_q, block_k, dropout_rate, bh_period, bh_stride,
         interpret, kv_length=None):
    bh, t, d = q.shape
    tk = k.shape[1]
    seq_len = tk if kv_length is None else int(kv_length)  # see _fwd
    block_q = min(block_q, max(t, 8))
    block_k = min(block_k, max(tk, 8))
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                            # [bh, t]

    qp = _pad_seq(q, block_q, 1)
    dop = _pad_seq(do, block_q, 1)
    kp = _pad_seq(k, block_k, 1)
    vp = _pad_seq(v, block_k, 1)
    tq_p, tk_p = qp.shape[1], kp.shape[1]
    nq, nk = tq_p // block_q, tk_p // block_k
    # q-blocked, sublane-padded row statistics ([bh, nq, 8, block_q]):
    # all kernel accesses are whole tiles at lane offset 0 (no dynamic
    # lane slicing, valid TPU block shape — see _fwd_kernel._finalize)
    def _rows(x):
        r = _pad_seq(x, block_q, 1).reshape(bh, nq, 1, block_q)
        return jnp.broadcast_to(r, (bh, nq, 8, block_q))

    lsep = _rows(lse)
    deltap = _rows(delta)

    q_spec_i = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    if causal:  # same revisit trick as the forward (see _fwd)
        def k_block_dq(b, i, j):
            return jnp.minimum(j, (i * block_q + block_q - 1) // block_k)
    else:
        def k_block_dq(b, i, j):
            return j

    def kv_im_j(b, i, j):
        return (b, k_block_dq(b, i, j), 0)
    kv_spec_j = pl.BlockSpec((1, block_k, d), kv_im_j)
    row_spec = pl.BlockSpec((1, 1, 8, block_q),
                            lambda b, i, j: (b, i, 0, 0))
    kmask_op, kmask_spec_dq, use_kmask = _kmask_args(
        kmask, bh, tk_p, block_k, k_block_dq)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=seq_len,
                          dropout_rate=dropout_rate,
                          bh_period=bh_period, bh_stride=bh_stride,
                          use_kmask=use_kmask),
        grid=(bh, nq, nk),
        in_specs=[q_spec_i, kv_spec_j, kv_spec_j, q_spec_i, row_spec,
                  row_spec, _SEED_SPEC, _BH_SPEC, kmask_spec_dq],
        out_specs=q_spec_i,
        out_shape=jax.ShapeDtypeStruct((bh, tq_p, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap, _seed_arr(seed), _seed_arr(bh_base),
      kmask_op)

    # dK/dV: k blocks outer, q blocks inner.
    if causal:
        # the first useful q block for k block i starts at the diagonal:
        # clamp below it so masked steps revisit (no fetch)
        def q_im_j(b, i, j):
            return (b, jnp.maximum(j, (i * block_k) // block_q), 0)

        def row_im_j(b, i, j):
            return (b, jnp.maximum(j, (i * block_k) // block_q), 0, 0)
    else:
        def q_im_j(b, i, j):
            return (b, j, 0)

        def row_im_j(b, i, j):
            return (b, j, 0, 0)
    q_spec_j = pl.BlockSpec((1, block_q, d), q_im_j)
    kv_spec_i = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0))
    row_spec_j = pl.BlockSpec((1, 1, 8, block_q), row_im_j)
    # k blocks ride the SECOND grid axis here (i), not the third
    _, kmask_spec_i, _ = _kmask_args(
        kmask, bh, tk_p, block_k, lambda b, i, j: i)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=seq_len,
                          dropout_rate=dropout_rate,
                          bh_period=bh_period, bh_stride=bh_stride,
                          use_kmask=use_kmask),
        grid=(bh, nk, nq),
        in_specs=[q_spec_j, kv_spec_i, kv_spec_i, q_spec_j, row_spec_j,
                  row_spec_j, _SEED_SPEC, _BH_SPEC, kmask_spec_i],
        out_specs=[kv_spec_i, kv_spec_i],
        out_shape=[jax.ShapeDtypeStruct((bh, tk_p, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, tk_p, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap, _seed_arr(seed), _seed_arr(bh_base),
      kmask_op)
    return dq[:, :t], dk[:, :tk], dv[:, :tk]


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(6, 7, 8, 9, 10, 11, 12, 13, 14))
def _flash(q, k, v, seed, bh_base, kmask, sm_scale, causal, block_q,
           block_k, dropout_rate, bh_period, bh_stride, interpret,
           kv_length):
    out, _ = _fwd(q, k, v, seed, bh_base, kmask, sm_scale=sm_scale,
                  causal=causal, block_q=block_q, block_k=block_k,
                  dropout_rate=dropout_rate, bh_period=bh_period,
                  bh_stride=bh_stride, interpret=interpret,
                  kv_length=kv_length)
    return out


def _flash_fwd(q, k, v, seed, bh_base, kmask, sm_scale, causal, block_q,
               block_k, dropout_rate, bh_period, bh_stride, interpret,
               kv_length):
    out, lse = _fwd(q, k, v, seed, bh_base, kmask, sm_scale=sm_scale,
                    causal=causal, block_q=block_q, block_k=block_k,
                    dropout_rate=dropout_rate, bh_period=bh_period,
                    bh_stride=bh_stride, interpret=interpret,
                    kv_length=kv_length)
    return out, (q, k, v, seed, bh_base, kmask, out, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, dropout_rate,
               bh_period, bh_stride, interpret, kv_length, res, do):
    q, k, v, seed, bh_base, kmask, out, lse = res
    dq, dk, dv = _bwd(q, k, v, out, lse, do, seed, bh_base, kmask,
                      sm_scale=sm_scale, causal=causal, block_q=block_q,
                      block_k=block_k, dropout_rate=dropout_rate,
                      bh_period=bh_period, bh_stride=bh_stride,
                      interpret=interpret, kv_length=kv_length)
    # integer-dtype primals (seed, bh base) take float0 cotangents
    dseed = np.zeros(np.shape(seed), jax.dtypes.float0)
    dbh = np.zeros(np.shape(bh_base), jax.dtypes.float0)
    # the key mask is a constant (0 / -1e9) in every caller; its true
    # gradient is never consumed, so it is treated as non-differentiable
    dkmask = None if kmask is None else jnp.zeros_like(kmask)
    return dq, dk, dv, dseed, dbh, dkmask


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = 512,
                    block_k: int = 512,
                    dropout_rate: float = 0.0,
                    dropout_rng=None,
                    dropout_seed=None,
                    bh_affine=None,
                    key_mask=None,
                    kv_length: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Flash attention over [B, H, T, Dh] inputs (differentiable).

    Attention-probability dropout runs inside the kernel when
    ``dropout_rate > 0``: the keep mask is hashed from positions + a
    seed (``dropout_seed`` uint32 scalar, or derived from
    ``dropout_rng``), regenerated bit-identically in the backward
    kernels.  ``bh_affine`` = (base, period, stride) overrides the
    batch·head ids the hash sees: row g of the flattened [B·H] grid maps
    to ``base + (g // period) * stride + g % period`` (base may be a
    traced uint32 scalar; period/stride are static ints).  Sharded
    callers (Ulysses) pass their GLOBAL head mapping so the realization
    matches the unsharded layout — see _grid_bh.

    ``key_mask``: optional per-key mask for padding (the BERT/HF case —
    the reference's fused softmax applies the same additive mask,
    csrc/transformer/softmax_kernels.cu).  Shape [B, Tk] (broadcast over
    heads) or [B·H, Tk]; boolean (True = attend) or additive float (0
    keep / large-negative drop).  Applied identically in forward and
    both backward kernels; the mask rides as an 8-row sublane-broadcast
    operand so the TPU tile rules accept it (see _kmask_args).

    ``kv_length``: static live length of the key/value tensors.  Keys at
    positions >= kv_length are HARD-masked (validity floor) in forward
    and both backward kernels — a KV buffer whose tail holds garbage
    (the KV-cache decode case) is never silently attended.  Out-of-range
    values raise.  Rows left with no valid key at all (kv_length=0, or a
    key_mask dropping every key of a row) output exact zeros with zero
    gradients instead of renormalizing over masked keys.  For PER-ROW
    traced lengths use ``ops.pallas.decode_attention`` (the single-query
    serving kernel).
    """
    assert q.ndim == 4, f"expected [B, H, T, D], got {q.shape}"
    b, h, t, d = q.shape
    tk = k.shape[2]
    # The causal mask is top-left-anchored (k_pos <= q_pos); with t != tk
    # that silently mis-masks (e.g. a KV-cache decode step would attend to
    # key 0 only).  Cross-length callers must use causal=False (and bound
    # the live keys with kv_length when the KV tail is not real data).
    assert not causal or t == tk, (
        f"causal flash attention requires equal q/k lengths, got {t} vs "
        f"{tk}; pass causal=False for cross-attention")
    if kv_length is not None:
        kv_length = int(kv_length)
        if not 0 <= kv_length <= tk:
            raise ValueError(
                f"kv_length={kv_length} is out of range for key length "
                f"{tk}: the mask would silently cover the wrong keys "
                f"(want 0 <= kv_length <= {tk})")
    if sm_scale is None:
        sm_scale = float(d) ** -0.5
    if interpret is None:
        interpret = _use_interpret()
    dropout_rate = float(dropout_rate)
    assert 0.0 <= dropout_rate < 1.0, f"bad dropout_rate {dropout_rate}"
    if dropout_rate > 0.0:
        if dropout_seed is not None:
            seed = jnp.asarray(dropout_seed, jnp.uint32)
        else:
            assert dropout_rng is not None, \
                "dropout_rate > 0 requires dropout_rng or dropout_seed"
            seed = jax.random.bits(dropout_rng, (), jnp.uint32)
    else:
        seed = jnp.zeros((), jnp.uint32)
    if bh_affine is None:
        bh_affine = (0, b * h, 0)
    bh_base, bh_period, bh_stride = bh_affine
    kmask = None
    if key_mask is not None:
        km = jnp.asarray(key_mask)
        if km.dtype == jnp.bool_:
            km = jnp.where(km, 0.0, NEG_MASK).astype(jnp.float32)
        else:
            km = km.astype(jnp.float32)
        if km.shape == (b, tk):
            km = jnp.broadcast_to(km[:, None, :], (b, h, tk))
        elif km.shape != (b * h, tk):
            raise ValueError(
                f"key_mask shape {km.shape} must be [B, Tk]={b, tk} or "
                f"[B*H, Tk]={b * h, tk}")
        kmask = km.reshape(b * h, tk)
    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, tk, d)
    vf = v.reshape(b * h, tk, d)
    out = _flash(qf, kf, vf, seed, jnp.asarray(bh_base, jnp.uint32),
                 kmask, sm_scale, causal, block_q, block_k,
                 dropout_rate, int(bh_period), int(bh_stride), interpret,
                 kv_length)
    return out.reshape(b, h, t, d)


def mha(q, k, v, dropout_rate: float = 0.0, dropout_rng=None,
        causal: bool = True, **kwargs):
    """Attention dispatcher (kept for callers of the old dense-fallback
    API): dropout now runs inside the flash kernel."""
    return flash_attention(q, k, v, causal=causal,
                           dropout_rate=dropout_rate,
                           dropout_rng=dropout_rng, **kwargs)
