"""Pallas execution-mode plumbing.

Pallas kernels must run in interpret mode off-TPU (CPU test meshes, the
driver's virtual-device dryrun).  ``jax.default_backend()`` is not a
reliable signal on this image — the TPU platform stays registered as
default even when the computation is placed on CPU devices — so each
engine declares the execution platform of *its* mesh around the calls
that trace its compiled steps (runtime/engine.py), and kernels consult
it at trace time.  A scoped setting (not a set-once global) keeps
several engines with different meshes in one process honest.
"""
from __future__ import annotations

import contextlib
from contextvars import ContextVar
from typing import Optional

import jax

_interpret_override: ContextVar[Optional[bool]] = ContextVar(
    "pallas_interpret", default=None)


@contextlib.contextmanager
def interpret_scope(value: Optional[bool]):
    """Force interpret mode (or None = auto) within the scope."""
    token = _interpret_override.set(value)
    try:
        yield
    finally:
        _interpret_override.reset(token)


def mesh_wants_interpret(mesh) -> bool:
    """True when the mesh's devices are not real TPU chips."""
    return mesh.devices.flat[0].platform != "tpu"


def use_interpret() -> bool:
    override = _interpret_override.get()
    if override is not None:
        return override
    return jax.default_backend() != "tpu"
