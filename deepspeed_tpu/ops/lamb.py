"""Fused LAMB — layerwise adaptive rates with trust-ratio clamping.

Replaces the reference's CUDA LAMB kernel
(reference: csrc/lamb/fused_lamb_cuda_kernel.cu — in-kernel L2 norm
reductions + trust-ratio clamp; Python wrapper ops/lamb/fused_lamb.py).
The per-tensor weight/update norms the CUDA kernel computes with
cooperative-group reductions are plain ``jnp.linalg.norm`` calls here; XLA
fuses them into the update loop.  ``max_coeff``/``min_coeff`` keep the
reference's clamp semantics.
"""
from __future__ import annotations

from typing import Callable, NamedTuple, Tuple, Union

import jax
import jax.numpy as jnp
import optax

ScalarOrSchedule = Union[float, Callable[[jnp.ndarray], jnp.ndarray]]


class FusedLambState(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates
    nu: optax.Updates


def fused_lamb(lr: ScalarOrSchedule = 1e-3,
               betas: Tuple[float, float] = (0.9, 0.999),
               eps: float = 1e-8,
               weight_decay: float = 0.0,
               max_coeff: float = 10.0,
               min_coeff: float = 0.01,
               bias_correction: bool = True) -> optax.GradientTransformation:
    b1, b2 = betas

    def init_fn(params):
        mu = jax.tree.map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        nu = jax.tree.map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        return FusedLambState(count=jnp.zeros([], jnp.int32), mu=mu, nu=nu)

    def update_fn(grads, state, params=None):
        if params is None:
            raise ValueError("fused_lamb requires params")
        count = state.count + 1
        step_lr = lr(count) if callable(lr) else jnp.asarray(lr, jnp.float32)

        mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, state.mu, grads)
        nu = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * (g * g),
                          state.nu, grads)
        if bias_correction:
            c1 = 1 - b1 ** count.astype(jnp.float32)
            c2 = 1 - b2 ** count.astype(jnp.float32)
        else:
            c1 = c2 = jnp.asarray(1.0, jnp.float32)

        def lamb_update(m, v, p):
            p32 = p.astype(jnp.float32)
            r = (m / c1) / (jnp.sqrt(v / c2) + eps)
            if weight_decay != 0.0:
                r = r + weight_decay * p32
            w_norm = jnp.linalg.norm(p32.reshape(-1))
            r_norm = jnp.linalg.norm(r.reshape(-1))
            trust = jnp.where(
                (w_norm > 0) & (r_norm > 0),
                jnp.clip(w_norm / r_norm, min_coeff, max_coeff),
                jnp.asarray(1.0, jnp.float32))
            return -step_lr * trust * r

        updates = jax.tree.map(lamb_update, mu, nu, params)
        return updates, FusedLambState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


# reference-parity namespace alias (deepspeed.ops.lamb.FusedLamb there)
FusedLamb = fused_lamb
