"""Attention ops — the XLA-path implementation.

This module is the reference ("dense") path; the Pallas flash-attention
kernel (ops/pallas/flash_attention.py) replaces it on TPU for long
sequences, and the block-sparse path (ops/sparse_attention/) covers the
reference's sparse-attention feature slot (reference:
deepspeed/ops/sparse_attention/sparse_self_attention.py:83-142).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def causal_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     dropout_rate: float = 0.0,
                     dropout_rng: Optional[jax.Array] = None,
                     mask: Optional[jnp.ndarray] = None,
                     sm_scale: Optional[float] = None,
                     dropout_keep: Optional[jnp.ndarray] = None) -> \
        jnp.ndarray:
    """Multi-head causal attention.

    q, k, v: [B, H, T, Dh].  Softmax accumulates in fp32 (matching the
    reference kernel's fp32 softmax accumulation for fp16 inputs,
    csrc/transformer/softmax_kernels.cu) and returns q.dtype.

    ``dropout_keep`` (a precomputed boolean keep mask, e.g. the flash
    kernel's position hash) takes precedence over ``dropout_rng``'s
    bernoulli draw — callers use it to keep dropout realizations
    identical across the dense/flash/sequence-parallel layouts.
    """
    B, H, T, Dh = q.shape
    scale = (jnp.asarray(sm_scale, jnp.float32) if sm_scale is not None
             else 1.0 / jnp.sqrt(Dh).astype(jnp.float32))
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    causal = jnp.tril(jnp.ones((T, T), bool))
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, jnp.float32)
    scores = jnp.where(causal[None, None], scores, neg)
    if mask is not None:
        scores = jnp.where(mask, scores, neg)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_rate > 0.0 and dropout_keep is not None:
        probs = jnp.where(dropout_keep, probs / (1.0 - dropout_rate), 0.0)
    elif dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    probs = probs.astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)
