"""DeepSpeed transformer layer — the fused BERT encoder block, TPU-native.

The reference implements this as ONE CUDA autograd function composing
cuBLAS GEMMs with hand-fused bias/GELU/dropout/LayerNorm/softmax kernels
and a 17-tensor save-list for backward (reference:
deepspeed/ops/transformer/transformer.py:150-418,
csrc/transformer/ds_transformer_cuda.cpp).  On TPU the fusion is XLA's:
the whole block compiles into MXU GEMMs with the elementwise chains fused
into them, so the value preserved here is

  - the exact math (BERT self-attention + FFN, pre- or post-LN, additive
    attention mask, fp32 softmax/LN accumulation for low-precision inputs);
  - the config surface (``DeepSpeedTransformerConfig`` key-for-key,
    transformer.py:93-134 there);
  - the *memory knobs*: ``normalize_invertible`` / ``gelu_checkpoint`` /
    ``attn_dropout_checkpoint`` drop saved intermediates in the reference;
    here they become ``jax.checkpoint`` (rematerialization) of the same
    segments, trading the identical FLOPs for the identical memory.
  - ``stochastic_mode`` relaxes RNG reproducibility for speed in the
    reference; here dropout keys are always cheap (counter-based TPU PRNG),
    so the flag is accepted and only recorded.

Differential tests against an independent jnp BERT encoder mirror the
reference's kernel-vs-HuggingFace tests (tests/unit/test_cuda_forward.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class DeepSpeedTransformerConfig:
    """Key-for-key port of the reference config
    (reference transformer.py:93-134)."""
    batch_size: int = -1
    max_seq_length: int = -1
    hidden_size: int = -1
    intermediate_size: int = -1
    heads: int = -1
    attn_dropout_ratio: float = 0.0
    hidden_dropout_ratio: float = 0.0
    num_hidden_layers: int = -1
    initializer_range: float = 0.02
    local_rank: int = -1          # accepted for parity; no device meaning
    seed: int = -1
    fp16: bool = False            # parity alias: prefer dtype=jnp.bfloat16
    pre_layer_norm: bool = True
    normalize_invertible: bool = False
    gelu_checkpoint: bool = False
    adjust_init_range: bool = True
    attn_dropout_checkpoint: bool = False
    stochastic_mode: bool = False
    # TPU extension mirroring models/gpt2.py:44 — the reference has no
    # such knob because its fused CUDA attention IS the only path
    # (csrc/transformer/ds_transformer_cuda.cpp:99-121); here 'flash'
    # runs the Pallas flash kernel (O(T·D) memory, no seq cap, in-kernel
    # dropout) and 'dense' the jnp softmax path.
    attn_impl: str = "flash"

    def __post_init__(self):
        if self.intermediate_size <= 0 < self.hidden_size:
            self.intermediate_size = 4 * self.hidden_size

    @classmethod
    def from_dict(cls, json_object: Dict[str, Any]):
        cfg = cls()
        for k, v in json_object.items():
            setattr(cfg, k, v)
        cfg.__post_init__()  # re-derive intermediate_size from hidden_size
        return cfg

    @classmethod
    def from_json_file(cls, json_file: str):
        import json
        with open(json_file, "r", encoding="utf-8") as f:
            return cls.from_dict(json.load(f))


def _layer_norm(x, scale, bias, eps: float = 1e-12):
    """fp32-accumulated LayerNorm (the reference kernel accumulates fp32
    for fp16 inputs, csrc/transformer/normalize_kernels.cu); BERT eps."""
    dt = x.dtype
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(dt)


def _dropout(x, rate: float, rng):
    if rate <= 0.0 or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


class DeepSpeedTransformerLayer:
    """Functional BERT encoder layer.

    ``__call__(params, hidden_states, attention_mask, rng, train)`` with
    hidden_states [B, T, H] and an additive attention mask broadcastable
    to [B, 1, 1, T] (HF convention: 0 keep, large-negative drop).

    Parameter names follow the reference layer's registry
    (transformer.py:437-466 there) so checkpoints map one-to-one:
    attn_qkvw/attn_qkvb, attn_ow/attn_ob, attn_nw/attn_nb (attention LN),
    inter_w/inter_b, output_w/output_b, norm_w/norm_b (output LN).
    """

    def __init__(self, config: DeepSpeedTransformerConfig,
                 initial_weights: Optional[Dict[str, Any]] = None):
        assert config.hidden_size > 0, "hidden_size must be set"
        assert config.heads > 0, "heads must be set"
        assert config.hidden_size % config.heads == 0, \
            f"hidden {config.hidden_size} not divisible by heads {config.heads}"
        self.config = config
        self.initial_weights = initial_weights

    # ------------------------------------------------------------------
    def init(self, rng) -> Dict[str, jnp.ndarray]:
        if self.initial_weights is not None:
            return dict(self.initial_weights)
        cfg = self.config
        d, i = cfg.hidden_size, cfg.intermediate_size
        std = cfg.initializer_range
        out_std = std
        if cfg.adjust_init_range and cfg.num_hidden_layers > 0:
            # output_std = initializer_range / sqrt(2 * num_layers)
            # (reference transformer.py docstring, adjust_init_range)
            out_std = std / float(2.0 * cfg.num_hidden_layers) ** 0.5
        ks = jax.random.split(rng, 4)
        n = jax.random.normal
        return {
            # [d, 3, d]: q/k/v on a dedicated dim so a TP 'model' shard of
            # the feature dim never straddles the q/k/v boundary (the
            # fused-[3d] layout forces GSPMD halo exchanges at the split)
            "attn_qkvw": n(ks[0], (d, 3, d), jnp.float32) * std,
            "attn_qkvb": jnp.zeros((3, d), jnp.float32),
            "attn_ow": n(ks[1], (d, d), jnp.float32) * out_std,
            "attn_ob": jnp.zeros((d,), jnp.float32),
            "attn_nw": jnp.ones((d,), jnp.float32),
            "attn_nb": jnp.zeros((d,), jnp.float32),
            "inter_w": n(ks[2], (d, i), jnp.float32) * std,
            "inter_b": jnp.zeros((i,), jnp.float32),
            "output_w": n(ks[3], (i, d), jnp.float32) * out_std,
            "output_b": jnp.zeros((d,), jnp.float32),
            "norm_w": jnp.ones((d,), jnp.float32),
            "norm_b": jnp.zeros((d,), jnp.float32),
        }

    # ------------------------------------------------------------------
    @staticmethod
    def _key_mask_rows(attention_mask, B, H, T):
        """HF additive mask (broadcastable to [B, 1|H, 1, T]) → [B, T]
        (shared across heads) or [B·H, T] (per-head) additive rows for
        the flash kernel's per-key mask.  Masks with a genuine
        q-position dimension cannot be expressed as a key mask — those
        callers need attn_impl='dense'."""
        m = jnp.asarray(attention_mask)
        while m.ndim < 4:
            m = m[:, None]
        if m.shape[2] != 1:
            raise ValueError(
                f"attn_impl='flash' supports key-padding masks "
                f"(broadcastable to [B, 1|H, 1, T]); got mask shape "
                f"{attention_mask.shape} with a q-position dimension — "
                "use attn_impl='dense' for arbitrary 2-D masks")
        if m.shape[1] == 1:
            return jnp.broadcast_to(m[:, 0, 0, :], (B, T)).astype(
                jnp.float32)
        # per-head masks keep their head dimension ([B·H, T] rows)
        rows = jnp.broadcast_to(m[:, :, 0, :], (B, H, T))
        return rows.reshape(B * H, T).astype(jnp.float32)

    def _attention(self, params, h, attention_mask, rng, train):
        cfg = self.config
        B, T, D = h.shape
        H = cfg.heads
        Dh = D // H
        qkv = (jnp.einsum("btd,dke->btke",
                          h, params["attn_qkvw"].astype(h.dtype))
               + params["attn_qkvb"].astype(h.dtype))
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        split = lambda t: t.reshape(B, T, H, Dh).transpose(0, 2, 1, 3)
        q, k, v = split(q), split(k), split(v)

        if cfg.attn_impl == "flash":
            # Pallas flash kernel: dropout fused in-kernel, padding mask
            # as the per-key operand.  attn_dropout_checkpoint is
            # structurally satisfied here — flash never materializes the
            # [T, T] probabilities, in forward OR backward.
            from ...ops.pallas.flash_attention import flash_attention
            km = (None if attention_mask is None
                  else self._key_mask_rows(attention_mask, B, H, T))
            ctx = flash_attention(
                q, k, v, causal=False,
                dropout_rate=cfg.attn_dropout_ratio if train else 0.0,
                dropout_rng=rng, key_mask=km)
            ctx = ctx.transpose(0, 2, 1, 3).reshape(B, T, D)
            return ctx @ params["attn_ow"].astype(h.dtype) \
                + params["attn_ob"].astype(h.dtype)
        if cfg.attn_impl != "dense":
            raise ValueError(
                f"attn_impl={cfg.attn_impl!r}: expected 'flash' or "
                "'dense'")

        def probs_ctx(q, k, v):
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                                preferred_element_type=jnp.float32)
            scores = scores * (float(Dh) ** -0.5)
            if attention_mask is not None:
                mask = attention_mask.astype(jnp.float32)
                while mask.ndim < 4:
                    mask = mask[:, None]
                scores = scores + mask
            probs = jax.nn.softmax(scores, axis=-1)
            probs = _dropout(probs.astype(q.dtype),
                             cfg.attn_dropout_ratio if train else 0.0, rng)
            return jnp.einsum("bhqk,bhkd->bhqd", probs, v)

        if cfg.attn_dropout_checkpoint:
            # the reference drops the attn-dropout/softmax intermediates and
            # recomputes them in backward (ds_transformer_cuda.cpp); remat
            # of this segment is the same trade
            probs_ctx = jax.checkpoint(probs_ctx)
        ctx = probs_ctx(q, k, v)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, T, D)
        return ctx @ params["attn_ow"].astype(h.dtype) \
            + params["attn_ob"].astype(h.dtype)

    def _ffn(self, params, h):
        def inner(h):
            x = h @ params["inter_w"].astype(h.dtype) \
                + params["inter_b"].astype(h.dtype)
            return jax.nn.gelu(x, approximate=False)

        if self.config.gelu_checkpoint:
            inner = jax.checkpoint(inner)
        x = inner(h)
        return x @ params["output_w"].astype(h.dtype) \
            + params["output_b"].astype(h.dtype)

    def __call__(self, params, hidden_states, attention_mask=None,
                 rng=None, train: bool = True):
        cfg = self.config
        x = hidden_states
        drop = cfg.hidden_dropout_ratio if train else 0.0
        if rng is None:
            rng = jax.random.PRNGKey(max(cfg.seed, 0))
        r_attn, r1, r2 = jax.random.split(rng, 3)

        ln1 = lambda t: _layer_norm(t, params["attn_nw"], params["attn_nb"])
        ln2 = lambda t: _layer_norm(t, params["norm_w"], params["norm_b"])
        if cfg.normalize_invertible:
            # reference: drop LN inputs, recompute from outputs
            # (normalize_invertible); remat of the LN segment ≡ same memory
            ln1, ln2 = jax.checkpoint(ln1), jax.checkpoint(ln2)

        if cfg.pre_layer_norm:
            attn_out = self._attention(params, ln1(x), attention_mask,
                                       r_attn, train)
            x = x + _dropout(attn_out, drop, r1)
            ffn_out = self._ffn(params, ln2(x))
            return x + _dropout(ffn_out, drop, r2)
        # post-LN (classic BERT)
        attn_out = self._attention(params, x, attention_mask, r_attn, train)
        x = ln1(x + _dropout(attn_out, drop, r1))
        ffn_out = self._ffn(params, x)
        return ln2(x + _dropout(ffn_out, drop, r2))

    forward = __call__
