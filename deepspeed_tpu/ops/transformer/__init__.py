"""Fused transformer layer (reference feature slot:
deepspeed/ops/transformer/ + csrc/transformer/)."""
from .transformer import (DeepSpeedTransformerConfig,
                          DeepSpeedTransformerLayer)

__all__ = ["DeepSpeedTransformerConfig", "DeepSpeedTransformerLayer"]
