"""deepspeed_tpu — a TPU-native training framework with the capability
surface of DeepSpeed v0.3.2 (see SURVEY.md), built on JAX/XLA/Pallas.

Public entry point mirrors the reference (reference: deepspeed/__init__.py:47):

    engine, optimizer, dataloader, lr_schedule = deepspeed_tpu.initialize(
        model=my_model, config=ds_config_dict_or_path, ...)
"""
from __future__ import annotations

import argparse
from typing import Any, Optional

from .version import __version__
from . import git_version_info as _gvi


def __getattr__(name):
    # lazily resolved (git subprocesses on first access, not at import);
    # NOTE: the bare name `version` stays bound to the version submodule
    if name == "__git_hash__":
        return _gvi.git_hash
    if name == "__git_branch__":
        return _gvi.git_branch
    raise AttributeError(name)
from .config import DeepSpeedConfig, DeepSpeedConfigError
from .config.constants import ADAM_OPTIMIZER, LAMB_OPTIMIZER
from .parallel.distributed import init_distributed
from .runtime.engine import DeepSpeedEngine
from .runtime.module import TrainModule, FunctionalModule, FlaxModule
from .runtime.dataloader import DeepSpeedDataLoader, RepeatingLoader
from .runtime.prefetch import DevicePlacedBatch, DevicePrefetcher
from .runtime.lr_schedules import add_tuning_arguments
from .runtime.activation_checkpointing import checkpointing
from .utils.logging import log_dist
from .ops.transformer import (DeepSpeedTransformerLayer,
                              DeepSpeedTransformerConfig)
from .pipe.module import PipelineModule
from .pipe.engine import PipelineEngine


def initialize(args=None,
               model: Optional[TrainModule] = None,
               optimizer=None,
               params: Optional[Any] = None,
               training_data=None,
               lr_scheduler=None,
               mesh=None,
               collate_fn=None,
               config=None,
               config_params=None,
               seed: int = 0):
    """Create the engine (reference: deepspeed/__init__.py:47-136).

    Returns ``(engine, optimizer, training_dataloader, lr_scheduler)`` —
    same 4-tuple contract as the reference.  ``config`` may be a path to a
    ds_config.json or a dict (``config_params`` alias kept for parity).
    Dispatches to the pipeline engine when ``model`` is a PipelineModule.
    """
    assert model is not None, "deepspeed_tpu.initialize requires a model"
    # engine-owned process-group init, as in the reference
    # (engine.py:125-145): join the multi-host runtime when the launcher's
    # env contract is present — must happen before any mesh/device use
    init_distributed()
    cfg_src = config if config is not None else config_params
    if cfg_src is None and args is not None:
        cfg_src = getattr(args, "deepspeed_config", None)
    if cfg_src is None:
        raise DeepSpeedConfigError("No DeepSpeed config provided")

    from .parallel.mesh import build_mesh, mesh_axis_size, DATA_AXIS
    from .pipe.module import PipelineModule

    def resolve_cfg(mesh):
        if isinstance(cfg_src, DeepSpeedConfig):
            return cfg_src  # pre-built config passes through unchanged
        return DeepSpeedConfig(cfg_src,
                               world_size=mesh_axis_size(mesh, DATA_AXIS))

    if isinstance(model, PipelineModule):
        from .pipe.engine import PipelineEngine
        if mesh is None:
            mesh = build_mesh(pp=model.num_stages)
        cfg = resolve_cfg(mesh)
        engine = PipelineEngine(model=model, config=cfg, mesh=mesh,
                                optimizer=optimizer,
                                lr_schedule=lr_scheduler, params=params,
                                training_data=training_data,
                                collate_fn=collate_fn, seed=seed)
    else:
        if mesh is None:
            mesh = build_mesh()
        cfg = resolve_cfg(mesh)
        engine = DeepSpeedEngine(model=model, config=cfg, mesh=mesh,
                                 optimizer=optimizer,
                                 lr_schedule=lr_scheduler, params=params,
                                 training_data=training_data,
                                 collate_fn=collate_fn, seed=seed)
    return (engine, engine.optimizer, engine.training_dataloader,
            engine.lr_scheduler)


def add_config_arguments(parser: argparse.ArgumentParser):
    """argparse plumbing (reference: deepspeed/__init__.py:139-203)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag, parity only)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the DeepSpeed json config file")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help=argparse.SUPPRESS)  # deprecated alias
    group.add_argument("--deepscale_config", default=None, type=str,
                       help=argparse.SUPPRESS)
    return parser
