"""ZeRO-Infinity disk tier — optimizer state and fp32 master params on
disk, streamed through the per-leaf update pipeline.

The host tier (runtime/offload.py) freed HBM by moving the fp32 master
and both Adam moments to host RAM — which then CAPS trainable size at
what the host can hold (12 bytes/param of state).  This module adds the
tier below (Rajbhandari et al. 2021, ZeRO-Infinity, PAPERS.md): the
state lives in ONE CRC'd file per parameter leaf under
``offload.disk_dir``, and host RAM holds only a bounded window of
leaves — ``io_depth`` read-ahead + the leaf being updated + ``io_depth``
write-back — so trainable size is capped by disk, not RAM.

The per-leaf pipeline gains a third tier: while the C++ Adam updates
leaf i,

  - leaf i+1's state is being READ from disk (the ``disk_read`` stage
    worker, bounded read-ahead through a :class:`~.stages.Channel`),
  - leaf i-1's updated state is being WRITTEN back (the ``disk_write``
    stage worker, tmp+rename with CRC, bounded queue), and
  - leaf i-1's compute copy is already uploading H2D (the engine's
    existing ``StreamingUploader`` via ``on_leaf`` — unchanged).

Failure semantics ride the PR 7 stage runtime wholesale: every disk
read/write is one ``Stage.call`` unit (``disk_read:read`` /
``disk_write:write`` injection points, ``DS_STAGE_FAULT`` /
``DS_STAGE_DELAY_S`` chaos for free), transient ``OSError``s retry
against ``io_retry`` inside and the stage's failure budget outside, and
an exhausted budget DEGRADES to the serial read-update-write loop —
bitwise the pipelined path, latency-only cost (docs/stages.md).  A
CRC mismatch is :class:`DiskStateCorruptError` (typed, non-transient):
it propagates before the corrupt bytes ever reach the Adam kernel, the
optimizer poisons, and checkpoint restore (``load_state_tree``)
rewrites every leaf file from the verified checkpoint.

Bitwise contract: the Adam kernel entry is ``DeepSpeedCPUAdam
.apply_leaf`` — the SAME call ``step_leaves`` makes for the host tier —
so disk-tier training loss is bitwise the host tier's, which is bitwise
the serial reference (the PR 3/7 discipline, tests/test_disk_offload.py).
"""
from __future__ import annotations

import io
import json
import os
import struct
import threading
import time
import zlib
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from ..ops.cpu_adam import (DeepSpeedCPUAdam, is_adam_float, lowp_np_dtype,
                            lowp_np_kind)
from ..utils.logging import logger
from .checkpointing import _from_storage, _to_storage
from .offload import (HostOffloadOptimizer, _PrefetchPuller, _transfer_span,
                      chunked_device_get)
from .resilience import (CheckpointCorruptError, DEFAULT_RETRY, RetryPolicy,
                         io_retry)
from .stages import Channel, Stage, spawn

__all__ = [
    "DiskLeafStore", "DiskOffloadOptimizer", "DiskStateCorruptError",
    "disk_fsync_enabled",
]

#: leaf-state file magic (version-stamped: a format change bumps this,
#: and an old file fails loudly as corrupt rather than misparsing)
_MAGIC = b"DSDISK1\n"

#: section order inside a leaf file (master first so partial reads of
#: just the params — compute_params, the master views — seek once)
_SECTIONS = ("master", "mu", "nu")


class DiskStateCorruptError(CheckpointCorruptError):
    """A disk-tier leaf-state file failed integrity verification (CRC /
    length / magic mismatch).  Typed and NON-transient: retrying cannot
    heal bit rot — the optimizer poisons and the caller restores from a
    checkpoint (``load_state_tree`` rewrites every leaf file)."""


def disk_fsync_enabled(config_default: bool = True) -> bool:
    """Per-file fsync before each leaf-state rename.  ON by default
    (the ``offload.fsync`` config knob AND the ``DS_DISK_FSYNC`` env
    var must both allow it — the DS_CKPT_FSYNC discipline: tests/CI set
    the env var to 0 because unit tests simulate process death, which
    the page cache survives, and the CI image's 9p filesystem charges
    ~50ms per fsync).  Even with fsync off, a torn write is caught by
    the CRC plane and the tmp+rename protocol keeps the previous good
    file in place."""
    return bool(config_default) and os.environ.get(
        "DS_DISK_FSYNC", "1") != "0"


class DiskLeafStore:
    """One CRC'd binary file per parameter leaf: magic, a JSON header
    naming each section's dtype/shape/CRC32/byte-extent, then the raw
    section payloads (master, mu, nu).  Writes stage to ``<path>.tmp``
    and rename atomically — a crash mid-write leaves the previous good
    file untouched (per-leaf last-good state) — with ``io_retry``
    absorbing transient OS blips.  Reads verify length + CRC per
    section and raise :class:`DiskStateCorruptError` BEFORE returning
    any bytes to the caller."""

    def __init__(self, directory: str, fsync: bool = True,
                 retry: RetryPolicy = DEFAULT_RETRY):
        self.directory = directory
        self.fsync = bool(fsync)
        self.retry = retry
        os.makedirs(directory, exist_ok=True)

    def path(self, idx: int) -> str:
        return os.path.join(self.directory, f"leaf_{idx:05d}.state")

    # -- write ----------------------------------------------------------
    def write(self, idx: int, sections: Dict[str, np.ndarray]) -> int:
        """Serialize ``sections`` (a subset of master/mu/nu, in
        :data:`_SECTIONS` order) for leaf ``idx``; returns payload bytes
        written.  tmp+rename so readers only ever see a complete file."""
        header: Dict[str, Any] = {"leaf": idx, "sections": {}}
        payload = io.BytesIO()
        total = 0
        for name in _SECTIONS:
            if name not in sections:
                continue
            store, logical = _to_storage(
                np.ascontiguousarray(sections[name]))
            raw = store.tobytes()
            header["sections"][name] = {
                "dtype": logical,
                "store_dtype": store.dtype.name,
                "shape": list(store.shape),
                "crc32": zlib.crc32(raw) & 0xFFFFFFFF,
                "offset": total,
                "nbytes": len(raw),
            }
            payload.write(raw)
            total += len(raw)
        blob = json.dumps(header).encode()
        path = self.path(idx)
        tmp = path + ".tmp"

        def do_write():
            with open(tmp, "wb") as f:
                f.write(_MAGIC)
                f.write(struct.pack("<Q", len(blob)))
                f.write(blob)
                f.write(payload.getbuffer())
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            os.rename(tmp, path)

        io_retry(do_write, f"disk-tier write {path}", self.retry)
        return total

    # -- read -----------------------------------------------------------
    def read(self, idx: int,
             names: Optional[Tuple[str, ...]] = None
             ) -> Dict[str, np.ndarray]:
        """Load (a subset of) leaf ``idx``'s sections, CRC-verified.
        Sections are seek-read individually, so a master-only read
        (``names=("master",)``) never touches the moment bytes."""
        path = self.path(idx)

        def do_read():
            out: Dict[str, np.ndarray] = {}
            with open(path, "rb") as f:
                magic = f.read(len(_MAGIC))
                if magic != _MAGIC:
                    raise DiskStateCorruptError(
                        f"disk-tier state {path}: bad magic {magic!r} "
                        "(truncated or foreign file)")
                (hlen,) = struct.unpack("<Q", f.read(8))
                try:
                    header = json.loads(f.read(hlen))
                except ValueError as e:
                    raise DiskStateCorruptError(
                        f"disk-tier state {path}: unparseable header "
                        f"({e})")
                base = f.tell()
                for name in (names or _SECTIONS):
                    ent = header["sections"].get(name)
                    if ent is None:
                        raise DiskStateCorruptError(
                            f"disk-tier state {path}: missing section "
                            f"{name!r}")
                    f.seek(base + int(ent["offset"]))
                    raw = f.read(int(ent["nbytes"]))
                    if len(raw) != int(ent["nbytes"]):
                        raise DiskStateCorruptError(
                            f"disk-tier state {path} section {name!r}: "
                            f"{len(raw)} bytes on disk, header records "
                            f"{ent['nbytes']} (truncated write?)")
                    got = zlib.crc32(raw) & 0xFFFFFFFF
                    if got != int(ent["crc32"]):
                        raise DiskStateCorruptError(
                            f"disk-tier state {path} section {name!r}: "
                            f"CRC32 mismatch (stored "
                            f"{int(ent['crc32']):#010x}, computed "
                            f"{got:#010x}) — bit corruption or partial "
                            "write")
                    arr = np.frombuffer(
                        bytearray(raw),
                        dtype=np.dtype(ent["store_dtype"])).reshape(
                            ent["shape"])
                    out[name] = _from_storage(arr, ent["dtype"])
            return out

        try:
            out = io_retry(do_read, f"disk-tier read {path}", self.retry)
        except FileNotFoundError:
            raise DiskStateCorruptError(
                f"disk-tier state {path} is missing")
        return out


class _DiskLeafView:
    """Lazy handle for one section of one leaf's disk state: carries
    shape/dtype metadata (what the checkpoint loader's templates need)
    and materializes from disk only when ``np.asarray`` asks — which is
    how a full checkpoint save streams the master leaf-by-leaf instead
    of holding the whole fp32 tree in RAM."""

    __slots__ = ("_store", "_idx", "_name", "shape", "dtype")

    def __init__(self, store: DiskLeafStore, idx: int, name: str,
                 shape: Tuple[int, ...], dtype):
        self._store = store
        self._idx = idx
        self._name = name
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64))

    @property
    def nbytes(self) -> int:
        return self.size * self.dtype.itemsize

    def __array__(self, dtype=None, copy=None):
        arr = self._store.read(self._idx, names=(self._name,))[self._name]
        return arr if dtype is None else arr.astype(dtype)

    def astype(self, dtype):
        return np.asarray(self).astype(dtype)

    def copy(self):
        return np.asarray(self)

    def __repr__(self):
        return (f"_DiskLeafView({self._name!r}, leaf={self._idx}, "
                f"shape={self.shape}, dtype={self.dtype.name})")


#: Channel end-of-stream sentinel for the write-back worker
_DONE = object()


class DiskOffloadOptimizer:
    """Single-controller ZeRO-Infinity disk tier — API-compatible with
    :class:`~.offload.HostOffloadOptimizer` (the engine treats both as
    ``_host_opt``), but the fp32 master and Adam moments live in
    per-leaf files and host RAM holds only the pipeline window.

    ``step`` drives the three-tier pipeline described in the module
    docstring; a DEGRADED ``disk_read``/``disk_write`` stage (or
    ``DS_DISK_OFFLOAD_PIPELINE=0``, the serial reference knob) pins the
    serial read-update-write loop — bitwise the pipelined path.

    ``ram_budget_bytes`` (optional; ``DS_OFFLOAD_DISK_RAM_BUDGET_MB``
    env override) is the capacity-accounting assert: resident leaf-
    state bytes (read-ahead + in-update + write-back buffers) must stay
    under it even when ``total_state_bytes`` — the full master+moments
    footprint on disk — exceeds it.  Exceeding the budget raises
    (non-transient): the window sizing is the contract, not a hint."""

    def __init__(self, master_params, lr, betas, eps, weight_decay,
                 adamw_mode: bool = True, bias_correction: bool = True,
                 compute_dtype=None, use_native: Optional[bool] = None,
                 disk_dir: str = "", io_depth: int = 2,
                 fsync: bool = True,
                 ram_budget_bytes: Optional[int] = None):
        import jax.numpy as jnp
        if compute_dtype is None:
            compute_dtype = jnp.bfloat16
        if not disk_dir:
            raise ValueError("DiskOffloadOptimizer requires disk_dir")
        HostOffloadOptimizer._probe_transfer_path(master_params)
        self._poisoned: Optional[BaseException] = None
        self.last_d2h_seconds = 0.0
        self.last_disk_breakdown: Optional[dict] = None
        self.io_depth = max(1, int(io_depth))
        self._store = DiskLeafStore(disk_dir,
                                    fsync=disk_fsync_enabled(fsync))
        self.opt = DeepSpeedCPUAdam(
            lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
            adamw_mode=adamw_mode, bias_correction=bias_correction,
            use_native=use_native)
        self.compute_dtype = compute_dtype
        self._out_dtype = ("bfloat16" if compute_dtype == jnp.bfloat16
                           else "float16" if compute_dtype == jnp.float16
                           else None)
        # stage records: private by default; the engine re-binds its
        # wired ``disk_read``/``disk_write`` records (telemetry counter
        # hook + flight-recorder dump) after wire_stage_plane runs
        self._read_stage = Stage("disk_read",
                                 fallback="the serial read-update-write "
                                          "loop")
        self._write_stage = Stage("disk_write",
                                  fallback="the serial read-update-write "
                                           "loop")
        env_budget = os.environ.get("DS_OFFLOAD_DISK_RAM_BUDGET_MB")
        if env_budget:
            ram_budget_bytes = int(float(env_budget) * (1 << 20))
        self.ram_budget_bytes = ram_budget_bytes
        self._resident_lock = threading.Lock()
        self._resident_bytes = 0
        self.peak_resident_bytes = 0
        self._abort = False
        self._inflight: list = []  # live Channels, closed on abort
        #: the current step's write-back completion event — restore
        #: fences on it so a stale in-flight leaf write can never land
        #: AFTER load_state_tree rewrote the file (a CRC-valid silent
        #: revert the corruption plane could not detect)
        self._writeback_done: Optional[threading.Event] = None
        # spill the initial state leaf-by-leaf: pull fp32 (floats) or
        # passthrough (ints/bools), write master + zero moments, FREE —
        # the full fp32 tree never has to be host-resident
        leaves, self._treedef = jax.tree.flatten(master_params)
        self._meta: list = []  # per leaf: (shape, np dtype, is_float)
        for i, leaf in enumerate(leaves):
            dt = np.dtype(leaf.dtype)
            promote = is_adam_float(dt)
            if promote:
                out = np.empty(np.shape(leaf), np.float32)
                blk = chunked_device_get(leaf, what="master spill",
                                         out=out)
                zeros = np.zeros_like(blk)
                self._write_leaf(i, blk, zeros, zeros)
            else:
                blk = np.array(chunked_device_get(
                    leaf, what="master spill"))
                self._write_leaf(i, blk, None, None)
            self._meta.append((tuple(np.shape(leaf)),
                               np.dtype(np.float32) if promote else dt,
                               promote))
        #: full master+moments footprint on disk (the capacity claim's
        #: numerator: this exceeds the RAM budget while training works)
        self.total_state_bytes = sum(
            (3 if prom else 1) * int(np.prod(shape, dtype=np.int64))
            * dt.itemsize
            for shape, dt, prom in self._meta)

    # -- stage plumbing -------------------------------------------------
    def bind_stages(self, read_stage: Stage, write_stage: Stage) -> None:
        """Adopt the engine's wired stage records (failure budgets that
        persist across steps, telemetry counters, flight-recorder
        dumps) in place of the construction-time private ones."""
        self._read_stage = read_stage
        self._write_stage = write_stage

    def _drain_close_release(self, ch: Channel) -> None:
        """Atomically snapshot-and-clear a pipeline channel's queued
        items, close it, and release their resident-byte claims.  A
        separate drain then close would let a racing put land between
        the two and be cleared uncounted; ``Channel.close`` alone
        clears items WITHOUT releasing — either way every later step
        would fail the budget check on phantom bytes."""
        with ch.cond:
            items = [it for it in ch.items if it is not _DONE]
            ch.items.clear()
            ch.closed = True
            ch.cond.notify_all()
        for it in items:
            # read channel items are (i, sections); write channel items
            # are (i, master, mu, nu, nbytes)
            self._release(self._state_bytes(it[1])
                          if len(it) == 2 else it[4])

    def abort_inflight(self) -> None:
        """Release the pipeline workers without waiting (engine close
        landing mid-step from another thread/signal handler): channels
        close, the step raises, nothing is half-published — the step's
        disk writes that already landed are superseded on restore."""
        self._abort = True
        for ch in list(self._inflight):
            self._drain_close_release(ch)

    @property
    def is_native(self) -> bool:
        return self.opt.is_native

    # -- residency accounting -------------------------------------------
    def _acquire(self, nbytes: int) -> None:
        with self._resident_lock:
            self._resident_bytes += nbytes
            claimed = self._resident_bytes
            over = (self.ram_budget_bytes is not None
                    and claimed > self.ram_budget_bytes)
            if over:
                # roll the claim back before raising: the buffer is
                # dropped on this failure path, so leaving it counted
                # would make every later step fail the budget spuriously
                self._resident_bytes -= nbytes
            elif claimed > self.peak_resident_bytes:
                self.peak_resident_bytes = claimed
        if over:
            raise RuntimeError(
                f"disk-tier resident state {claimed} bytes "
                f"exceeds the configured host-RAM budget "
                f"{self.ram_budget_bytes} (io_depth={self.io_depth}): "
                "the pipeline window no longer fits — lower io_depth or "
                "raise the budget")

    def _release(self, nbytes: int) -> None:
        with self._resident_lock:
            self._resident_bytes -= nbytes

    @staticmethod
    def _state_bytes(sections: Dict[str, np.ndarray]) -> int:
        return sum(int(a.nbytes) for a in sections.values())

    # -- file I/O units (one Stage.call each) ----------------------------
    def _write_leaf(self, i: int, master, mu, nu,
                    timings: Optional[list] = None) -> None:
        sections = {"master": master}
        if mu is not None:
            sections["mu"] = mu
            sections["nu"] = nu
        nbytes = self._state_bytes(sections)
        t0 = time.perf_counter()
        with _transfer_span("offload/disk_write", cat="disk", leaf=i,
                            bytes=nbytes):
            self._write_stage.call(
                "write", lambda: self._store.write(i, sections),
                path=self._store.path(i))
        if timings is not None:
            timings.append((t0, time.perf_counter(), nbytes))

    def _read_leaf(self, i: int, timings: Optional[list] = None,
                   names: Optional[Tuple[str, ...]] = None
                   ) -> Dict[str, np.ndarray]:
        _shape, _dt, promote = self._meta[i]
        if names is None:
            names = _SECTIONS if promote else ("master",)
        t0 = time.perf_counter()
        with _transfer_span("offload/disk_read", cat="disk", leaf=i):
            out = self._read_stage.call(
                "read", lambda: self._store.read(i, names=names),
                path=self._store.path(i))
        if timings is not None:
            timings.append((t0, time.perf_counter(),
                            self._state_bytes(out)))
        return out

    # -- views ----------------------------------------------------------
    def _view(self, i: int, name: str) -> _DiskLeafView:
        shape, dt, _promote = self._meta[i]
        return _DiskLeafView(self._store, i, name, shape, dt)

    @property
    def master(self):
        """Lazy master views (TrainState's tree): shape/dtype resident,
        bytes on disk until a checkpoint save (or explicit np.asarray)
        materializes them leaf-by-leaf."""
        return jax.tree.unflatten(
            self._treedef,
            [self._view(i, "master") for i in range(len(self._meta))])

    def compute_params(self):
        """Initial compute-dtype copies, materialized one leaf at a time
        (master-section seek-reads; the fp32 tree is never resident)."""
        dt = lowp_np_dtype(self._out_dtype)
        outs = []
        for i, (_shape, ldt, promote) in enumerate(self._meta):
            # master-only seek-read: the moments' 8 bytes/param must
            # not be read (and CRC'd) just to be discarded
            blk = self._read_leaf(i, names=("master",))["master"]
            if promote and dt is not None:
                blk = blk.astype(dt)
            outs.append(blk)
        return jax.tree.unflatten(self._treedef, outs)

    # -- the step --------------------------------------------------------
    def _require_healthy(self):
        if self._poisoned is not None:
            raise RuntimeError(
                "DiskOffloadOptimizer is poisoned: a previous step "
                "failed mid-update, leaving the on-disk master/moments "
                "inconsistent across leaves. Restore from a checkpoint. "
                f"Original error: {self._poisoned!r}")

    def step(self, host_grads, on_leaf: Optional[Callable] = None):
        """C++ Adam over disk-resident state; returns upload copies in
        the configured compute dtype (same contract as the host tier's
        ``step``, including the ``on_leaf`` streaming hook the engine's
        H2D uploader consumes).  Grad leaves may be numpy or jax Arrays
        — the watchdogged prefetch puller overlaps their D2H with the
        Adam, exactly as on the host tier.

        A mid-step failure leaves leaf files before the failing leaf at
        step t and later ones at t-1 (and the step counter advanced) —
        the optimizer POISONS itself; ``load_state_tree`` (checkpoint
        restore) rewrites every leaf file and clears the poison."""
        self._require_healthy()
        with self._resident_lock:
            if self._resident_bytes:
                # nothing is legitimately resident between steps: a
                # stranded claim (a failure path that dropped buffers
                # without releasing) must not fail every later step's
                # budget check — log it and reset, loudly
                logger.warning(
                    "disk-tier resident accounting reset: %d bytes "
                    "stranded by a previous failed step",
                    self._resident_bytes)
                self._resident_bytes = 0
        g_leaves = jax.tree.leaves(host_grads)
        n = len(self._meta)
        assert len(g_leaves) == n, (len(g_leaves), n)
        serial = (self._read_stage.degraded or self._write_stage.degraded
                  or os.environ.get("DS_DISK_OFFLOAD_PIPELINE", "1")
                  == "0")
        self.opt.step_count += 1
        lr = self.opt._lr_now()
        lowp = lowp_np_kind(self._out_dtype)
        read_t: list = []
        write_t: list = []
        adam_t: list = []
        leaf_get = _PrefetchPuller(g_leaves)
        self._abort = False
        try:
            if serial:
                outs = self._step_serial(g_leaves, leaf_get, lr, lowp,
                                         on_leaf, read_t, write_t, adam_t)
            else:
                outs = self._step_pipelined(g_leaves, leaf_get, lr, lowp,
                                            on_leaf, read_t, write_t,
                                            adam_t)
        except BaseException as e:
            self._poisoned = e
            raise
        finally:
            self.last_d2h_seconds = leaf_get.seconds
            leaf_get.close()
            self._record_breakdown(read_t, write_t, adam_t, serial)
        return jax.tree.unflatten(self._treedef, outs)

    def _update_one(self, i: int, state: Dict[str, np.ndarray], g,
                    leaf_get, lr: float, lowp: int, adam_t: list):
        """Adam over ONE leaf's freshly-read state; returns (upload
        leaf, updated sections or None for passthrough).  The kernel
        entry is ``apply_leaf`` — the host tier's exact code path."""
        shape, _dt, promote = self._meta[i]
        p = state["master"]
        if not promote:
            # non-floating state (step counters, int buffers): no Adam;
            # upload the (fresh, never-mutated) buffer like the host
            # tier uploads its live block
            return (p if lowp else p.copy()), None
        t0 = time.perf_counter()
        with _transfer_span("offload/adam_leaf", cat="offload", leaf=i):
            flat_p = p.reshape(-1)
            flat_g = np.ascontiguousarray(
                np.asarray(leaf_get(g), dtype=np.float32).reshape(-1))
            m, v = state["mu"].reshape(-1), state["nu"].reshape(-1)
            out = self.opt.apply_leaf(flat_p, flat_g, m, v, lr, lowp)
        adam_t.append((t0, time.perf_counter()))
        up = (out.view(lowp_np_dtype(self._out_dtype)).reshape(shape)
              if lowp else p.copy())
        return up, state

    def _step_serial(self, g_leaves, leaf_get, lr, lowp, on_leaf,
                     read_t, write_t, adam_t):
        """The degradation target and bitwise reference: read leaf i,
        update, write it back, then move to leaf i+1 — one leaf's state
        resident at a time, no workers."""
        outs: list = [None] * len(self._meta)
        for i, g in enumerate(g_leaves):
            state = self._read_leaf(i, read_t)
            nbytes = self._state_bytes(state)
            self._acquire(nbytes)
            try:
                up, updated = self._update_one(i, state, g, leaf_get,
                                               lr, lowp, adam_t)
                if updated is not None:
                    self._write_leaf(i, updated["master"], updated["mu"],
                                     updated["nu"], write_t)
            finally:
                self._release(nbytes)
            outs[i] = up
            if on_leaf is not None:
                on_leaf(i, up)
        return outs

    def _step_pipelined(self, g_leaves, leaf_get, lr, lowp, on_leaf,
                        read_t, write_t, adam_t):
        """The three-tier pipeline: a read-ahead worker keeps at most
        ``io_depth`` leaf states staged, the main thread Adams them in
        order, a write-back worker drains at most ``io_depth`` updated
        states to disk — leaf i's compute, i+1's read, and i-1's
        write-back all in flight."""
        n = len(self._meta)
        rd_ch = Channel(capacity=self.io_depth)
        wr_ch = Channel(capacity=self.io_depth)
        self._inflight = [rd_ch, wr_ch]
        wr_done = threading.Event()
        self._writeback_done = wr_done
        wr_err: dict = {}

        def read_loop():
            try:
                for i in range(n):
                    if self._abort:
                        # close the channel OURSELVES: an abort_inflight
                        # that raced step() before _inflight was
                        # populated closed nothing, and a silent return
                        # would park the main thread in rd_ch.get()
                        # forever
                        rd_ch.close()
                        return
                    state = self._read_leaf(i, read_t)
                    self._acquire(self._state_bytes(state))
                    if not rd_ch.put((i, state)):
                        # consumer gone (poison/close): the staged leaf
                        # will never be consumed — release its bytes
                        self._release(self._state_bytes(state))
                        return
                rd_ch.put(_DONE, force=True)
            except BaseException as e:
                rd_ch.poison(e)

        def write_loop():
            try:
                while True:
                    item = wr_ch.get()
                    if item is _DONE:
                        break
                    i, master, mu, nu, nbytes = item
                    try:
                        self._write_leaf(i, master, mu, nu, write_t)
                    finally:
                        self._release(nbytes)
            except BaseException as e:
                wr_err["e"] = e
                wr_ch.poison(e)
            finally:
                wr_done.set()

        spawn(read_loop, name="ds-disk-read", restarts=0)
        spawn(write_loop, name="ds-disk-write", restarts=0)
        outs: list = [None] * n
        try:
            for i, g in enumerate(g_leaves):
                item = rd_ch.get()  # re-raises the reader's poison
                assert item is not _DONE and item[0] == i, (i, item)
                state = item[1]
                nbytes = self._state_bytes(state)
                try:
                    up, updated = self._update_one(i, state, g, leaf_get,
                                                   lr, lowp, adam_t)
                except BaseException:
                    self._release(nbytes)
                    raise
                if updated is not None:
                    # Deliberate bounded-RAM backpressure: the streaming
                    # step runs on the main thread and MUST stall when
                    # the writer falls behind — unbounded buffering here
                    # defeats the disk tier's memory ceiling.  The put
                    # result is checked and the writer's error surfaced.
                    # jaxlint: disable=JL008
                    if not wr_ch.put((i, updated["master"], updated["mu"],
                                      updated["nu"], nbytes)):
                        # writer poisoned/closed: surface ITS error
                        self._release(nbytes)
                        raise wr_err.get("e") or RuntimeError(
                            "disk write-back channel closed mid-step")
                else:
                    self._release(nbytes)
                outs[i] = up
                if on_leaf is not None:
                    on_leaf(i, up)
            wr_ch.put(_DONE, force=True)
            wr_done.wait()
            if "e" in wr_err:
                raise wr_err["e"]
        except BaseException:
            # fail fast AND release the workers: a parked reader/writer
            # would otherwise pin channel buffers (and leak the thread).
            # Queued items are dropped here, so their resident-byte
            # claims must be released first (Channel.close clears the
            # queue) — a stranded claim would fail every later step's
            # budget check spuriously.
            self._drain_close_release(rd_ch)
            self._drain_close_release(wr_ch)
            wr_done.wait(timeout=30.0)
            raise
        finally:
            self._inflight = []
        return outs

    # -- overlap accounting ----------------------------------------------
    def _record_breakdown(self, read_t, write_t, adam_t, serial):
        """How much disk I/O time ran CONCURRENTLY with Adam compute,
        from host timestamps: each I/O interval is intersected with the
        merged per-leaf Adam intervals (serial loop: I/O sits between
        Adam calls, so hidden == 0 by construction — the same shape as
        the host tier's h2d_hidden accounting)."""
        # snapshot: on a failure path a worker may still be appending
        read_t, write_t, adam_t = list(read_t), list(write_t), list(adam_t)
        merged: list = []
        for a0, a1 in sorted(adam_t):
            if merged and a0 <= merged[-1][1]:
                merged[-1] = (merged[-1][0], max(merged[-1][1], a1))
            else:
                merged.append((a0, a1))

        def hidden_of(t0, t1):
            h = 0.0
            for a0, a1 in merged:
                h += max(0.0, min(t1, a1) - max(t0, a0))
            return h

        read_s = sum(t1 - t0 for t0, t1, _ in read_t)
        write_s = sum(t1 - t0 for t0, t1, _ in write_t)
        hidden = sum(hidden_of(t0, t1) for t0, t1, _ in read_t)
        hidden += sum(hidden_of(t0, t1) for t0, t1, _ in write_t)
        io_s = read_s + write_s
        self.last_disk_breakdown = {
            "tier": "disk",
            "disk_serial": bool(serial),
            "disk_read_s": read_s,
            "disk_write_s": write_s,
            "disk_hidden_s": hidden,
            "disk_overlap_ratio": (hidden / io_s) if io_s > 0 else 0.0,
            "disk_bytes_read": sum(b for _, _, b in read_t),
            "disk_bytes_written": sum(b for _, _, b in write_t),
        }

    def poison(self, err: BaseException) -> None:
        """Engine-side poison (an H2D upload failed after the Adam
        completed) — same contract as the host tier."""
        self._poisoned = err

    # -- checkpoint plumbing ---------------------------------------------
    def state_tree(self):
        """Optimizer state as lazy disk views aligned with the master
        (what TrainState.opt_state holds and the checkpointer streams
        leaf-by-leaf at save).  Refuses while poisoned — serializing a
        cross-leaf-inconsistent state would turn a clean failure into
        silent divergence on restore."""
        if self._poisoned is not None:
            raise RuntimeError(
                "refusing to serialize inconsistent optimizer state (a "
                "step failed mid-update on the disk tier). Restore from "
                f"an earlier checkpoint. Original error: "
                f"{self._poisoned!r}")
        n = len(self._meta)

        def views(name):
            # passthrough leaves get zeros in their OWN dtype — the same
            # zeros_like shape the host tier's _moments would hold
            return jax.tree.unflatten(
                self._treedef,
                [self._view(i, name) if self._meta[i][2]
                 else np.zeros(self._meta[i][0], self._meta[i][1])
                 for i in range(n)])

        return {"step": np.asarray(self.opt.step_count, np.int64),
                "mu": views("mu"), "nu": views("nu")}

    def load_state_tree(self, master_tree, opt_tree) -> None:
        """Restore by REWRITING every leaf file from the loaded trees
        (``opt_tree=None`` zeroes the moments and the step counter, the
        module-only restore path) — which is also what heals a torn
        (killed-mid-write-back) state: every leaf lands at the
        checkpoint's step, and the poison clears."""
        ev = self._writeback_done
        if ev is not None and not ev.wait(timeout=60.0):
            # a wedged write-back worker may still hold a tmp+rename in
            # flight; restoring UNDER it would let that stale step-t
            # write atomically replace the freshly restored file —
            # CRC-valid, undetectable, exactly the cross-leaf
            # divergence the poison contract forbids
            raise RuntimeError(
                "disk write-back worker from a failed step is still in "
                "flight after 60s; refusing to restore over it")
        m_leaves = jax.tree.leaves(master_tree)
        mu_leaves = nu_leaves = None
        if opt_tree is not None:
            mu_leaves = jax.tree.leaves(opt_tree["mu"])
            nu_leaves = jax.tree.leaves(opt_tree["nu"])

        def to_host(x, dtype):
            if isinstance(x, jax.Array):
                arr = chunked_device_get(x, what="restore pull")
            else:
                arr = np.asarray(x)
            return np.ascontiguousarray(arr, dtype=dtype)

        for i, (shape, dt, promote) in enumerate(self._meta):
            blk = to_host(m_leaves[i], dt)
            assert tuple(blk.shape) == shape, (blk.shape, shape)
            if not promote:
                self._write_leaf(i, blk, None, None)
                continue
            if mu_leaves is None:
                mu = np.zeros(shape, np.float32)
                nu = np.zeros(shape, np.float32)
            else:
                mu = to_host(mu_leaves[i], np.float32)
                nu = to_host(nu_leaves[i], np.float32)
            self._write_leaf(i, blk, mu, nu)
        self.opt.step_count = (
            0 if opt_tree is None
            else int(np.asarray(jax.device_get(opt_tree["step"]))))
        self._poisoned = None
