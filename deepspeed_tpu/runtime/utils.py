"""Runtime numeric utilities (norms, clipping).

(reference: deepspeed/runtime/utils.py:154-275 — grad/weight norms with
model-parallel dedup.  Under SPMD-by-sharding there is nothing to dedup:
gradients are unique per logical tensor, so the norms are plain reductions
which XLA fuses into the step.)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def global_norm(tree) -> jnp.ndarray:
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    sq = sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    return jnp.sqrt(sq)


def clip_by_global_norm(tree, max_norm: float, norm=None):
    """Scale the tree so its global L2 norm is <= max_norm
    (reference: runtime/utils.py clip_grad_norm_ semantics)."""
    if norm is None:
        norm = global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda x: x * scale.astype(x.dtype), tree), norm


def weight_norm(tree) -> jnp.ndarray:
    return global_norm(tree)


def see_memory_usage(message: str = "", force: bool = False) -> str:
    """Device + host memory snapshot (reference: runtime/utils.py:489-553
    see_memory_usage/memory_status — CUDA allocator stats there, per-device
    ``memory_stats()`` + RSS here)."""
    return memory_status(message)


def memory_status(message: str = "") -> str:
    import jax

    parts = []
    for d in jax.devices()[:8]:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            pass
        if stats:
            used = stats.get("bytes_in_use", 0) / 2 ** 30
            peak = stats.get("peak_bytes_in_use", 0) / 2 ** 30
            lim = stats.get("bytes_limit", 0) / 2 ** 30
            parts.append(f"{d.id}: {used:.2f}/{lim:.2f}GB peak {peak:.2f}")
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    rss_gb = int(line.split()[1]) / 2 ** 20
                    parts.append(f"host RSS {rss_gb:.2f}GB")
                    break
    except OSError:
        pass
    report = (f"MEMORY {message}: " if message else "MEMORY: ") + \
        ("; ".join(parts) if parts else "no stats available")
    from ..utils.logging import log_dist
    log_dist(report, ranks=[0])
    return report
