"""Runtime numeric utilities (norms, clipping).

(reference: deepspeed/runtime/utils.py:154-275 — grad/weight norms with
model-parallel dedup.  Under SPMD-by-sharding there is nothing to dedup:
gradients are unique per logical tensor, so the norms are plain reductions
which XLA fuses into the step.)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def global_norm(tree) -> jnp.ndarray:
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    sq = sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    return jnp.sqrt(sq)


def clip_by_global_norm(tree, max_norm: float, norm=None):
    """Scale the tree so its global L2 norm is <= max_norm
    (reference: runtime/utils.py clip_grad_norm_ semantics)."""
    if norm is None:
        norm = global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda x: x * scale.astype(x.dtype), tree), norm


def weight_norm(tree) -> jnp.ndarray:
    return global_norm(tree)
