"""Runtime numeric utilities (norms, clipping).

(reference: deepspeed/runtime/utils.py:154-275 — grad/weight norms with
model-parallel dedup.  Under SPMD-by-sharding there is nothing to dedup:
gradients are unique per logical tensor, so the norms are plain reductions
which XLA fuses into the step.)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def global_norm(tree) -> jnp.ndarray:
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    sq = sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    return jnp.sqrt(sq)


def clip_by_global_norm(tree, max_norm: float, norm=None):
    """Scale the tree so its global L2 norm is <= max_norm
    (reference: runtime/utils.py clip_grad_norm_ semantics)."""
    if norm is None:
        norm = global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda x: x * scale.astype(x.dtype), tree), norm


def weight_norm(tree) -> jnp.ndarray:
    return global_norm(tree)


def see_memory_usage(message: str = "", force: bool = False) -> str:
    """Device + host memory snapshot (reference: runtime/utils.py:489-553
    see_memory_usage/memory_status — CUDA allocator stats there, per-device
    ``memory_stats()`` + RSS here)."""
    return memory_status(message)


def memory_status(message: str = "") -> str:
    import jax

    parts = []
    for d in jax.devices()[:8]:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            pass
        if stats:
            used = stats.get("bytes_in_use", 0) / 2 ** 30
            peak = stats.get("peak_bytes_in_use", 0) / 2 ** 30
            lim = stats.get("bytes_limit", 0) / 2 ** 30
            parts.append(f"{d.id}: {used:.2f}/{lim:.2f}GB peak {peak:.2f}")
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    rss_gb = int(line.split()[1]) / 2 ** 20
                    parts.append(f"host RSS {rss_gb:.2f}GB")
                    break
    except OSError:
        pass
    report = (f"MEMORY {message}: " if message else "MEMORY: ") + \
        ("; ".join(parts) if parts else "no stats available")
    from ..utils.logging import log_dist
    log_dist(report, ranks=[0])
    return report


class PartitionedTensor:
    """A tensor uniformly partitioned along a named mesh axis, with the
    reference's meta encoding (reference: runtime/utils.py:379-482 —
    used by the pipeline engine to ship tensor-parallel activations as
    per-rank slices and reconstruct with an all-gather).

    Inside ``shard_map`` over ``axis_name``, ``local_data`` is this
    shard's flat slice and ``full()`` reconstructs the original tensor
    with one ``all_gather``.  The meta vector follows the reference's
    field order (``[ndims, *shape, num_parts, rank, 0, *cumparts]``) but
    the partitioning itself is equal-ceil slices (padded), NOT the
    reference's base+remainder split — static slice shapes are what make
    the single fused all_gather possible; ``from_meta`` validates the
    layout so mixed-layout interop fails loudly rather than corrupting.
    """

    @staticmethod
    def _row_ptr(numel: int, parts: int):
        # equal ceil-sized slices (padded) — static shapes for the gather;
        # the rowptr is clamped to numel so meta matches the logical tensor
        per = -(-numel // parts)
        return [min(i * per, numel) for i in range(parts + 1)], per

    def __init__(self, tensor, axis_name: str, _local=None, _shape=None):
        self.axis_name = axis_name
        self.num_parts = jax.lax.axis_size(axis_name)
        self.rank = jax.lax.axis_index(axis_name)
        if _local is not None:
            self.local_data, self.orig_shape = _local, tuple(_shape)
            self.partition, _ = self._row_ptr(
                int(np.prod(self.orig_shape)), self.num_parts)
            return
        self.orig_shape = tuple(tensor.shape)
        numel = int(np.prod(self.orig_shape))
        self.partition, per = self._row_ptr(numel, self.num_parts)
        flat = jnp.pad(tensor.reshape(-1),
                       (0, per * self.num_parts - numel))
        self.local_data = jax.lax.dynamic_slice_in_dim(
            flat, self.rank * per, per)

    def to_meta(self) -> np.ndarray:
        """Meta vector in the reference's encoding (int32):
        ``[ndims, *shape, num_parts, rank, 0, *row_ptr[1:]]``.

        Returns CONCRETE numpy even under jit — every field is static at
        trace time (shapes, axis size, row pointers); the rank slot is -1
        because the receiver's own ``axis_index`` is the authoritative
        rank (the reference's assert rank==meta[1] compares pipe peers at
        the same coordinate, runtime/utils.py:411 there)."""
        shape = list(self.orig_shape)
        return np.asarray(
            [len(shape)] + shape + [self.num_parts, -1, 0]
            + list(self.partition)[1:], np.int32)

    @classmethod
    def from_meta(cls, meta, local_part, axis_name: str):
        meta = np.asarray(meta)
        nd = int(meta[0])
        shape = tuple(int(x) for x in meta[1:1 + nd])
        num_parts = int(meta[1 + nd])
        obj = cls(None, axis_name, _local=local_part, _shape=shape)
        if num_parts != obj.num_parts:
            raise ValueError(
                f"meta was produced over {num_parts} parts but axis "
                f"{axis_name!r} has {obj.num_parts}")
        _, per = obj._row_ptr(int(np.prod(shape)), obj.num_parts)
        if int(local_part.shape[0]) != per:
            raise ValueError(
                f"local slice has {local_part.shape[0]} elements; layout "
                f"expects {per}")
        return obj

    def full_size(self):
        return self.orig_shape

    def full(self) -> jnp.ndarray:
        flat = jax.lax.all_gather(self.local_data, self.axis_name,
                                  tiled=True)
        numel = int(np.prod(self.orig_shape))
        return flat[:numel].reshape(self.orig_shape)
