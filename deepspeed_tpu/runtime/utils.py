"""Runtime numeric utilities (norms, clipping).

(reference: deepspeed/runtime/utils.py:154-275 — grad/weight norms with
model-parallel dedup.  Under SPMD-by-sharding there is nothing to dedup:
gradients are unique per logical tensor, so the norms are plain reductions
which XLA fuses into the step.)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def global_norm(tree) -> jnp.ndarray:
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    sq = sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    return jnp.sqrt(sq)


def clip_by_global_norm(tree, max_norm: float, norm=None):
    """Scale the tree so its global L2 norm is <= max_norm
    (reference: runtime/utils.py clip_grad_norm_ semantics)."""
    if norm is None:
        norm = global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda x: x * scale.astype(x.dtype), tree), norm


def weight_norm(tree) -> jnp.ndarray:
    return global_norm(tree)


def see_memory_usage(message: str = "", force: bool = False) -> str:
    """Device + host memory snapshot (reference: runtime/utils.py:489-553
    see_memory_usage/memory_status — CUDA allocator stats there, per-device
    ``memory_stats()`` + RSS here)."""
    return memory_status(message)


def collect_memory_stats() -> dict:
    """Structured device + host memory snapshot — the ONE collection
    path shared by the ``memory_status`` log line and the telemetry
    gauges (``telemetry.memory.MemorySampler``), so neither re-parses
    the other's formatting.

    Returns ``{"devices": [{"id", "platform", "bytes_in_use",
    "peak_bytes_in_use", "bytes_limit"}, ...], "host_rss_bytes": int |
    None}``.  Reads PJRT allocator bookkeeping (``memory_stats()``) and
    ``/proc/self/status`` only — never drains the device, so it is safe
    to call at the engine's sync cadence without adding a sync."""
    import jax

    devices = []
    for d in jax.local_devices():
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # backend without allocator stats (CPU)
            pass
        if stats:
            devices.append({
                "id": d.id,
                "platform": getattr(d, "platform", None),
                "bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
            })
    rss = None
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    rss = int(line.split()[1]) * 1024
                    break
    except OSError:
        pass
    return {"devices": devices, "host_rss_bytes": rss}


def format_memory_status(stats: dict, message: str = "") -> str:
    """Render ``collect_memory_stats()`` output the way ``memory_status``
    always has (first 8 devices, GiB with peaks, host RSS)."""
    parts = []
    for dev in stats.get("devices", [])[:8]:
        used = (dev.get("bytes_in_use") or 0) / 2 ** 30
        peak = (dev.get("peak_bytes_in_use") or 0) / 2 ** 30
        lim = (dev.get("bytes_limit") or 0) / 2 ** 30
        parts.append(f"{dev['id']}: {used:.2f}/{lim:.2f}GB peak {peak:.2f}")
    rss = stats.get("host_rss_bytes")
    if rss is not None:
        parts.append(f"host RSS {rss / 2 ** 30:.2f}GB")
    return (f"MEMORY {message}: " if message else "MEMORY: ") + \
        ("; ".join(parts) if parts else "no stats available")


def memory_status(message: str = "") -> str:
    report = format_memory_status(collect_memory_stats(), message)
    from ..utils.logging import log_dist
    log_dist(report, ranks=[0])
    return report


class PartitionedTensor:
    """A tensor uniformly partitioned along a named mesh axis, with the
    reference's meta encoding (reference: runtime/utils.py:379-482 —
    used by the pipeline engine to ship tensor-parallel activations as
    per-rank slices and reconstruct with an all-gather).

    Inside ``shard_map`` over ``axis_name``, ``local_data`` is this
    shard's flat slice and ``full()`` reconstructs the original tensor
    with one ``all_gather``.  The meta vector follows the reference's
    field order (``[ndims, *shape, num_parts, rank, 0, *cumparts]``) but
    the partitioning itself is equal-ceil slices (padded), NOT the
    reference's base+remainder split — static slice shapes are what make
    the single fused all_gather possible; ``from_meta`` validates the
    layout so mixed-layout interop fails loudly rather than corrupting.
    """

    @staticmethod
    def _row_ptr(numel: int, parts: int):
        # equal ceil-sized slices (padded) — static shapes for the gather;
        # the rowptr is clamped to numel so meta matches the logical tensor
        per = -(-numel // parts)
        return [min(i * per, numel) for i in range(parts + 1)], per

    def __init__(self, tensor, axis_name: str, _local=None, _shape=None):
        self.axis_name = axis_name
        self.num_parts = jax.lax.axis_size(axis_name)
        self.rank = jax.lax.axis_index(axis_name)
        if _local is not None:
            self.local_data, self.orig_shape = _local, tuple(_shape)
            self.partition, _ = self._row_ptr(
                int(np.prod(self.orig_shape)), self.num_parts)
            return
        self.orig_shape = tuple(tensor.shape)
        numel = int(np.prod(self.orig_shape))
        self.partition, per = self._row_ptr(numel, self.num_parts)
        flat = jnp.pad(tensor.reshape(-1),
                       (0, per * self.num_parts - numel))
        self.local_data = jax.lax.dynamic_slice_in_dim(
            flat, self.rank * per, per)

    def to_meta(self) -> np.ndarray:
        """Meta vector in the reference's encoding (int32):
        ``[ndims, *shape, num_parts, rank, 0, *row_ptr[1:]]``.

        Returns CONCRETE numpy even under jit — every field is static at
        trace time (shapes, axis size, row pointers); the rank slot is -1
        because the receiver's own ``axis_index`` is the authoritative
        rank (the reference's assert rank==meta[1] compares pipe peers at
        the same coordinate, runtime/utils.py:411 there)."""
        shape = list(self.orig_shape)
        return np.asarray(
            [len(shape)] + shape + [self.num_parts, -1, 0]
            + list(self.partition)[1:], np.int32)

    @classmethod
    def from_meta(cls, meta, local_part, axis_name: str):
        meta = np.asarray(meta)
        nd = int(meta[0])
        shape = tuple(int(x) for x in meta[1:1 + nd])
        num_parts = int(meta[1 + nd])
        obj = cls(None, axis_name, _local=local_part, _shape=shape)
        if num_parts != obj.num_parts:
            raise ValueError(
                f"meta was produced over {num_parts} parts but axis "
                f"{axis_name!r} has {obj.num_parts}")
        _, per = obj._row_ptr(int(np.prod(shape)), obj.num_parts)
        if int(local_part.shape[0]) != per:
            raise ValueError(
                f"local slice has {local_part.shape[0]} elements; layout "
                f"expects {per}")
        return obj

    def full_size(self):
        return self.orig_shape

    def full(self) -> jnp.ndarray:
        flat = jax.lax.all_gather(self.local_data, self.axis_name,
                                  tiled=True)
        numel = int(np.prod(self.orig_shape))
        return flat[:numel].reshape(self.orig_shape)
