"""LR schedules as pure functions of the (traced) step counter.

Behavioral ports of the reference schedules
(reference: deepspeed/runtime/lr_schedules.py — LRRangeTest:301,
OneCycle:401, WarmupLR:645, WarmupDecayLR:722), re-expressed as
``step -> lr`` callables that compose with the fused optimizers and trace
cleanly under jit (jnp ops only, no Python branching on step).

Engine resolution mirrors the reference (engine.py:426-441): a scheduler
name + params from the config block, instantiated via ``get_lr_schedule``.
"""
from __future__ import annotations

import argparse
from typing import Callable

import jax.numpy as jnp

LR_SCHEDULE = "lr_schedule"
LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False) -> Schedule:
    """lr = min_lr * (1 + step_rate * step/step_size), optionally staircased."""
    def sched(step):
        s = step.astype(jnp.float32)
        ratio = s / lr_range_test_step_size
        if lr_range_test_staircase:
            ratio = jnp.floor(ratio)
        return lr_range_test_min_lr * (1.0 + lr_range_test_step_rate * ratio)
    return sched


def one_cycle(cycle_min_lr: float = 0.0,
              cycle_max_lr: float = 1e-2,
              cycle_first_step_size: int = 2000,
              cycle_second_step_size: int = None,
              cycle_first_stair_count: int = 0,
              cycle_second_stair_count: int = None,
              decay_step_size: int = 0,
              decay_lr_rate: float = 0.0,
              **_ignored_momentum_kwargs) -> Schedule:
    """Triangular one-cycle: min→max over the first phase, max→min over the
    second, then per-``decay_step_size`` decay by ``decay_lr_rate``.

    Momentum cycling kwargs are accepted for config parity but applied at the
    optimizer level only when the optimizer supports a beta schedule.
    """
    second = (cycle_second_step_size if cycle_second_step_size is not None
              else cycle_first_step_size)
    cycle_len = cycle_first_step_size + second

    def sched(step):
        s = step.astype(jnp.float32)
        in_cycle = s < cycle_len
        up = jnp.minimum(s, cycle_first_step_size) / cycle_first_step_size
        down = jnp.clip((s - cycle_first_step_size) / second, 0.0, 1.0)
        tri = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * (up - down)
        # post-cycle decay
        post = jnp.maximum(s - cycle_len, 0.0)
        if decay_step_size > 0:
            decays = jnp.floor(post / decay_step_size)
        else:
            decays = post
        decayed = cycle_min_lr / (1.0 + decay_lr_rate * decays)
        return jnp.where(in_cycle, tri, decayed)
    return sched


def warmup_lr(warmup_min_lr: float = 0.0,
              warmup_max_lr: float = 1e-3,
              warmup_num_steps: int = 1000,
              warmup_type: str = "log") -> Schedule:
    """min→max over warmup (log or linear interpolation), then constant.
    The reference's default is log warmup with interpolation factor
    log(1+step)/log(1+warmup_num_steps) (lr_schedules.py:645 there)."""
    def sched(step):
        s = step.astype(jnp.float32)
        if warmup_type == "log":
            frac = jnp.log1p(s) / jnp.log1p(float(warmup_num_steps))
        else:
            frac = s / max(warmup_num_steps, 1)
        frac = jnp.clip(frac, 0.0, 1.0)
        lr = warmup_min_lr + (warmup_max_lr - warmup_min_lr) * frac
        return jnp.where(s >= warmup_num_steps, warmup_max_lr, lr)
    return sched


def warmup_decay_lr(total_num_steps: int,
                    warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 1e-3,
                    warmup_num_steps: int = 1000,
                    warmup_type: str = "log") -> Schedule:
    """Warmup then linear decay to zero at ``total_num_steps``."""
    base = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def sched(step):
        s = step.astype(jnp.float32)
        decay = jnp.clip(
            (total_num_steps - s) /
            max(total_num_steps - warmup_num_steps, 1), 0.0, 1.0)
        return jnp.where(s <= warmup_num_steps, base(step),
                         warmup_max_lr * decay)
    return sched


_REGISTRY = {
    LR_RANGE_TEST: lr_range_test,
    ONE_CYCLE: one_cycle,
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
}


def get_lr_schedule(name: str, params: dict) -> Schedule:
    if name not in _REGISTRY:
        raise ValueError(
            f"Unknown lr schedule {name!r}; valid: {VALID_LR_SCHEDULES}")
    return _REGISTRY[name](**params)


def _str2bool(v) -> bool:
    """argparse bool that honors 'false'/'0' (plain ``type=bool`` would
    parse any non-empty string — including 'False' — as True)."""
    if isinstance(v, bool):
        return v
    if v.lower() in ("true", "t", "yes", "y", "1"):
        return True
    if v.lower() in ("false", "f", "no", "n", "0"):
        return False
    raise argparse.ArgumentTypeError(f"expected a boolean, got {v!r}")


def add_tuning_arguments(parser: argparse.ArgumentParser):
    """Convergence-tuning CLI flags (reference: lr_schedules.py:54-152).

    Same flag names and defaults so existing launch scripts keep working;
    ``override_lr_schedule_config`` turns the parsed namespace back into a
    scheduler config block.
    """
    group = parser.add_argument_group(
        "Convergence Tuning", "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help="LR schedule for training.")
    # Learning rate range test
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001,
                       help="Starting lr value.")
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0,
                       help="scaling rate for LR range test.")
    group.add_argument("--lr_range_test_step_size", type=int, default=1000,
                       help="training steps per LR change.")
    group.add_argument("--lr_range_test_staircase", type=_str2bool,
                       default=False,
                       help="use staircase scaling for LR range test.")
    # OneCycle phase sizes
    group.add_argument("--cycle_first_step_size", type=int, default=1000,
                       help="size of first step of 1Cycle schedule.")
    group.add_argument("--cycle_first_stair_count", type=int, default=-1,
                       help="first stair count for 1Cycle schedule.")
    group.add_argument("--cycle_second_step_size", type=int, default=-1,
                       help="size of second step (default first_step_size).")
    group.add_argument("--cycle_second_stair_count", type=int, default=-1,
                       help="second stair count for 1Cycle schedule.")
    group.add_argument("--decay_step_size", type=int, default=1000,
                       help="intervals for applying post-cycle decay.")
    # OneCycle LR
    group.add_argument("--cycle_min_lr", type=float, default=0.01,
                       help="1Cycle LR lower bound.")
    group.add_argument("--cycle_max_lr", type=float, default=0.1,
                       help="1Cycle LR upper bound.")
    group.add_argument("--decay_lr_rate", type=float, default=0.0,
                       help="post cycle LR decay rate.")
    # OneCycle momentum
    group.add_argument("--cycle_momentum", default=False,
                       action="store_true",
                       help="Enable 1Cycle momentum schedule.")
    group.add_argument("--cycle_min_mom", type=float, default=0.8,
                       help="1Cycle momentum lower bound.")
    group.add_argument("--cycle_max_mom", type=float, default=0.9,
                       help="1Cycle momentum upper bound.")
    group.add_argument("--decay_mom_rate", type=float, default=0.0,
                       help="post cycle momentum decay rate.")
    # Warmup
    group.add_argument("--warmup_min_lr", type=float, default=0,
                       help="WarmupLR minimum/initial LR value")
    group.add_argument("--warmup_max_lr", type=float, default=0.001,
                       help="WarmupLR maximum LR value.")
    group.add_argument("--warmup_num_steps", type=int, default=1000,
                       help="WarmupLR step count for LR warmup.")
    group.add_argument("--total_num_steps", type=int, default=None,
                       help="WarmupDecayLR total training step count "
                            "(decay reaches zero here).")
    return parser


def parse_arguments():
    """Parse only the tuning flags (reference: lr_schedules.py:155-160)."""
    parser = argparse.ArgumentParser()
    parser = add_tuning_arguments(parser)
    return parser.parse_known_args()


def schedule_params_from_args(args) -> dict | None:
    """Turn a parsed tuning namespace into a ``scheduler`` config block
    (the reference consumes these flags through its config override path,
    lr_schedules.py:163-216).  Returns None when --lr_schedule is unset."""
    name = getattr(args, "lr_schedule", None)
    if not name:
        return None
    prefixes = {
        LR_RANGE_TEST: ("lr_range_test_",),
        ONE_CYCLE: ("cycle_", "decay_"),
        WARMUP_LR: ("warmup_",),
        WARMUP_DECAY_LR: ("warmup_", "total_num_steps"),
    }
    if name not in prefixes:
        raise ValueError(
            f"Unknown lr schedule {name!r}; valid: {VALID_LR_SCHEDULES}")
    params = {}
    for key, val in vars(args).items():
        if val is None or key == LR_SCHEDULE:
            continue
        if any(key.startswith(p) for p in prefixes[name]):
            # argparse's -1 sentinels mean "unset" in the reference
            if isinstance(val, int) and val == -1:
                continue
            params[key] = val
    if name == WARMUP_DECAY_LR and "total_num_steps" not in params:
        raise ValueError(
            "--lr_schedule WarmupDecayLR requires --total_num_steps")
    return {"type": name, "params": params}
