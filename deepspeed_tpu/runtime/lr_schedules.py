"""LR schedules as pure functions of the (traced) step counter.

Behavioral ports of the reference schedules
(reference: deepspeed/runtime/lr_schedules.py — LRRangeTest:301,
OneCycle:401, WarmupLR:645, WarmupDecayLR:722), re-expressed as
``step -> lr`` callables that compose with the fused optimizers and trace
cleanly under jit (jnp ops only, no Python branching on step).

Engine resolution mirrors the reference (engine.py:426-441): a scheduler
name + params from the config block, instantiated via ``get_lr_schedule``.
"""
from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False) -> Schedule:
    """lr = min_lr * (1 + step_rate * step/step_size), optionally staircased."""
    def sched(step):
        s = step.astype(jnp.float32)
        ratio = s / lr_range_test_step_size
        if lr_range_test_staircase:
            ratio = jnp.floor(ratio)
        return lr_range_test_min_lr * (1.0 + lr_range_test_step_rate * ratio)
    return sched


def one_cycle(cycle_min_lr: float = 0.0,
              cycle_max_lr: float = 1e-2,
              cycle_first_step_size: int = 2000,
              cycle_second_step_size: int = None,
              cycle_first_stair_count: int = 0,
              cycle_second_stair_count: int = None,
              decay_step_size: int = 0,
              decay_lr_rate: float = 0.0,
              **_ignored_momentum_kwargs) -> Schedule:
    """Triangular one-cycle: min→max over the first phase, max→min over the
    second, then per-``decay_step_size`` decay by ``decay_lr_rate``.

    Momentum cycling kwargs are accepted for config parity but applied at the
    optimizer level only when the optimizer supports a beta schedule.
    """
    second = (cycle_second_step_size if cycle_second_step_size is not None
              else cycle_first_step_size)
    cycle_len = cycle_first_step_size + second

    def sched(step):
        s = step.astype(jnp.float32)
        in_cycle = s < cycle_len
        up = jnp.minimum(s, cycle_first_step_size) / cycle_first_step_size
        down = jnp.clip((s - cycle_first_step_size) / second, 0.0, 1.0)
        tri = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * (up - down)
        # post-cycle decay
        post = jnp.maximum(s - cycle_len, 0.0)
        if decay_step_size > 0:
            decays = jnp.floor(post / decay_step_size)
        else:
            decays = post
        decayed = cycle_min_lr / (1.0 + decay_lr_rate * decays)
        return jnp.where(in_cycle, tri, decayed)
    return sched


def warmup_lr(warmup_min_lr: float = 0.0,
              warmup_max_lr: float = 1e-3,
              warmup_num_steps: int = 1000,
              warmup_type: str = "log") -> Schedule:
    """min→max over warmup (log or linear interpolation), then constant.
    The reference's default is log warmup with interpolation factor
    log(1+step)/log(1+warmup_num_steps) (lr_schedules.py:645 there)."""
    def sched(step):
        s = step.astype(jnp.float32)
        if warmup_type == "log":
            frac = jnp.log1p(s) / jnp.log1p(float(warmup_num_steps))
        else:
            frac = s / max(warmup_num_steps, 1)
        frac = jnp.clip(frac, 0.0, 1.0)
        lr = warmup_min_lr + (warmup_max_lr - warmup_min_lr) * frac
        return jnp.where(s >= warmup_num_steps, warmup_max_lr, lr)
    return sched


def warmup_decay_lr(total_num_steps: int,
                    warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 1e-3,
                    warmup_num_steps: int = 1000,
                    warmup_type: str = "log") -> Schedule:
    """Warmup then linear decay to zero at ``total_num_steps``."""
    base = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def sched(step):
        s = step.astype(jnp.float32)
        decay = jnp.clip(
            (total_num_steps - s) /
            max(total_num_steps - warmup_num_steps, 1), 0.0, 1.0)
        return jnp.where(s <= warmup_num_steps, base(step),
                         warmup_max_lr * decay)
    return sched


_REGISTRY = {
    LR_RANGE_TEST: lr_range_test,
    ONE_CYCLE: one_cycle,
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
}


def get_lr_schedule(name: str, params: dict) -> Schedule:
    if name not in _REGISTRY:
        raise ValueError(
            f"Unknown lr schedule {name!r}; valid: {VALID_LR_SCHEDULES}")
    return _REGISTRY[name](**params)
