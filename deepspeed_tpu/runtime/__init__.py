from .engine import DeepSpeedEngine, TrainState, StepMetrics
from .module import TrainModule, FunctionalModule, FlaxModule
from .dataloader import DeepSpeedDataLoader, RepeatingLoader
from .zero import ZeroShardingPlan
from . import precision, lr_schedules
