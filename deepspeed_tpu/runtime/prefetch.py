"""Asynchronous input pipeline — prefetched host collate + device placement.

The engine's step loop is sync-free on the device side (``train_batch``
dispatches and returns), but every step still paid host-side work
serially BEFORE dispatch: ``next(data_iter)`` → collate →
``_shard_batch`` (reshape + ``jax.device_put``) all ran on the caller's
thread while the devices sat idle waiting for the next program's
arguments.  ``DevicePrefetcher`` moves that whole chain off the hot
path: ONE stage worker pulls batches ahead of consumption through a
bounded channel (default depth 2 — double buffering), runs the collate
and device placement there, and the step loop receives already
device-resident sharded pytrees.  Built on the shared async-stage
runtime (``runtime/stages.py``, docs/stages.md): the worker, bounded
queue, poison propagation, failure budget, and fault-injection plane
are the same primitives the offload and checkpoint stages use.

Contracts:

  - the worker drains each placed batch with ``jax.block_until_ready``
    INSIDE its ``data/prefetch_place`` span, so a queued batch is
    actually device-resident (not merely dispatched — the JL006 bug
    class) and an async transfer failure poisons the iterator instead
    of escaping into the consuming step;
  - ``StopIteration`` from the source propagates cleanly at the epoch
    boundary AFTER every already-produced batch is consumed, and the
    iterator stays exhausted (a persistent training iterator must not
    resurrect);
  - any non-transient worker failure poisons the channel: the consumer
    re-raises the ORIGINAL exception (again on every later ``next``),
    after first draining batches produced before the failure;
  - TRANSIENT failures (``OSError`` — the stage runtime's retryable
    class, which includes injected ``DS_STAGE_FAULT`` faults) are
    retried against the same drawn batch up to the stage's failure
    budget; exhausting it DEGRADES the stage (one loud warning +
    ``stage_degraded_total``): the worker hands the source to the
    consumer and iteration continues INLINE — every batch still
    arrives, in order, outside the injection plane;
  - ``close()`` is idempotent and releases the worker (the engine's
    ``close()`` drains it via the stage graph); a closed prefetcher
    refuses further pulls.

Knobs: the ``data_prefetch`` config block (enabled/depth; default ON),
``DS_PREFETCH=0`` — the no-config escape hatch back to inline
collate+placement, and the unified chaos spec (docs/stages.md):
``DS_STAGE_FAULT=prefetch:place:n[+]`` injects placement faults,
``DS_STAGE_DELAY_S=prefetch:sec`` (alias: the legacy
``DS_PREFETCH_DELAY_S``) sleeps inside each placement span, emulating
a slow collate/H2D link so a CPU-only run can prove the overlap from
tracer timestamps (``tests/test_prefetch.py``).

Sample-exact resume (docs/elastic.md): when the source is a
checkpointable loader (``state_dict``/``load_state_dict``), the worker
captures the source's state right after producing each batch and the
queue carries it alongside; ``state_dict()`` returns the state
belonging to the last CONSUMED batch, so batches sitting prefetched in
the queue (produced, not yet consumed) are accounted as not-yet-drawn
— a resume from this state re-produces exactly them, no replay, no
skip.  The degraded inline path keeps the same accounting.
"""
from __future__ import annotations

import contextlib
import copy
import threading
import time
from typing import Any, Callable, Optional

import jax

from ..telemetry.tracing import TraceContext
from .stages import Channel, Stage, spawn

__all__ = ["DevicePlacedBatch", "DevicePrefetcher"]


class DevicePlacedBatch:
    """Tag for a batch that has ALREADY been collated and device-placed
    (the prefetcher's product).  The engine detects it and skips its own
    ``_shard_batch``; ``rows`` is the pre-reshape local batch length —
    what a consumption-time leaf overwrite (PLD theta) needs to rebuild
    a leaf for the same placement.  ``kind`` records WHICH placement
    produced it ("train": reshaped+sharded accumulation layout; "eval":
    flat micro-batch) so the consumption sites can reject a batch placed
    for the other path with a descriptive error instead of a deep shape
    failure.  An explicit tag, never sniffed from leaf types: a user
    batch that happens to contain jax Arrays must still go through the
    engine's reshape/validation."""

    __slots__ = ("tree", "rows", "kind", "ctx")

    def __init__(self, tree: Any, rows: Optional[int] = None,
                 kind: str = "train", ctx: Any = None):
        self.tree = tree
        self.rows = rows
        self.kind = kind
        #: causal-trace identity (telemetry.tracing.TraceContext): the
        #: producing worker opens a flow inside its place span; the
        #: consuming step closes it inside its dispatch span, drawing
        #: the producer->consumer arrow in trace.json
        self.ctx = ctx


class _End:
    """Queue sentinel: the source raised StopIteration."""

    __slots__ = ()


_END = _End()


class DevicePrefetcher:
    """Wrap a batch iterator with a single stage worker and a bounded
    channel, pulling batches ahead of consumption.

    ``place_fn(batch)`` runs ON THE WORKER (collate output → device
    placement); it may return a :class:`DevicePlacedBatch` (the engine's
    placement closures do) or a plain pytree.  ``span_fn`` (optional,
    the engine passes ``_tel_span``) receives two host-side spans:
    ``data/prefetch_place`` around each worker-side placement (transfer
    drained inside — see the module docstring) and ``data/prefetch_wait``
    around each consumer-side queue wait — the time the step actually
    blocked on input, the pipeline's "hidden vs. exposed" number
    (steady state ≈ 0 when production hides under the previous step).

    ``stage`` (optional) is the engine's persistent ``prefetch``
    :class:`~.stages.Stage` record, so the failure budget and a
    degradation stick across the prefetchers an engine builds; standalone
    constructions get a private one.

    ``stats()`` exposes cumulative ``hits`` (batch already queued when
    requested), ``misses``, ``wait_s``, and ``consumed`` — the engine
    turns interval deltas into the ``prefetch_hit_ratio`` sync scalar.
    """

    def __init__(self, source, place_fn: Optional[Callable] = None,
                 depth: int = 2, span_fn: Optional[Callable] = None,
                 name: str = "train", stage: Optional[Stage] = None,
                 tracer: Optional[Any] = None):
        if not isinstance(depth, int) or isinstance(depth, bool) \
                or depth < 1:
            raise ValueError(f"prefetch depth must be an int >= 1, "
                             f"got {depth!r}")
        # the stateful OBJECT (loader / RepeatingLoader) when the source
        # is checkpointable: iterating it (below) advances its internal
        # position, which state_dict() reads at the consumption point
        from .dataloader import supports_iter_state
        self._state_src = source if supports_iter_state(source) else None
        # captured BEFORE the worker starts pulling (the thread below
        # mutates the source immediately): the nothing-consumed state
        self._consumed_state = None
        if self._state_src is not None:
            try:
                self._consumed_state = copy.deepcopy(
                    self._state_src.state_dict())
            except TypeError:
                # quacks the protocol but can't honor it (RepeatingLoader
                # over a raw iterable): a stateless source, NOT an error —
                # this configuration trained fine before sample-exact
                # resume existed and must keep doing so
                self._state_src = None
        self._src = source if hasattr(source, "__next__") else iter(source)
        self._place = place_fn if place_fn is not None else (lambda b: b)
        self._span = span_fn if span_fn is not None else (
            lambda *a, **k: contextlib.nullcontext())
        self.depth = depth
        self.name = name
        #: causal tracing (docs/observability.md): a TraceRecorder —
        #: each placed batch gets a TraceContext + a flow opened inside
        #: its place span; the engine closes it in the consuming step
        self._tracer = tracer
        self.stage = stage if stage is not None else Stage("prefetch")
        self._chan = Channel(depth)
        # flight recorder: this prefetcher's queue depth rides every
        # stage event (a shared train/eval stage samples the
        # last-constructed prefetcher's channel — close enough for a
        # post-mortem trajectory)
        self.stage.depth_fn = self.qsize
        self._ended = False
        # degraded hand-off: the worker stopped and the source belongs
        # to the consumer now (inline iteration); serialized by this lock
        self._worker_inline = False
        self._inline_lock = threading.Lock()
        # cumulative stats (guarded by the channel's lock)
        self._hits = 0
        self._misses = 0
        self._wait_s = 0.0
        self._consumed = 0
        # restarts=0 like every other subsystem worker: _work is not
        # reentrant (a restart would re-draw and silently drop the
        # in-flight batch); an escaping exception takes the poison path
        self._worker = spawn(self._work,
                             name=f"ds-data-prefetch-{name}", restarts=0)

    # -- the worker -----------------------------------------------------
    def _open_flow(self, placed):
        """Stamp a freshly placed batch with a TraceContext and open its
        causal flow — called INSIDE the ``data/prefetch_place`` span so
        the arrow's tail binds to it.  Host-side appends only (the
        zero-added-device-syncs contract)."""
        if self._tracer is not None \
                and isinstance(placed, DevicePlacedBatch):
            placed.ctx = TraceContext.new()
            self._tracer.flow_start("data/batch", placed.ctx, cat="data")
        return placed

    def _place_and_drain(self, item):
        placed = self._place(item)
        # drain INSIDE the span: device_put only dispatches, so without
        # this a queued batch would not actually be resident (the JL006
        # dispatch-only class) and an async transfer failure would
        # surface in the consuming step instead of the poison path
        tree = (placed.tree if isinstance(placed, DevicePlacedBatch)
                else placed)
        jax.block_until_ready(tree)
        return placed

    def _work(self):
        # anything escaping the produce loop (channel-op failure — the
        # draw/place sites poison for themselves below) must poison too:
        # with restarts=0 a silently dead worker would strand consumers
        # waiting on the channel forever
        try:
            self._produce()
        except BaseException as e:
            self._chan.poison(e)
            raise

    def _produce(self):
        batch_idx = 0
        while True:
            if not self._chan.wait_space():
                return  # closed
            if self.stage.degraded:
                # budget exhausted: hand the source to the consumer —
                # iteration continues INLINE (docs/stages.md)
                with self._chan.cond:
                    self._worker_inline = True
                    self._chan.cond.notify_all()
                return
            try:
                item = next(self._src)
                # source position AFTER drawing this batch: rides the
                # queue so the consumer can mark it consumed (a failure
                # here is a real loader bug — poison, same as next())
                post_state = (copy.deepcopy(self._state_src.state_dict())
                              if self._state_src is not None else None)
            except StopIteration:
                self._chan.put((_END, None), force=True)  # after every batch
                return
            except BaseException as e:  # poison: consumer re-raises it
                self._chan.poison(e)
                return
            try:
                with self._span("data/prefetch_place", cat="data",
                                batch=batch_idx):
                    # the stage boundary: injected delay/fault, transient
                    # retry against the SAME drawn batch (sample order is
                    # preserved), degradation on budget exhaustion
                    placed = self.stage.call(
                        "place", lambda: self._place_and_drain(item))
                    placed = self._open_flow(placed)
            except BaseException as e:
                self._chan.poison(e)
                return
            batch_idx += 1
            if not self._chan.put((placed, post_state)):
                return  # closed while parked: consumers already released

    # -- the consumer side ----------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter()
        with self._span("data/prefetch_wait", cat="data"):
            with self._chan.cond:
                # exhausted BEFORE closed: consuming the epoch-end
                # sentinel self-closes below (the worker has already
                # exited), and an exhausted iterator must keep raising
                # StopIteration, not a closed error
                if self._ended:
                    raise StopIteration
                if self._chan.closed:
                    raise RuntimeError(
                        "DevicePrefetcher is closed (engine.close() shut "
                        "it down)")
                hit = bool(self._chan.items)
                self._chan.cond.wait_for(
                    lambda: self._chan.items or self._chan.err is not None
                    or self._chan.closed or self._worker_inline)
                if self._chan.closed:
                    raise RuntimeError(
                        "DevicePrefetcher closed while waiting for a "
                        "batch")
                if self._chan.items:
                    # batches produced before an end/failure/degradation
                    # drain first
                    item, post_state = self._chan.items.pop(0)
                    self._chan.cond.notify_all()  # a slot freed
                    if isinstance(item, _End):
                        # the worker already exited; self-close so an
                        # exhausted prefetcher counts as drained (the
                        # engine prunes closed ones from its list)
                        self._ended = True
                        self._chan.closed = True
                        raise StopIteration
                    if post_state is not None:
                        # this batch is now CONSUMED: the resume point
                        # advances past it
                        self._consumed_state = post_state
                    self._hits += 1 if hit else 0
                    self._misses += 0 if hit else 1
                    self._wait_s += time.perf_counter() - t0
                    self._consumed += 1
                    return item
                if self._chan.err is not None:
                    # queue empty, worker dead: the original error
                    raise self._chan.err
            # queue empty and the worker handed the source over
            return self._next_inline(t0)

    def _next_inline(self, t0: float):
        """Degraded mode: the async stage is gone; pull, place, and
        drain on the consumer's thread — the inline-iteration fallback,
        OUTSIDE the injection plane (same batches, same order, same
        resume accounting)."""
        with self._inline_lock:
            with self._chan.cond:
                if self._ended:
                    raise StopIteration
                if self._chan.err is not None:
                    # same poison contract as the async path: a prior
                    # inline failure re-raises on every later next — a
                    # retrying caller must not silently skip the batch
                    # the failure consumed
                    raise self._chan.err
                if self._chan.closed:
                    raise RuntimeError(
                        "DevicePrefetcher is closed (engine.close() shut "
                        "it down)")
            try:
                item = next(self._src)
                post_state = (copy.deepcopy(self._state_src.state_dict())
                              if self._state_src is not None else None)
            except StopIteration:
                with self._chan.cond:
                    self._ended = True
                    self._chan.closed = True
                raise
            except BaseException as e:
                self._chan.poison(e)
                raise
            try:
                # same span name as the async path: a degraded run's
                # trace stays readable with the same queries
                with self._span("data/prefetch_place", cat="data",
                                inline=True):
                    placed = self._place_and_drain(item)
                    placed = self._open_flow(placed)
            except BaseException as e:
                self._chan.poison(e)
                raise
            with self._chan.cond:
                if post_state is not None:
                    self._consumed_state = post_state
                self._misses += 1
                self._wait_s += time.perf_counter() - t0
                self._consumed += 1
            return placed

    # -- introspection ---------------------------------------------------
    def qsize(self) -> int:
        """Batches ready for consumption right now (the queue-depth
        gauge; the epoch-end sentinel does not count)."""
        with self._chan.cond:
            return len([x for x, _ in self._chan.items
                        if not isinstance(x, _End)])

    # -- sample-exact resume ---------------------------------------------
    def state_dict(self) -> dict:
        """The SOURCE loader's state at the consumption point: batches
        already produced into the queue but not yet consumed count as
        not-yet-drawn (a resume from this state re-produces them).
        Raises TypeError when the source is not checkpointable — the
        engine probes support before persisting the data-iterator
        checkpoint plane."""
        if self._state_src is None:
            raise TypeError(
                f"DevicePrefetcher({self.name}): source "
                f"{type(self._src).__name__} has no state_dict/"
                "load_state_dict — sample-exact resume needs a "
                "checkpointable loader (DeepSpeedDataLoader or "
                "RepeatingLoader over one), passed to prefetch() as the "
                "loader object, not a raw iterator")
        with self._chan.cond:
            if self._chan.err is not None:
                raise self._chan.err
            return copy.deepcopy(self._consumed_state)

    def stats(self) -> dict:
        with self._chan.cond:
            return {"hits": self._hits, "misses": self._misses,
                    "wait_s": self._wait_s, "consumed": self._consumed}

    @property
    def closed(self) -> bool:
        return self._chan.closed

    # -- shutdown --------------------------------------------------------
    def close(self):
        """Release the worker and drop queued batches.  Idempotent; a
        parked worker (queue full) would otherwise wait forever holding
        references to ``depth`` device-resident batches.  Also releases
        the shared stage record's depth sampler when it is OURS — the
        bound method would otherwise pin this prefetcher (and its
        source iterator) for the stage's engine-long lifetime, and
        later stage events would sample a dead channel's depth."""
        if self.stage.depth_fn == self.qsize:
            self.stage.depth_fn = None
        self._chan.close()
