"""Asynchronous input pipeline — prefetched host collate + device placement.

The engine's step loop is sync-free on the device side (``train_batch``
dispatches and returns), but every step still paid host-side work
serially BEFORE dispatch: ``next(data_iter)`` → collate →
``_shard_batch`` (reshape + ``jax.device_put``) all ran on the caller's
thread while the devices sat idle waiting for the next program's
arguments.  ``DevicePrefetcher`` moves that whole chain off the hot
path: ONE daemon worker pulls batches ahead of consumption through a
bounded queue (default depth 2 — double buffering), runs the collate
and device placement there, and the step loop receives already
device-resident sharded pytrees.  The input-feeding half of the
ZeRO-Offload overlap story: the same streaming-worker shape as the
optimizer pipeline in ``runtime/offload.py`` (bounded queue,
drain-inside-span, poison-on-failure), applied to the data path —
where remote-platform H2D latency (BENCH_NOTES.md's tunnel round
trips) is entirely hideable behind the previous step's compute.

Contracts:

  - the worker drains each placed batch with ``jax.block_until_ready``
    INSIDE its ``data/prefetch_place`` span, so a queued batch is
    actually device-resident (not merely dispatched — the JL006 bug
    class) and an async transfer failure poisons the iterator instead
    of escaping into the consuming step;
  - ``StopIteration`` from the source propagates cleanly at the epoch
    boundary AFTER every already-produced batch is consumed, and the
    iterator stays exhausted (a persistent training iterator must not
    resurrect);
  - any other worker failure poisons the queue: the consumer re-raises
    the ORIGINAL exception (again on every later ``next``), after
    first draining batches produced before the failure;
  - ``close()`` is idempotent and releases the worker (the engine's
    ``close()`` calls it); a closed prefetcher refuses further pulls.

Knobs: the ``data_prefetch`` config block (enabled/depth; default ON),
``DS_PREFETCH=0`` — the no-config escape hatch back to inline
collate+placement, and ``DS_PREFETCH_DELAY_S`` — fault injection
(tests/bench only): the worker sleeps this long inside each placement
span, emulating a slow collate/H2D link so a CPU-only run can prove
the overlap from tracer timestamps (``tests/test_prefetch.py``).

Sample-exact resume (docs/elastic.md): when the source is a
checkpointable loader (``state_dict``/``load_state_dict``), the worker
captures the source's state right after producing each batch and the
queue carries it alongside; ``state_dict()`` returns the state
belonging to the last CONSUMED batch, so batches sitting prefetched in
the queue (produced, not yet consumed) are accounted as not-yet-drawn
— a resume from this state re-produces exactly them, no replay, no
skip.
"""
from __future__ import annotations

import contextlib
import copy
import os
import threading
import time
from typing import Any, Callable, Optional

import jax

__all__ = ["DevicePlacedBatch", "DevicePrefetcher"]


class DevicePlacedBatch:
    """Tag for a batch that has ALREADY been collated and device-placed
    (the prefetcher's product).  The engine detects it and skips its own
    ``_shard_batch``; ``rows`` is the pre-reshape local batch length —
    what a consumption-time leaf overwrite (PLD theta) needs to rebuild
    a leaf for the same placement.  ``kind`` records WHICH placement
    produced it ("train": reshaped+sharded accumulation layout; "eval":
    flat micro-batch) so the consumption sites can reject a batch placed
    for the other path with a descriptive error instead of a deep shape
    failure.  An explicit tag, never sniffed from leaf types: a user
    batch that happens to contain jax Arrays must still go through the
    engine's reshape/validation."""

    __slots__ = ("tree", "rows", "kind")

    def __init__(self, tree: Any, rows: Optional[int] = None,
                 kind: str = "train"):
        self.tree = tree
        self.rows = rows
        self.kind = kind


class _End:
    """Queue sentinel: the source raised StopIteration."""

    __slots__ = ()


_END = _End()


class DevicePrefetcher:
    """Wrap a batch iterator with a single daemon worker and a bounded
    queue, pulling batches ahead of consumption.

    ``place_fn(batch)`` runs ON THE WORKER (collate output → device
    placement); it may return a :class:`DevicePlacedBatch` (the engine's
    placement closures do) or a plain pytree.  ``span_fn`` (optional,
    the engine passes ``_tel_span``) receives two host-side spans:
    ``data/prefetch_place`` around each worker-side placement (transfer
    drained inside — see the module docstring) and ``data/prefetch_wait``
    around each consumer-side queue wait — the time the step actually
    blocked on input, the pipeline's "hidden vs. exposed" number
    (steady state ≈ 0 when production hides under the previous step).

    ``stats()`` exposes cumulative ``hits`` (batch already queued when
    requested), ``misses``, ``wait_s``, and ``consumed`` — the engine
    turns interval deltas into the ``prefetch_hit_ratio`` sync scalar.
    """

    def __init__(self, source, place_fn: Optional[Callable] = None,
                 depth: int = 2, span_fn: Optional[Callable] = None,
                 name: str = "train"):
        if not isinstance(depth, int) or isinstance(depth, bool) \
                or depth < 1:
            raise ValueError(f"prefetch depth must be an int >= 1, "
                             f"got {depth!r}")
        # the stateful OBJECT (loader / RepeatingLoader) when the source
        # is checkpointable: iterating it (below) advances its internal
        # position, which state_dict() reads at the consumption point
        from .dataloader import supports_iter_state
        self._state_src = source if supports_iter_state(source) else None
        # captured BEFORE the worker starts pulling (the thread below
        # mutates the source immediately): the nothing-consumed state
        self._consumed_state = None
        if self._state_src is not None:
            try:
                self._consumed_state = copy.deepcopy(
                    self._state_src.state_dict())
            except TypeError:
                # quacks the protocol but can't honor it (RepeatingLoader
                # over a raw iterable): a stateless source, NOT an error —
                # this configuration trained fine before sample-exact
                # resume existed and must keep doing so
                self._state_src = None
        self._src = source if hasattr(source, "__next__") else iter(source)
        self._place = place_fn if place_fn is not None else (lambda b: b)
        self._span = span_fn if span_fn is not None else (
            lambda *a, **k: contextlib.nullcontext())
        self.depth = depth
        self.name = name
        self._delay = float(os.environ.get("DS_PREFETCH_DELAY_S", "0"))
        self._cond = threading.Condition()
        self._q: list = []
        self._err: Optional[BaseException] = None
        self._closed = False
        self._ended = False
        # cumulative stats (guarded by _cond's lock)
        self._hits = 0
        self._misses = 0
        self._wait_s = 0.0
        self._consumed = 0
        self._thread = threading.Thread(
            target=self._work, daemon=True,
            name=f"ds-data-prefetch-{name}")
        self._thread.start()

    # -- the worker -----------------------------------------------------
    def _work(self):
        batch_idx = 0
        while True:
            with self._cond:
                self._cond.wait_for(
                    lambda: self._closed or len(self._q) < self.depth)
                if self._closed:
                    return
            try:
                item = next(self._src)
                # source position AFTER drawing this batch: rides the
                # queue so the consumer can mark it consumed (a failure
                # here is a real loader bug — poison, same as next())
                post_state = (copy.deepcopy(self._state_src.state_dict())
                              if self._state_src is not None else None)
            except StopIteration:
                with self._cond:
                    self._q.append((_END, None))  # after every batch
                    self._cond.notify_all()
                return
            except BaseException as e:  # poison: consumer re-raises it
                with self._cond:
                    self._err = e
                    self._cond.notify_all()
                return
            try:
                with self._span("data/prefetch_place", cat="data",
                                batch=batch_idx):
                    if self._delay > 0:
                        time.sleep(self._delay)
                    placed = self._place(item)
                    # drain INSIDE the span: device_put only dispatches,
                    # so without this a queued batch would not actually
                    # be resident (the JL006 dispatch-only class) and an
                    # async transfer failure would surface in the
                    # consuming step instead of the poison path
                    tree = (placed.tree
                            if isinstance(placed, DevicePlacedBatch)
                            else placed)
                    jax.block_until_ready(tree)
            except BaseException as e:
                with self._cond:
                    self._err = e
                    self._cond.notify_all()
                return
            batch_idx += 1
            with self._cond:
                if self._closed:
                    return  # dropped: close() already released consumers
                self._q.append((placed, post_state))
                self._cond.notify_all()

    # -- the consumer side ----------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter()
        with self._span("data/prefetch_wait", cat="data"):
            with self._cond:
                # exhausted BEFORE closed: consuming the epoch-end
                # sentinel self-closes below (the worker has already
                # exited), and an exhausted iterator must keep raising
                # StopIteration, not a closed error
                if self._ended:
                    raise StopIteration
                if self._closed:
                    raise RuntimeError(
                        "DevicePrefetcher is closed (engine.close() shut "
                        "it down)")
                hit = bool(self._q)
                self._cond.wait_for(
                    lambda: self._q or self._err is not None
                    or self._closed)
                if self._closed:
                    raise RuntimeError(
                        "DevicePrefetcher closed while waiting for a "
                        "batch")
                if self._q:
                    # batches produced before an end/failure drain first
                    item, post_state = self._q.pop(0)
                    self._cond.notify_all()  # a slot freed
                    if isinstance(item, _End):
                        # the worker already exited; self-close so an
                        # exhausted prefetcher counts as drained (the
                        # engine prunes closed ones from its list)
                        self._ended = True
                        self._closed = True
                        raise StopIteration
                    if post_state is not None:
                        # this batch is now CONSUMED: the resume point
                        # advances past it
                        self._consumed_state = post_state
                    self._hits += 1 if hit else 0
                    self._misses += 0 if hit else 1
                    self._wait_s += time.perf_counter() - t0
                    self._consumed += 1
                    return item
                # queue empty, worker dead: surface the original error
                raise self._err

    # -- introspection ---------------------------------------------------
    def qsize(self) -> int:
        """Batches ready for consumption right now (the queue-depth
        gauge; the epoch-end sentinel does not count)."""
        with self._cond:
            return len([x for x, _ in self._q if not isinstance(x, _End)])

    # -- sample-exact resume ---------------------------------------------
    def state_dict(self) -> dict:
        """The SOURCE loader's state at the consumption point: batches
        already produced into the queue but not yet consumed count as
        not-yet-drawn (a resume from this state re-produces them).
        Raises TypeError when the source is not checkpointable — the
        engine probes support before persisting the data-iterator
        checkpoint plane."""
        if self._state_src is None:
            raise TypeError(
                f"DevicePrefetcher({self.name}): source "
                f"{type(self._src).__name__} has no state_dict/"
                "load_state_dict — sample-exact resume needs a "
                "checkpointable loader (DeepSpeedDataLoader or "
                "RepeatingLoader over one), passed to prefetch() as the "
                "loader object, not a raw iterator")
        with self._cond:
            if self._err is not None:
                raise self._err
            return copy.deepcopy(self._consumed_state)

    def stats(self) -> dict:
        with self._cond:
            return {"hits": self._hits, "misses": self._misses,
                    "wait_s": self._wait_s, "consumed": self._consumed}

    @property
    def closed(self) -> bool:
        return self._closed

    # -- shutdown --------------------------------------------------------
    def close(self):
        """Release the worker and drop queued batches.  Idempotent; a
        parked worker (queue full) would otherwise wait forever holding
        references to ``depth`` device-resident batches."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._q.clear()
            self._cond.notify_all()
