"""CSR (IndexedSlices-style) sparse tensor for embedding gradients.

The reference converts ``nn.Embedding`` grads to a minimal CSR container
and allreduces them as padded (indices, values) allgathers (reference:
deepspeed/runtime/csr_tensor.py:1-59, engine.py:1153-1209).  The JAX
equivalent: a pytree-registered container over (indices [nnz], values
[nnz, ...]) with dense↔sparse conversion and an SPMD combine that
concatenates row shards via ``all_gather`` inside ``shard_map`` — same
wire format (indices + values, no dense materialization on the wire).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
class CSRTensor:
    """Row-sparse view of a [num_rows, ...] array: ``values[i]`` is the
    dense row at index ``indices[i]``.  Duplicate indices are allowed and
    sum on densify (gradient semantics)."""

    def __init__(self, indices: jnp.ndarray, values: jnp.ndarray,
                 dense_shape: Tuple[int, ...]):
        self.indices = indices
        self.values = values
        self.dense_shape = tuple(dense_shape)

    # -- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        return (self.indices, self.values), self.dense_shape

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    # -- constructors ---------------------------------------------------
    @classmethod
    def from_dense(cls, dense: jnp.ndarray,
                   max_nnz: int = None) -> "CSRTensor":
        """Rows with any nonzero become sparse rows.  ``max_nnz`` fixes the
        static row budget (defaults to all rows — callers that know their
        sparsity should pass the real bound, e.g. tokens-per-batch)."""
        num_rows = dense.shape[0]
        nnz = num_rows if max_nnz is None else max_nnz
        row_used = jnp.any(dense != 0, axis=tuple(range(1, dense.ndim)))
        # stable top-k: indices of used rows first, padded with 0
        order = jnp.argsort(~row_used, stable=True)[:nnz]
        valid = row_used[order]
        indices = jnp.where(valid, order, 0)
        values = dense[order] * valid.reshape(
            (-1,) + (1,) * (dense.ndim - 1)).astype(dense.dtype)
        return cls(indices.astype(jnp.int32), values, dense.shape)

    # -- ops ------------------------------------------------------------
    def to_dense(self) -> jnp.ndarray:
        out = jnp.zeros(self.dense_shape, self.values.dtype)
        return out.at[self.indices].add(self.values)

    @property
    def nnz(self) -> int:
        return int(self.indices.shape[0])

    def sparse_size(self) -> int:
        """Elements stored sparsely (reference csr_tensor.py sparse size
        accounting)."""
        return int(self.indices.size + self.values.size)

    def __repr__(self):
        return (f"CSRTensor(nnz={self.indices.shape[0]}, "
                f"dense_shape={self.dense_shape})")


def csr_allgather(csr: CSRTensor, axis_name: str) -> CSRTensor:
    """Combine row-sparse gradients across a mesh axis by concatenating
    every shard's (indices, values) — the reference's padded allgather
    exchange (engine.py:1166-1204) without the manual padding: shard_map
    shapes are static so the gather is exact.  Duplicate row indices from
    different shards sum on ``to_dense``."""
    idx = jax.lax.all_gather(csr.indices, axis_name)    # [world, nnz]
    vals = jax.lax.all_gather(csr.values, axis_name)    # [world, nnz, ...]
    return CSRTensor(idx.reshape(-1),
                     vals.reshape((-1,) + vals.shape[2:]),
                     csr.dense_shape)


def sparse_embedding_grad(dense_grad: jnp.ndarray,
                          token_ids: jnp.ndarray) -> CSRTensor:
    """Build the CSR gradient of an embedding table from the dense grad
    and the batch's token ids (the rows that can be nonzero).  nnz is the
    number of tokens — static, so this works under jit.

    Repeated tokens: ``dense_grad[row]`` already sums every occurrence, so
    each of the k duplicate entries carries row/k — ``to_dense`` then
    reconstructs exactly ``dense_grad`` instead of k× it."""
    ids = token_ids.reshape(-1).astype(jnp.int32)
    counts = jnp.zeros((dense_grad.shape[0],), jnp.float32).at[ids].add(1.0)
    scale = (1.0 / counts[ids]).astype(dense_grad.dtype)
    values = dense_grad[ids] * scale.reshape(
        (-1,) + (1,) * (dense_grad.ndim - 1))
    return CSRTensor(ids, values, dense_grad.shape)
