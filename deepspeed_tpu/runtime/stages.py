"""One fault plane: the shared async-stage runtime.

PRs 2-6 grew four independent hand-rolled async subsystems —
``DevicePrefetcher`` (prefetch.py), ``StreamingUploader`` and the
offload pull worker (offload.py), and ``AsyncCheckpointWriter``
(resilience.py) — each with its own daemon thread, bounded queue,
poison path, drain ordering, fault-injection env var, and telemetry
wiring.  The half-swapped-tree and writer-drain bugs fixed in the
PR 3/PR 5 review rounds were all instances of the same missing
abstraction.  This module IS that abstraction (docs/stages.md): every
async stage in ``deepspeed_tpu/runtime/`` is built from the primitives
here, so failure semantics are one tested plane instead of four
slightly-different copies.

The primitives:

  ``StageWorker``      the daemon-thread handle (restart-on-crash
                       policy) — the ONLY way runtime code makes a
                       thread (jaxlint JL007 flags raw
                       ``threading.Thread`` in runtime/ outside this
                       file).
  ``Channel``          bounded FIFO with close/poison — the queue every
                       stage pair communicates through.  Poison carries
                       the ORIGINAL exception: downstream consumers
                       fail fast re-raising it, upstream producers stop.
  ``Stage``            the per-subsystem fault record: failure budget,
                       graceful degradation, surfaced post-close errors,
                       and the injection points of the unified fault
                       spec.  ``Stage.call`` wraps one unit of stage
                       work with the whole policy.
  ``WatchdogPool``     per-stage watchdog timeouts with
                       abandon-and-replace (the PR 3 ``_PullWorker``
                       idiom, generalized): one persistent worker
                       serves every guarded call; a timeout abandons
                       the wedged worker and the next call lazily gets
                       a fresh one.
  ``StageGraph``       THE documented drain order.  ``engine.close()``,
                       sync-save, and elastic restart all reduce to one
                       call — prefetch -> offload uploads -> checkpoint
                       writer -> telemetry flush (producers before
                       consumers of durability: batches are droppable,
                       an in-flight save is not).

Graceful degradation: a stage whose work keeps failing with a
TRANSIENT error (``OSError`` — the same class ``resilience.io_retry``
retries; anything else takes the subsystem's existing poison path
unchanged) is retried up to ``stages.max_stage_failures`` (default
3) consecutive times; when the budget is
exhausted the stage falls back to its inline/serial equivalent with ONE
loud warning and a ``stage_degraded_total`` counter instead of killing
the run: prefetch -> inline iteration, streamed offload -> serial
update, async save -> sync save.  A degraded stage bypasses the
injection plane entirely (its fallback is the code path that never had
the async machinery), so a genuinely broken resource still surfaces its
real error.

Fault injection (one chaos harness for every stage boundary):

  ``DS_STAGE_FAULT="<stage>:<point>:<n>[+][,...]"`` — the n-th hit
      (1-based, process-wide) of the named stage point raises an
      injected ``InjectedStageFault`` (an ``OSError``: transient class);
      a trailing ``+`` makes it STICKY (every hit >= n fails).
  ``DS_STAGE_DELAY_S="<stage>:<seconds>[,...]"`` — stage work sleeps
      this long inside its span/timing window (CPU overlap proofs).

  Back-compat aliases (kept and tested): ``DS_CKPT_FAULT=<point>:<n>[+]``
  == stage ``ckpt``; ``DS_PREFETCH_DELAY_S`` == delay of stage
  ``prefetch``; ``DS_OFFLOAD_H2D_DELAY_S`` == delay of stage
  ``offload_h2d``; ``DS_CKPT_DELAY_S`` == delay of stage ``ckpt``.

Stage names and points currently wired: ``prefetch:place``,
``offload_h2d:put``, ``offload_pull:pull``, the disk offload tier's
``disk_read:read`` / ``disk_write:write`` (runtime/disk_offload.py),
``ckpt_writer:job``, the ``ckpt`` write points
(leaf/shard_index/manifest/meta/rename/latest/read) that live inside
``runtime/checkpointing.py``, the serving engine's
``serve:admit`` / ``serve:step`` (deepspeed_tpu/inference/engine.py,
docs/serving.md), the multi-tenant adapter pool's
``adapter_fetch:fetch`` — one cold adapter's host->HBM upload
(deepspeed_tpu/inference/adapters.py, docs/serving.md "multi-tenant
serving"), and the KV tier's ``kv_spill:pageout`` /
``kv_spill:write`` / ``kv_fetch:read`` / ``kv_fetch:pagein`` — park
and resume of idle sessions' KV pages (deepspeed_tpu/inference/
kv_tier.py, docs/serving.md "KV tiering").
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.logging import logger

__all__ = [
    "DEFAULT_MAX_STAGE_FAILURES", "FLIGHT_RING_SIZE", "InjectedStageFault",
    "WorkerAbandoned", "Channel", "Stage", "StageWorker", "StageGraph",
    "WatchdogPool", "fault_point", "injected_delay",
    "reset_fault_injection", "spawn",
]

#: default per-stage consecutive-failure budget before degradation
#: (``stages.max_stage_failures`` in the config block overrides).
DEFAULT_MAX_STAGE_FAILURES = 3

#: base delay between transient retries inside ``Stage.call`` (doubles
#: per consecutive failure, capped at 1s).  Without it one real blip —
#: microseconds long — would burn the whole budget before the condition
#: clears and permanently degrade the stage; with it the budget spans
#: ~0.35s+, the same order as ``checkpoint.io_retry``'s backoff.
RETRY_BACKOFF_BASE_S = 0.05
RETRY_BACKOFF_MAX_S = 1.0

#: per-stage flight-recorder ring length (docs/observability.md): the
#: recent structured events a ``flightrec_<step>.json`` dump preserves
#: for post-mortem — bounded so a multi-day run's recorder costs O(1)
#: memory per stage.
FLIGHT_RING_SIZE = 256


class InjectedStageFault(OSError):
    """The injected transient failure (``DS_STAGE_FAULT``).  An
    ``OSError`` so it rides the same transient class the retry planes
    (``io_retry``, the stage failure budget) already handle."""


class WorkerAbandoned(Exception):
    """Internal to the watchdog plane: a job hit a worker that was
    already stopped (another call timed out and abandoned it).
    ``WatchdogPool.call`` retries once on a fresh worker — this must
    never surface as a user-facing error on a healthy link."""


# ---------------------------------------------------------------------------
# unified fault injection
# ---------------------------------------------------------------------------
_FAULT_ENV = "DS_STAGE_FAULT"
_DELAY_ENV = "DS_STAGE_DELAY_S"
#: legacy per-subsystem delay knobs -> the stage they alias
_DELAY_ALIASES = {
    "prefetch": "DS_PREFETCH_DELAY_S",
    "offload_h2d": "DS_OFFLOAD_H2D_DELAY_S",
    "ckpt": "DS_CKPT_DELAY_S",
}

_fault_lock = threading.Lock()
_fault_hits: Dict[Tuple[str, str], int] = {}
# parsed-spec caches keyed by the raw env strings: the injection plane
# sits on per-leaf hot paths (offload pulls), so it must cost a dict
# lookup when armed and near-nothing when not
_fault_cache: Optional[Tuple[Tuple[str, str], dict]] = None
_delay_cache: Optional[Tuple[tuple, dict]] = None


def _parse_hits(n: str):
    sticky = n.endswith("+")
    if sticky:
        n = n[:-1]
    return int(n), sticky


def _fault_spec() -> dict:
    """{(stage, point): (n, sticky)} from ``DS_STAGE_FAULT`` plus the
    ``DS_CKPT_FAULT`` alias (stage ``ckpt``; unified entries win)."""
    global _fault_cache
    key = (os.environ.get(_FAULT_ENV, ""),
           os.environ.get("DS_CKPT_FAULT", ""))
    if _fault_cache is not None and _fault_cache[0] == key:
        return _fault_cache[1]
    spec: dict = {}
    for part in key[0].split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        try:
            if len(bits) != 3:
                raise ValueError(part)
            spec[(bits[0].strip(), bits[1].strip())] = _parse_hits(
                bits[2].strip())
        except ValueError:
            logger.warning("%s: unparseable spec %r ignored (want "
                           "stage:point:n[+])", _FAULT_ENV, part)
    for part in key[1].split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        point, n = part.split(":", 1)
        try:
            spec.setdefault(("ckpt", point.strip()),
                            _parse_hits(n.strip()))
        except ValueError:
            logger.warning("DS_CKPT_FAULT: unparseable spec %r ignored",
                           part)
    _fault_cache = (key, spec)
    return spec


def fault_point(stage: str, point: str, path: str = "") -> None:
    """Raise an :class:`InjectedStageFault` when the unified spec arms
    this (stage, point)'s current hit number.  No-op (one cached dict
    lookup) when nothing is armed."""
    arm = _fault_spec().get((stage, point))
    if arm is None:
        return
    n, sticky = arm
    with _fault_lock:
        hits = _fault_hits.get((stage, point), 0) + 1
        _fault_hits[(stage, point)] = hits
    if hits == n or (sticky and hits >= n):
        raise InjectedStageFault(
            f"injected fault at stage {stage!r} point {point!r}"
            f" (hit {hits}{'+' if sticky else ''})"
            + (f": {path}" if path else ""))


def injected_delay(stage: str) -> float:
    """Seconds of injected latency for ``stage`` work —
    ``DS_STAGE_DELAY_S`` spec entries first, then the stage's legacy
    alias env var."""
    global _delay_cache
    key = (os.environ.get(_DELAY_ENV, ""),) + tuple(
        os.environ.get(v, "") for v in _DELAY_ALIASES.values())
    if _delay_cache is None or _delay_cache[0] != key:
        spec: dict = {}
        for part in key[0].split(","):
            part = part.strip()
            if not part or ":" not in part:
                continue
            name, sec = part.rsplit(":", 1)
            try:
                spec[name.strip()] = float(sec)
            except ValueError:
                logger.warning("%s: unparseable spec %r ignored",
                               _DELAY_ENV, part)
        for name, env in _DELAY_ALIASES.items():
            raw = os.environ.get(env, "")
            if raw and name not in spec:
                try:
                    spec[name] = float(raw)
                except ValueError:
                    logger.warning("%s: unparseable value %r ignored",
                                   env, raw)
        _delay_cache = (key, spec)
    return _delay_cache[1].get(stage, 0.0)


def reset_fault_injection() -> None:
    """Clear the per-point hit counters (tests call this between cases;
    the env vars themselves are the test's to manage)."""
    with _fault_lock:
        _fault_hits.clear()


# ---------------------------------------------------------------------------
# StageWorker: the one thread constructor
# ---------------------------------------------------------------------------
class StageWorker:
    """Daemon worker thread with a restart-on-crash policy.

    ``loop`` is the stage's worker body.  Job-level failures are the
    stage's own business (caught inside the loop, routed to its poison/
    budget path); an exception ESCAPING the loop is a runtime bug that
    would otherwise kill the subsystem silently mid-training — the
    policy logs it loudly and restarts the loop up to ``restarts``
    times before letting it die.  Restarts are OPT-IN (default 0):
    every current worker body is non-reentrant (a restart would
    silently drop its in-flight item), so a loop must be written for
    re-entry before asking for them."""

    def __init__(self, loop: Callable[[], None], name: str,
                 restarts: int = 0):
        self.name = name
        self._loop = loop
        self._restarts = int(restarts)
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=name)
        self._thread.start()

    def _run(self):
        attempt = 0
        while True:
            try:
                self._loop()
                return
            except BaseException as e:
                if attempt >= self._restarts:
                    logger.error(
                        "stage worker %r crashed (no restarts left): %r",
                        self.name, e)
                    raise
                attempt += 1
                logger.error(
                    "stage worker %r crashed; restarting its loop "
                    "(%d/%d): %r", self.name, attempt, self._restarts, e)

    def is_alive(self) -> bool:
        return self._thread.is_alive()

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout=timeout)


def spawn(loop: Callable[[], None], name: str,
          restarts: int = 0) -> StageWorker:
    """Start a :class:`StageWorker` — the only sanctioned way runtime
    code makes a daemon thread (JL007)."""
    return StageWorker(loop, name, restarts=restarts)


# ---------------------------------------------------------------------------
# Channel: bounded queue with close/poison
# ---------------------------------------------------------------------------
class Channel:
    """Bounded FIFO connecting one stage to the next.

    The poison contract: ``poison(err)`` stores the ORIGINAL exception;
    consumers draining the channel receive items produced before the
    failure first, then re-raise exactly ``err`` (typed propagation —
    no wrapping); producers observe ``closed``/``err`` and stop.
    ``close()`` drops queued items and releases both sides.  All state
    is guarded by ``cond`` — stage-specific wait predicates may take
    the lock directly (``with chan.cond: chan.cond.wait_for(...)``)."""

    def __init__(self, capacity: Optional[int] = None):
        self.cond = threading.Condition()
        self.items: List[Any] = []
        self.capacity = capacity
        self.closed = False
        self.err: Optional[BaseException] = None

    def put(self, item, force: bool = False) -> bool:
        """Blocking bounded put; ``force`` bypasses the bound (end
        sentinels).  Returns False when the channel closed OR was
        poisoned while waiting — the producer's signal to stop (a
        consumer-side poison must release a producer parked on a full
        channel nobody will drain again)."""
        with self.cond:
            if not force:
                self.cond.wait_for(
                    lambda: self.closed or self.err is not None
                    or self.capacity is None
                    or len(self.items) < self.capacity)
            if self.closed or self.err is not None:
                return False
            self.items.append(item)
            self.cond.notify_all()
            return True

    def wait_space(self) -> bool:
        """Park until there is room to produce (or the channel closed/
        poisoned); True = go ahead, False = stop producing."""
        with self.cond:
            self.cond.wait_for(
                lambda: self.closed or self.err is not None
                or self.capacity is None
                or len(self.items) < self.capacity)
            return not self.closed and self.err is None

    def get(self, timeout: Optional[float] = None):
        """Pop the oldest item; queued items drain BEFORE a poison
        re-raises (the original exception) and before a close surfaces
        as ``RuntimeError("Channel is closed")``.  Consumers with richer
        semantics (the prefetcher's hit/miss stats) use ``cond``
        directly."""
        with self.cond:
            ok = self.cond.wait_for(
                lambda: self.items or self.err is not None or self.closed,
                timeout=timeout)
            if not ok:
                raise TimeoutError("Channel.get timed out")
            if self.items:
                item = self.items.pop(0)
                self.cond.notify_all()
                return item
            if self.err is not None:
                raise self.err
            raise RuntimeError("Channel is closed")

    def poison(self, err: BaseException) -> None:
        with self.cond:
            if self.err is None:
                self.err = err
            self.cond.notify_all()

    def close(self) -> None:
        with self.cond:
            self.closed = True
            self.items.clear()
            self.cond.notify_all()

    def qsize(self) -> int:
        with self.cond:
            return len(self.items)


# ---------------------------------------------------------------------------
# Stage: budget, degradation, surfaced errors, injection points
# ---------------------------------------------------------------------------
class Stage:
    """The persistent per-subsystem fault record.

    One ``Stage`` object outlives the (possibly per-step) worker
    objects of its subsystem — the engine owns one per async plane and
    threads it through, so the failure budget counts across steps and a
    degradation sticks for the rest of the run.

    ``transient`` classifies which failures are the runtime's to absorb
    (retry, then degrade): ``OSError`` — the same class ``io_retry``
    retries and the injection plane raises.  Anything else takes the
    subsystem's pre-existing poison path untouched, so the PR 3/4/5
    contracts (prefetch poison, uploader poison, writer
    poison-this-save-only) are bitwise what they were."""

    def __init__(self, name: str,
                 max_failures: Optional[int] = None,
                 allow_degraded: bool = True,
                 fallback: str = "its inline/serial equivalent",
                 transient=(OSError,)):
        self.name = name
        self.max_failures = (DEFAULT_MAX_STAGE_FAILURES
                             if max_failures is None else int(max_failures))
        self.allow_degraded = bool(allow_degraded)
        self.fallback = fallback
        self.transient = transient
        self.degraded = False
        self.failures = 0            # total transient failures absorbed
        self._consecutive = 0
        self._lock = threading.Lock()
        self._surfaced: Optional[BaseException] = None
        #: telemetry hook installed by the engine:
        #: counter_fn(name, help, amount) — None = log-only
        self.counter_fn: Optional[Callable[[str, str, float], None]] = None
        #: flight recorder: bounded ring of recent structured events
        #: (call outcomes, failures, degradation transitions, surfaced
        #: errors), each stamped with the channel depth when ``depth_fn``
        #: is installed.  deque.append is atomic; readers snapshot.
        self.events: deque = deque(maxlen=FLIGHT_RING_SIZE)
        #: optional queue-depth sampler (the owning subsystem installs
        #: its channel's qsize) — sampled into every recorded event so a
        #: dump shows the depth trajectory leading up to a failure.  May
        #: return a dict of named int gauges instead (keep a "depth"
        #: key for the primary trajectory): the serve stage samples
        #: queue depth AND the KV page pool's free-page count
        self.depth_fn: Optional[Callable[[], Any]] = None
        #: one-shot hook fired when the stage DEGRADES (the engine dumps
        #: a flight record); called outside the stage lock
        self.on_degrade: Optional[Callable[["Stage"], None]] = None

    # -- flight recorder -------------------------------------------------
    def record_event(self, kind: str, **fields) -> None:
        """Append one structured event to the bounded flight-recorder
        ring.  Host-only and cheap; the depth sample runs OUTSIDE the
        stage lock (depth_fn takes its subsystem's own lock), the
        append inside it so a concurrent ``flight_snapshot`` iteration
        never races a mutation.  A broken depth sampler must never
        break the stage."""
        ev = {"t": time.time(), "kind": kind}
        if self.depth_fn is not None:
            try:
                d = self.depth_fn()
                if isinstance(d, dict):
                    # multi-gauge sampler (the serve stage stamps queue
                    # depth, free-page count, and the live speculation
                    # accept ratio); "depth" stays the primary key
                    # diagnose's trajectory reads.  Float gauges (the
                    # accept ratio) keep their fraction — int() would
                    # truncate every ratio to 0
                    for dk, dv in d.items():
                        ev[dk] = float(dv) if isinstance(dv, float) \
                            else int(dv)
                else:
                    ev["depth"] = int(d)
            except Exception:
                pass
        ev.update(fields)
        with self._lock:
            self.events.append(ev)

    def flight_snapshot(self) -> dict:
        """Plain-data view of this stage's fault record + event ring —
        one entry of a ``flightrec_<step>.json`` dump."""
        with self._lock:
            return {"degraded": self.degraded, "failures": self.failures,
                    "max_failures": self.max_failures,
                    "fallback": self.fallback,
                    "surfaced": (repr(self._surfaced)
                                 if self._surfaced else None),
                    "events": list(self.events)}

    # -- hooks ----------------------------------------------------------
    def _count(self, name: str, help: str, n: float = 1):
        if self.counter_fn is not None:
            try:
                self.counter_fn(name, help, n)
            except Exception:  # a broken hook must never break a stage
                logger.exception("stage %r counter hook failed", self.name)

    # -- the injection boundary -----------------------------------------
    def check(self, point: str, path: str = "") -> None:
        """The stage boundary: injected delay + armed fault.  A
        DEGRADED stage skips it entirely — its fallback is the code
        path that never had the async machinery, so chaos specs cannot
        re-kill the inline equivalent (and a real failure there
        surfaces its real error)."""
        if self.degraded:
            return
        delay = injected_delay(self.name)
        if delay > 0:
            time.sleep(delay)
        fault_point(self.name, point, path)

    def is_transient(self, err: BaseException) -> bool:
        return isinstance(err, self.transient)

    # -- bookkeeping -----------------------------------------------------
    def note_ok(self) -> None:
        with self._lock:
            self._consecutive = 0

    def note_failure(self, err: BaseException,
                     attempts: Optional[int] = None) -> int:
        """Count one transient failure against the budget; returns the
        effective consecutive count (``>= max_failures`` means the
        budget is now exhausted).  The count is claimed under the lock —
        two workers sharing one Stage (train + eval prefetchers) each
        get their own exact value for backoff/logging.  ``attempts`` is
        the call-site's OWN retry count and acts as a floor: a sibling
        worker's interleaved successes reset the shared counter but
        must not let a persistently failing call-site retry unbounded.
        Crossing the threshold with ``allow_degraded`` marks the stage
        degraded — ONE loud warning + ``stage_degraded_total``."""
        with self._lock:
            self.failures += 1
            self._consecutive += 1
            n = self._consecutive
            if attempts is not None and attempts > n:
                n = attempts
            newly = (n >= self.max_failures and self.allow_degraded
                     and not self.degraded)
            if newly:
                self.degraded = True
        self.record_event("failure", error=repr(err), consecutive=n)
        self._count("stage_failures_total",
                    "transient stage failures absorbed by the runtime")
        if newly:
            logger.warning(
                "stage %r exceeded its failure budget (%d consecutive "
                "transient failures, stages.max_stage_failures=%d) — "
                "DEGRADING to %s for the rest of the run. Last error: %r",
                self.name, n, self.max_failures,
                self.fallback, err)
            self.record_event("degraded", error=repr(err),
                              fallback=self.fallback)
            self._count("stage_degraded_total",
                        "stages that fell back to their inline/serial "
                        "equivalent after exhausting the failure budget")
            if self.on_degrade is not None:
                try:  # a broken dump hook must never break the stage
                    self.on_degrade(self)
                except Exception:
                    logger.exception(
                        "stage %r on_degrade hook failed", self.name)
        return n

    # -- the policy wrapper ----------------------------------------------
    def call(self, point: str, fn: Callable[[], Any], path: str = ""):
        """Run one unit of stage work under the whole fault policy:
        injection boundary, transient retry up to the budget, then
        degradation (run ``fn`` once more OUTSIDE the injection plane —
        the inline equivalent) or, with degradation disabled, the
        original exception.  Non-transient failures propagate untouched
        on the first hit — the subsystem's own poison path."""
        if self.degraded:
            return fn()
        attempts = 0
        while True:
            try:
                t0 = time.perf_counter()
                self.check(point, path)
                out = fn()
                self.note_ok()
                self.record_event("ok", point=point,
                                  dur_s=round(time.perf_counter() - t0, 6))
                return out
            except BaseException as e:
                if not self.is_transient(e):
                    raise
                attempts += 1
                # this call-site's own attempt count floors the shared
                # counter: a sibling worker's interleaved successes
                # (train vs eval prefetcher on ONE Stage) must not let
                # a persistently failing site retry unbounded
                n = self.note_failure(e, attempts=attempts)
                if n < self.max_failures:
                    # transient retry within budget — spaced out so one
                    # real blip can't burn every attempt inside its own
                    # window (injected faults pay it too: the chaos
                    # tests prove the budget, not the timing); n is THIS
                    # thread's claimed count, race-free vs a sharing
                    # worker
                    time.sleep(min(
                        RETRY_BACKOFF_BASE_S * 2 ** (n - 1),
                        RETRY_BACKOFF_MAX_S))
                    continue
                if self.degraded:
                    return fn()  # the inline equivalent, no injection
                raise

    # -- surfaced errors (nowhere else to land) ---------------------------
    def surface(self, err: BaseException) -> None:
        """Record a failure whose natural reporting path is gone (an
        upload failing after ``close()``/``abort()`` began) so the
        engine's pre-step tick can land it in ``last_stage_error``
        instead of it vanishing with the daemon thread."""
        with self._lock:
            self._surfaced = err
        self.record_event("surfaced", error=repr(err))
        self._count("stage_errors_total",
                    "stage failures surfaced outside their normal "
                    "reporting path (post-close/post-abort)")
        logger.error("stage %r failure after close/abort (surfaced to "
                     "the engine tick): %r", self.name, err)

    def pop_error(self) -> Optional[BaseException]:
        with self._lock:
            err, self._surfaced = self._surfaced, None
            return err


# ---------------------------------------------------------------------------
# WatchdogPool: per-stage watchdog timeouts with abandon-and-replace
# ---------------------------------------------------------------------------
class _WatchdogWorker:
    """ONE persistent daemon thread serving every watchdogged call of a
    pool.  ``stop()`` flags it: jobs still queued (or submitted after —
    the sentinel race) fail fast with :class:`WorkerAbandoned` instead
    of being stranded, and the thread exits once its in-flight native
    call (if any) ever returns."""

    def __init__(self, name: str):
        self._cond = threading.Condition()
        self._q: list = []
        self._stopped = False
        spawn(self._run, name, restarts=0)

    def _run(self):
        while True:
            with self._cond:
                self._cond.wait_for(lambda: self._q or self._stopped)
                if self._stopped:
                    for _fn, box, done in self._q:  # never strand a job
                        box["e"] = WorkerAbandoned()
                        done.set()
                    self._q.clear()
                    return
                fn, box, done = self._q.pop(0)
            try:
                box["v"] = fn()
            except BaseException as e:  # surfaced to the waiting caller
                box["e"] = e
            finally:
                done.set()

    def submit(self, fn):
        box: dict = {}
        done = threading.Event()
        with self._cond:
            if self._stopped:
                box["e"] = WorkerAbandoned()
                done.set()
            else:
                self._q.append((fn, box, done))
                self._cond.notify_all()
        return box, done

    def stop(self):
        with self._cond:
            self._stopped = True
            self._cond.notify_all()


class WatchdogPool:
    """Abandon-and-replace watchdog calls over one persistent worker.

    A guarded call that stalls *inside one un-interruptible native
    call* (the round-3 tunnel root cause, BENCH_NOTES.md) cannot be
    interrupted by signals; running it on the pool's worker converts
    the forever-stall into a RuntimeError after ``timeout_s``.  The
    wedged worker is abandoned — replaced lazily on the next call — so
    later calls never queue behind a stalled one; a call landing on a
    worker another timeout just stopped retries ONCE on a fresh worker
    (that race must not masquerade as a stall).  Note the semantic
    shift vs thread-per-call: concurrent calls serialize through one
    worker, so a call's timeout window includes queue wait — acceptable
    where calls share one underlying link anyway."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self.worker: Optional[_WatchdogWorker] = None

    def call(self, fn: Callable[[], Any], timeout_s: float, what: str,
             timeout_msg: Optional[str] = None):
        for _attempt in range(2):
            with self._lock:
                worker = self.worker
                if worker is None:
                    worker = self.worker = _WatchdogWorker(self.name)
            box, done = worker.submit(fn)
            if not done.wait(timeout=timeout_s):
                with self._lock:
                    if self.worker is worker:
                        self.worker = None  # next call starts fresh
                worker.stop()
                raise RuntimeError(
                    timeout_msg if timeout_msg is not None else
                    f"{what} did not complete within {timeout_s:.0f}s: "
                    f"stage watchdog {self.name!r} abandoned the wedged "
                    "worker")
            if "e" in box:
                if isinstance(box["e"], WorkerAbandoned):
                    with self._lock:
                        if self.worker is worker:
                            self.worker = None
                    continue  # fresh worker, one retry
                raise box["e"]
            return box["v"]
        raise RuntimeError(
            f"{what}: watchdog {self.name!r} worker abandoned twice in a "
            "row — concurrent timeouts on this link; treat as stalled.")

    def stop(self):
        """Release the current worker (tests/teardown)."""
        with self._lock:
            worker, self.worker = self.worker, None
        if worker is not None:
            worker.stop()


# ---------------------------------------------------------------------------
# StageGraph: the documented drain order
# ---------------------------------------------------------------------------
class StageGraph:
    """Ordered registry of the engine's async planes — "drain
    everything" as one call.

    THE order (docs/stages.md) is registration order, and the engine
    registers: prefetch -> offload uploads -> checkpoint writer ->
    telemetry flush.  Rationale: stop producing work before draining
    consumers of it, and drain everything that EMITS telemetry before
    the exporters flush; prefetched batches are droppable, an in-flight
    checkpoint save is not.  ``close_all``/``drain_all`` are idempotent
    (every registered close is), never abort mid-order (a failing entry
    is collected and the rest still drain), and never raise — the
    collected errors are returned for the caller to surface."""

    def __init__(self):
        self._entries: List[Tuple[str, Callable, Optional[Callable]]] = []
        self._lock = threading.Lock()

    def register(self, name: str, close: Callable[[], None],
                 drain: Optional[Callable[[], None]] = None) -> None:
        with self._lock:
            self._entries.append((name, close, drain))

    def _run(self, which: str) -> List[Tuple[str, BaseException]]:
        with self._lock:
            entries = list(self._entries)
        errors: List[Tuple[str, BaseException]] = []
        for name, close, drain in entries:
            fn = close if which == "close" else (drain or close)
            try:
                fn()
            except BaseException as e:
                logger.error("stage graph: %s of %r failed: %r",
                             which, name, e)
                errors.append((name, e))
        return errors

    def drain_all(self) -> List[Tuple[str, BaseException]]:
        """Wait out in-flight work in drain order without tearing the
        stages down — the barrier form; the built-in sync save drains
        just the ckpt entry (its other drains are no-ops)."""
        return self._run("drain")

    def close_all(self) -> List[Tuple[str, BaseException]]:
        """Drain + stop every stage in drain order (engine.close)."""
        return self._run("close")

    @property
    def order(self) -> List[str]:
        with self._lock:
            return [name for name, _, _ in self._entries]
