"""Progressive Layer Drop (reference:
deepspeed/runtime/progressive_layer_drop.py:1-33).

Keep-probability schedule θ(t) = (1−θ̄)·exp(−γ·t) + θ̄; the engine advances
it each step and models consume ``get_state()`` (the reference injects
``progressive_layer_drop`` kwargs into the forward, engine.py:787-788).
On TPU the drop decision itself belongs inside the model (a
``lax.cond``/mask over the scanned layer stack keyed on the theta value),
so this class stays pure bookkeeping, exactly like the reference.
"""
from __future__ import annotations

import math


class ProgressiveLayerDrop:
    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0
        from ..utils.logging import log_dist
        log_dist(f"Enabled progressive layer dropping (theta = "
                 f"{self.theta})", ranks=[0])

    def get_state(self) -> dict:
        return {"progressive_layer_drop": True,
                "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> None:
        def _prob(x, gamma, p):
            return (1.0 - p) * math.exp(-gamma * x) + p

        self.current_theta = _prob(global_step, self.gamma, self.theta)
