"""Mixed precision + loss scaling as traced, jit-safe state.

Behavioral port of the reference loss scalers
(reference: deepspeed/runtime/fp16/loss_scaler.py:56-166): static scale, and
dynamic scaling with growth window + hysteresis ("delayed shift").  The
reference mutates Python attributes on overflow (stage2.py:1341-1362); here
the overflow→skip→rescale decision is data in the train-step pytree under
``lax.cond`` inside one compiled step (SURVEY.md §7 "hard parts" #1).

State/config split: ``LossScaleState`` holds only traced arrays (it rides in
the donated TrainState pytree); ``LossScaleConfig`` is static Python the
step closes over — keeping jit caches stable.

On TPU the native compute dtype is bfloat16, which needs no loss scaling —
``make_loss_scaler(enabled=False)`` yields a unit scale and ``update_scale``
becomes the identity.
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    """Traced pytree state."""
    loss_scale: jnp.ndarray      # f32 scalar
    good_steps: jnp.ndarray      # i32 — consecutive overflow-free steps
    hysteresis: jnp.ndarray      # i32 — overflows left before scale halves


@dataclasses.dataclass(frozen=True)
class LossScaleConfig:
    """Static knobs (hashable; closed over by the compiled step)."""
    dynamic: bool = True
    scale_window: int = 1000
    min_scale: float = 1.0
    init_hysteresis: int = 2
    enabled: bool = True


def make_loss_scaler(enabled: bool = True,
                     static_scale: float = 0,
                     initial_scale_power: int = 32,
                     scale_window: int = 1000,
                     hysteresis: int = 2,
                     min_scale: float = 1.0
                     ) -> Tuple[LossScaleState, LossScaleConfig]:
    """``static_scale == 0`` selects dynamic scaling (reference semantics:
    fp16.loss_scale == 0 ⇒ dynamic, runtime/config.py)."""
    dynamic = static_scale == 0
    init = float(2 ** initial_scale_power) if dynamic else float(static_scale)
    if not enabled:
        init = 1.0
    state = LossScaleState(
        loss_scale=jnp.asarray(init, jnp.float32),
        good_steps=jnp.asarray(0, jnp.int32),
        hysteresis=jnp.asarray(hysteresis, jnp.int32),
    )
    config = LossScaleConfig(
        dynamic=dynamic and enabled,
        scale_window=scale_window,
        min_scale=min_scale,
        init_hysteresis=hysteresis,
        enabled=enabled,
    )
    return state, config


def from_fp16_config(fp16_cfg) -> Tuple[LossScaleState, LossScaleConfig]:
    """Build from a DeepSpeedFP16Config block."""
    return make_loss_scaler(
        enabled=fp16_cfg.enabled,
        static_scale=fp16_cfg.loss_scale,
        initial_scale_power=fp16_cfg.initial_scale_power,
        scale_window=fp16_cfg.loss_scale_window,
        hysteresis=fp16_cfg.hysteresis,
        min_scale=fp16_cfg.min_loss_scale,
    )


def scale_loss(loss: jnp.ndarray, state: LossScaleState) -> jnp.ndarray:
    return loss * state.loss_scale.astype(loss.dtype)


def unscale_grads(grads, state: LossScaleState):
    inv = (1.0 / state.loss_scale).astype(jnp.float32)
    return jax.tree.map(lambda g: g.astype(jnp.float32) * inv, grads)


def grads_finite(grads) -> jnp.ndarray:
    """Fused isfinite-reduction overflow check (replaces the reference's
    serial NaN/Inf scan + allreduce, runtime/utils.py:41-137; under SPMD the
    cross-replica reduction is implicit because grads are already reduced)."""
    leaves = jax.tree.leaves(grads)
    if not leaves:
        return jnp.asarray(True)
    finite = [jnp.all(jnp.isfinite(g)) for g in leaves]
    return jnp.stack(finite).all()


def update_scale(state: LossScaleState, finite: jnp.ndarray,
                 config: LossScaleConfig) -> LossScaleState:
    """One dynamic-loss-scale transition (reference: loss_scaler.py:151-166)."""
    if not config.dynamic:
        return state

    def on_good(s: LossScaleState):
        good = s.good_steps + 1
        grow = good >= config.scale_window
        new_scale = jnp.where(grow, s.loss_scale * 2.0, s.loss_scale)
        new_good = jnp.where(grow, 0, good).astype(jnp.int32)
        # replenish hysteresis at every growth window (reference:
        # loss_scaler.py:161-166 resets cur_hysteresis on raise)
        new_hys = jnp.where(grow, config.init_hysteresis,
                            s.hysteresis).astype(jnp.int32)
        return s._replace(loss_scale=new_scale, good_steps=new_good,
                          hysteresis=new_hys)

    def on_overflow(s: LossScaleState):
        hys = s.hysteresis - 1
        drop = hys <= 0
        new_scale = jnp.where(
            drop, jnp.maximum(s.loss_scale / 2.0, config.min_scale),
            s.loss_scale)
        new_hys = jnp.where(drop, config.init_hysteresis, hys).astype(jnp.int32)
        return s._replace(loss_scale=new_scale,
                          good_steps=jnp.asarray(0, jnp.int32),
                          hysteresis=new_hys)

    return jax.lax.cond(finite, on_good, on_overflow, state)


def select_compute_dtype(fp16_enabled: bool, bf16_enabled: bool):
    if bf16_enabled:
        return jnp.bfloat16
    if fp16_enabled:
        return jnp.float16
    return jnp.float32


def cast_to_compute(params, dtype):
    """fp32 master → compute-dtype params (the reference's model.half() at
    engine.py:508 becomes a per-step cast; float leaves only)."""
    def cast(x):
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x
    return jax.tree.map(cast, params)
