"""Activation checkpointing — rematerialization policies + RNG tracking.

The reference implements a Megatron-derived autograd Function that saves
RNG state, optionally partitions/offloads saved activations, and recomputes
in backward (reference: deepspeed/runtime/activation_checkpointing/
checkpointing.py:314-576).  On TPU every piece maps to a first-class JAX
facility:

  checkpoint(fn, *args)      → ``jax.checkpoint`` (recompute-in-backward is
                               the transform's definition; RNG replay is
                               automatic because keys are explicit values)
  partition_activations      → saved residuals inherit the model's sharding
                               constraints (GSPMD shards them; nothing to
                               hand-partition).  Flag accepted + recorded.
  cpu_checkpointing          → remat policy that offloads saved dot
                               operands to host memory when the jax version
                               provides the offload policy; else full remat
                               (strictly less memory than saving).
  CudaRNGStatesTracker       → named-key tracker (checkpointing.py:147-220
                               there): explicit ``jax.random`` keys instead
                               of mutable CUDA RNG state — fork() returns a
                               fresh key and advances the named stream.

``configure()`` / ``is_configured()`` mirror the reference module surface
(checkpointing.py:654-746).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax

from ...config.config import DeepSpeedActivationCheckpointingConfig
from ...utils.logging import log_dist

_config: Optional[DeepSpeedActivationCheckpointingConfig] = None
_policy = None


# ---------------------------------------------------------------------------
# RNG tracker (reference CudaRNGStatesTracker, checkpointing.py:147-220)
# ---------------------------------------------------------------------------
_MODEL_PARALLEL_RNG = "model-parallel-rng"


class RNGStatesTracker:
    """Named streams of jax PRNG keys.  ``add(name, seed)`` registers a
    stream; ``fork(name)`` returns a fresh key and advances the stream —
    the functional analogue of the reference's get/set of device RNG
    state around each checkpointed region."""

    def __init__(self):
        self.states_: Dict[str, jax.Array] = {}

    def reset(self):
        self.states_ = {}

    def get_states(self) -> Dict[str, Any]:
        return dict(self.states_)

    def set_states(self, states: Dict[str, Any]):
        self.states_ = dict(states)

    def add(self, name: str, seed: int):
        if name in self.states_:
            raise Exception(f"rng state {name} already exists")
        self.states_[name] = jax.random.PRNGKey(seed)

    def fork(self, name: str = _MODEL_PARALLEL_RNG) -> jax.Array:
        if name not in self.states_:
            raise Exception(f"rng state {name} is not added")
        self.states_[name], out = tuple(
            jax.random.split(self.states_[name]))
        return out


_RNG_TRACKER = RNGStatesTracker()


def get_cuda_rng_tracker() -> RNGStatesTracker:  # reference-compatible name
    return _RNG_TRACKER


def model_parallel_cuda_manual_seed(seed: int, tp_rank: int = 0,
                                    pp_rank: int = 0, tp_size: int = 1):
    """Seed scheme from the reference (checkpointing.py:223-262): the
    model-parallel stream offsets by 2718 + tp_rank (+ pipeline offset) so
    different TP ranks draw different dropout masks while the default
    stream stays rank-invariant."""
    offset = seed + 2718 + pp_rank * tp_size
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add(_MODEL_PARALLEL_RNG, offset + tp_rank)
    return offset + tp_rank


# ---------------------------------------------------------------------------
# checkpoint()
# ---------------------------------------------------------------------------
def _select_policy(cfg: DeepSpeedActivationCheckpointingConfig):
    if cfg.cpu_checkpointing:
        pols = getattr(jax, "checkpoint_policies", None)
        offload = getattr(pols, "offload_dot_with_no_batch_dims", None)
        if offload is not None:
            try:
                return offload("device", "pinned_host")
            except TypeError:
                pass
        log_dist("cpu_checkpointing: offload remat policy unavailable in "
                 "this jax; using full rematerialization", ranks=[0])
    return None  # jax.checkpoint default: save nothing, recompute all


def configure(mpu_=None, deepspeed_config=None,
              partition_activations: Optional[bool] = None,
              contiguous_checkpointing: Optional[bool] = None,
              num_checkpoints: Optional[int] = None,
              checkpoint_in_cpu: Optional[bool] = None,
              synchronize: Optional[bool] = None,
              profile: Optional[bool] = None):
    """Reference-compatible configure (checkpointing.py:654-733): explicit
    args override the config block."""
    global _config, _policy
    if deepspeed_config is not None and hasattr(
            deepspeed_config, "activation_checkpointing_config"):
        _config = deepspeed_config.activation_checkpointing_config
    elif isinstance(deepspeed_config, dict):
        _config = DeepSpeedActivationCheckpointingConfig(deepspeed_config)
    elif _config is None:
        _config = DeepSpeedActivationCheckpointingConfig({})
    assert _config is not None
    for name, val in (("partition_activations", partition_activations),
                      ("contiguous_memory_optimization",
                       contiguous_checkpointing),
                      ("number_checkpoints", num_checkpoints),
                      ("cpu_checkpointing", checkpoint_in_cpu),
                      ("synchronize_checkpoint_boundary", synchronize),
                      ("profile", profile)):
        if val is not None:
            setattr(_config, name, val)
    _policy = _select_policy(_config)


def is_configured() -> bool:
    return _config is not None


def reset():
    """Reference reset() (checkpointing.py:598): clears configure state."""
    global _config, _policy
    _config = None
    _policy = None


def checkpoint(function, *args):
    """Checkpoint a forward segment: memory-saving recompute-in-backward
    (reference CheckpointFunction.apply, checkpointing.py:579-596).
    Differentiable; RNG keys passed through ``args`` replay identically in
    the recompute (keys are values, the property the reference's RNG
    save/restore machinery exists to emulate)."""
    policy = _policy if is_configured() else None
    return jax.checkpoint(function, policy=policy)(*args)
