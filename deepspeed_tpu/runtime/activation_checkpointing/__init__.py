from . import checkpointing
from ...config.config import DeepSpeedActivationCheckpointingConfig
