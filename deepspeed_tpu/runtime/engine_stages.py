"""The engine's stage plane (docs/stages.md): wiring, not policy.

``runtime/stages.py`` owns the shared async-stage primitives (workers,
channels, failure budgets, watchdogs, the ``StageGraph``); this module
owns how ONE :class:`~.engine.DeepSpeedEngine` instantiates them — the
persistent per-subsystem :class:`~.stages.Stage` records, the telemetry
counter hook, and THE documented drain order with its four close/drain
entries.  It lives outside engine.py so the stage plane is readable as
one unit and the engine keeps only the two calls (``drain_stages()``,
``close()``) that use it.

THE drain order (rationale in docs/stages.md): stop producers of
droppable work first, wait out durability consumers after, flush
telemetry last so it still sees every stage's final spans/counters —

    prefetch -> offload uploads -> disk write-back -> ckpt writer
             -> telemetry flush

The serving engine has its own graph with the same discipline
(``wire_serve_stage_plane``; the fence's second line) —

    serve queue -> kv spill -> kv fetch -> telemetry flush

Prefetched batches are droppable and uploads never outlive their step
call; the disk tier's write-back workers are joined before their step
returns (a mid-step close aborts them and the step poisons); an
in-flight checkpoint save is not droppable, so its stage drains (and
surfaces failures) before anything flushes.
"""
from __future__ import annotations

import weakref

from .stages import Stage, StageGraph

#: (stage name, inline/serial fallback named in the degradation warning)
ENGINE_STAGES = (
    ("prefetch", "inline iteration"),
    ("offload_h2d", "the serial offload update"),
    ("disk_read", "the serial read-update-write loop"),
    ("disk_write", "the serial read-update-write loop"),
    ("ckpt_writer", "synchronous saves"),
)


def wire_stage_plane(engine) -> None:
    """Install the stage records and THE drain-order graph on ``engine``.

    The counter hook holds the engine WEAKLY: stage records ride worker
    threads (GC roots), and a strong capture would pin the engine for
    process lifetime.  The graph's entries resolve engine attributes at
    call time (``getattr``), so wiring happens before the checkpoint
    writer exists and close stays correct on partially-built engines.
    """
    eng_ref = weakref.ref(engine)

    def _stage_counter(name, help, n):
        eng = eng_ref()
        if eng is not None and eng.telemetry is not None:
            eng.telemetry.registry.counter(name, help).inc(n)

    def _stage_degrade_dump(st):
        # flight recorder (docs/observability.md): a degradation is the
        # moment the history explaining it is still in the rings — dump
        # before it scrolls off.  Runs on the degrading worker's thread;
        # dump_flight_record never raises.
        eng = eng_ref()
        if eng is not None:
            eng.dump_flight_record(
                reason=f"stage {st.name!r} degraded to {st.fallback}")

    engine._stage_records = {}
    for sname, fallback in ENGINE_STAGES:
        st = Stage(sname,
                   max_failures=engine.config.stages_config
                   .max_stage_failures,
                   fallback=fallback)
        st.counter_fn = _stage_counter
        st.on_degrade = _stage_degrade_dump
        engine._stage_records[sname] = st
    engine.last_stage_error = None
    #: every surfaced stage error, oldest first (bounded) — one tick
    #: can pop several stages' failures and ``last_stage_error`` only
    #: carries the newest
    engine.stage_errors = []
    engine._active_uploader = None

    graph = StageGraph()
    graph.register("prefetch",
                   close=lambda: close_prefetch_stage(engine),
                   drain=lambda: None)  # queued batches are droppable
    graph.register("offload_uploads",
                   close=lambda: close_upload_stage(engine),
                   drain=lambda: None)  # never outlives its step call
    graph.register("disk_writeback",
                   close=lambda: close_disk_stage(engine),
                   drain=lambda: None)  # joined before step returns
    graph.register("ckpt_writer",
                   close=lambda: close_ckpt_stage(engine),
                   drain=lambda: drain_ckpt_stage(engine))
    graph.register("telemetry",
                   close=lambda: close_telemetry_stage(engine),
                   drain=engine._flush_tensorboard)
    engine._stage_graph = graph


def stage_degraded(engine, name: str) -> bool:
    """True when the named stage exhausted its failure budget — the
    engine's hot paths pin their serial/inline equivalent on this."""
    recs = getattr(engine, "_stage_records", None)
    return bool(recs) and name in recs and recs[name].degraded


def pop_stage_errors(engine) -> None:
    """Land stage failures whose natural reporting path was gone (an
    upload failing after close()/abort() began) in
    ``engine.last_stage_error`` — the training thread's advertised
    surface, ticked pre-step alongside the checkpoint writer's.  One
    tick can pop several stages' failures; all of them are retained in
    ``engine.stage_errors`` (bounded, oldest dropped) so an earlier
    stage's error is never silently replaced by a later one."""
    for st in getattr(engine, "_stage_records", {}).values():
        err = st.pop_error()
        if err is not None:
            engine.last_stage_error = err
            engine.stage_errors.append(err)
            del engine.stage_errors[:-16]


def finish_close(engine) -> None:
    """The tail of ``engine.close()``: run THE drain order, release the
    preemption hook and the GC finalizer, then surface any close-time
    failures.  ``close_all`` never aborts mid-order, so every stage
    still closed; the errors land in ``stage_errors``/
    ``last_stage_error`` and the FIRST re-raises so an explicit caller
    sees the shutdown was not clean (a GC finalizer swallows it like
    any finalizer exception — the hook/finalizer release above already
    happened, so a later explicit close stays idempotent)."""
    errors = engine._stage_graph.close_all()
    # a failure surfaced DURING the drain (an aborted upload dying mid-
    # put) has no later pre-step tick to land it — pop it here
    pop_stage_errors(engine)
    ph = getattr(engine, "_preemption_handler", None)
    if ph is not None and not ph.fired:
        ph.uninstall()
    if getattr(engine, "_finalizer", None) is not None:
        engine._finalizer.detach()
        engine._finalizer = None
    if errors:
        for _name, err in errors:
            engine.last_stage_error = err
            engine.stage_errors.append(err)
        del engine.stage_errors[:-16]
        raise errors[0][1]


# ---------------------------------------------------------------------------
# the four stage-graph entries, in THE drain order
# ---------------------------------------------------------------------------
def close_prefetch_stage(engine) -> None:
    """Release the input pipeline: each parked worker and the
    device-resident batches it staged ahead (idempotent).  Covers every
    engine-built prefetcher (train and eval) AND an adopted caller-built
    training prefetcher — ``_bind_train_prefetcher`` puts all of them in
    this list."""
    for pf in getattr(engine, "_prefetchers", []):
        pf.close()


def close_upload_stage(engine) -> None:
    """Abort a mid-flight streamed-offload uploader (a close landing
    inside a step from another thread/signal handler): queued uploads
    are dropped — the master's step is not yet published, so the old
    compute tree stays the consistent truth — and an in-flight failure
    surfaces through the stage record."""
    up = getattr(engine, "_active_uploader", None)
    if up is not None:
        up.abort()


def close_disk_stage(engine) -> None:
    """Abort a mid-flight disk-tier read-ahead/write-back pipeline (a
    close landing inside a step from another thread/signal handler):
    the channels close, the step raises and poisons — per-leaf files
    before the abort point hold step t, later ones t-1, which is
    exactly the inconsistency ``load_state_tree`` (checkpoint restore)
    heals by rewriting every leaf.  A between-steps close is a no-op:
    the pipeline workers never outlive their ``step`` call."""
    opt = getattr(engine, "_host_opt", None)
    if opt is not None and hasattr(opt, "abort_inflight"):
        opt.abort_inflight()


def drain_ckpt_stage(engine) -> None:
    """Wait out an in-flight async save WITHOUT stopping the writer
    (sync-save / elastic-restart ordering); its failure, if any,
    surfaces exactly like the pre-step tick's."""
    w = getattr(engine, "_ckpt_writer", None)
    if w is not None:
        from .checkpointing import _surface_writer_error
        _surface_writer_error(engine, w.drain())


def close_ckpt_stage(engine) -> None:
    """Close the checkpoint writer BEFORE telemetry: an in-flight async
    save must land (its spans/counters included), and a failure surfaces
    here rather than vanishing with the daemon thread."""
    w = getattr(engine, "_ckpt_writer", None)
    if w is not None:
        w.close()
        engine._ckpt_writer_tick()


def close_telemetry_stage(engine) -> None:
    """Flush buffered scalars, release the module transfer tracer hook,
    and close the hub + summary writer — LAST, after every stage that
    emits telemetry has drained."""
    engine._flush_tensorboard()
    tel = getattr(engine, "telemetry", None)
    if tel is not None:
        from . import offload
        if tel.tracer is not None \
                and offload._TRANSFER_TRACER is tel.tracer:
            offload.set_transfer_tracer(None)
        tel.close()
    if engine.summary_writer is not None:
        engine.summary_writer.close()


# ---------------------------------------------------------------------------
# the serving engine's stage graph, in ITS drain order
# ---------------------------------------------------------------------------
def wire_serve_stage_plane(serve) -> None:
    """Install the :class:`~..inference.engine.ServeEngine`'s drain-
    order graph (docs/stages.md; the fence's second line).

    Close order: stop taking requests first (``serve_queue`` fails the
    queued/pending typed and clears the prefix cache), then stop the KV
    tier's parking and write its host-resident parked pages to the disk
    tier (``kv_spill`` — the durability consumer waits out its
    backlog), then drop the remaining parked records (``kv_fetch`` —
    host/disk bytes only, no pool refs to return), telemetry last so
    the final flush still sees every tier counter.  Both kv entries are
    no-ops when the tier is off (``serving.kv_tier.idle_park_ticks=0``).
    """
    serve._graph = StageGraph()
    serve._graph.register("serve_queue", close=serve._close_queue,
                          drain=lambda: None)
    serve._graph.register("kv_spill", close=serve._close_kv_spill,
                          drain=serve._drain_kv_spill)
    serve._graph.register("kv_fetch", close=serve._close_kv_fetch,
                          drain=lambda: None)
    serve._graph.register("telemetry", close=serve._close_telemetry,
                          drain=serve._flush)
