"""Checkpoint save / load — two-plane scheme with reshard-on-load.

The reference writes a *model plane* (fp16 module weights + engine counters,
one file per MP rank: reference deepspeed/runtime/engine.py:1211-1236) and a
*ZeRO plane* (per-DP-rank partitioned fp32 master weights + optimizer state:
engine.py:1218-1229, zero/stage2.py:1675-1706), and supports loading ZeRO
checkpoints at a *different* DP world size by merging and re-partitioning
(stage2.py:1712-1778, stage1.py:836-941).

On TPU the partitioning is a sharding annotation, not a file layout, so the
natural design is: save the *logical* (unpartitioned) arrays once, and
re-apply the current engine's shardings at load time.  Resharding across any
mesh change (DP resize, ZeRO stage change, TP change) then falls out of
``jax.device_put`` — the elastic-restore feature costs nothing.

Layout of ``<save_dir>/<tag>/``:
  - ``meta.json``                       counters, world info, client_state
  - ``model/manifest.json  + *.npy``    module weights in compute dtype
  - ``optim/manifest.json  + *.npy``    fp32 master + optimizer state + scaler

``<save_dir>/latest`` holds the most recent tag (reference engine.py:1406).
Non-numpy-native dtypes (bfloat16) are stored as bit-pattern views with the
logical dtype recorded in the manifest.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from ..utils.logging import log_dist

LATEST_FILE = "latest"


# ---------------------------------------------------------------------------
# leaf codec
# ---------------------------------------------------------------------------
def _to_storage(arr: np.ndarray) -> Tuple[np.ndarray, str]:
    """Return (storable array, logical dtype name)."""
    logical = arr.dtype.name
    if arr.dtype.kind == "V" or logical in ("bfloat16", "float8_e4m3fn",
                                            "float8_e5m2"):
        itemsize = arr.dtype.itemsize
        view_dtype = {1: np.uint8, 2: np.uint16, 4: np.uint32}[itemsize]
        return arr.view(view_dtype), logical
    return arr, logical


def _from_storage(arr: np.ndarray, logical: str) -> np.ndarray:
    if arr.dtype.name != logical:
        import ml_dtypes
        return arr.view(np.dtype(getattr(ml_dtypes, logical)))
    return arr


def _keystr(path) -> str:
    return jax.tree_util.keystr(path)


# ---------------------------------------------------------------------------
# tree save / load
# ---------------------------------------------------------------------------
def save_tree(dirpath: str, tree: Any) -> None:
    """Write every leaf of ``tree`` as an .npy plus a manifest mapping
    pytree key-paths to files."""
    os.makedirs(dirpath, exist_ok=True)
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    manifest: Dict[str, Dict[str, Any]] = {}
    for i, (path, leaf) in enumerate(flat):
        arr = np.asarray(jax.device_get(leaf))
        store, logical = _to_storage(arr)
        fname = f"leaf_{i:05d}.npy"
        np.save(os.path.join(dirpath, fname), store, allow_pickle=False)
        manifest[_keystr(path)] = {
            "file": fname,
            "dtype": logical,
            "shape": list(arr.shape),
        }
    with open(os.path.join(dirpath, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1)


def load_tree(dirpath: str, target: Any, strict: bool = True) -> Any:
    """Load leaves by key-path into the structure of ``target``.  Each loaded
    array is placed with the corresponding target leaf's sharding — this is
    the reshard-on-load that makes DP-resize restore work (reference
    stage2.py:1712-1778 does this with explicit merge/repartition)."""
    with open(os.path.join(dirpath, "manifest.json")) as f:
        manifest = json.load(f)
    flat, treedef = jax.tree_util.tree_flatten_with_path(target)
    out = []
    for path, tleaf in flat:
        key = _keystr(path)
        entry = manifest.get(key)
        if entry is None:
            if strict:
                raise KeyError(
                    f"checkpoint at {dirpath} has no entry for {key!r}")
            log_dist(f"checkpoint {dirpath}: no entry for {key!r}; "
                     "keeping the engine's current value", ranks=[0])
            out.append(tleaf)
            continue
        arr = np.load(os.path.join(dirpath, entry["file"]),
                      allow_pickle=False)
        arr = _from_storage(arr, entry["dtype"])
        tshape = tuple(getattr(tleaf, "shape", ()))
        if tuple(arr.shape) != tshape:
            # Pipeline-resize elastic restore: stage-local stacked leaves
            # are [num_stages, layers_per_stage, ...]; stage ranges are
            # contiguous, so flattening the two leading dims is a canonical
            # layer order and a checkpoint saved at pp=2 reshapes losslessly
            # onto a pp=4 engine (reference analogue: ZeRO checkpoint
            # merge/re-partition across DP sizes, stage2.py:1712-1778).
            if ("stack_" in key
                    and len(arr.shape) >= 2 and len(tshape) >= 2
                    and arr.shape[2:] == tshape[2:]
                    and arr.shape[0] * arr.shape[1]
                    == tshape[0] * tshape[1]):
                arr = arr.reshape(tshape)
                log_dist(
                    f"checkpoint leaf {key!r}: restacked "
                    f"{entry['shape']} -> {list(tshape)} (pipeline resize)",
                    ranks=[0])
            else:
                raise ValueError(
                    f"checkpoint leaf {key!r} has shape {arr.shape}, engine "
                    f"expects {tshape} — model/optimizer config mismatch")
        sharding = getattr(tleaf, "sharding", None)
        tdtype = getattr(tleaf, "dtype", arr.dtype)
        arr = arr.astype(tdtype) if arr.dtype != tdtype else arr
        # Re-apply only mesh-aware placements; committing scalars to a single
        # device would pin them and conflict with the mesh under jit.  numpy
        # targets (offload host/flat staging templates) stay numpy — putting
        # a multi-GB offloaded master on device here would defeat offload.
        from jax.sharding import NamedSharding
        if isinstance(sharding, NamedSharding):
            out.append(jax.device_put(arr, sharding))
        elif isinstance(tleaf, np.ndarray):
            out.append(arr)
        else:
            out.append(jax.numpy.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# engine-level save / load
# ---------------------------------------------------------------------------
def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[dict] = None,
                    save_latest: bool = True) -> str:
    """Two-plane checkpoint write (reference engine.py:1211-1290).

    The write is atomic: everything lands in ``<tag>.tmp`` and is renamed
    into place only after ``meta.json`` (written last) is on disk, so a
    killed save can never leave a loadable-looking partial checkpoint.

    The model plane intentionally duplicates a down-cast of the fp32 master
    (~0.5× extra bytes): it keeps module-only loads (inference handoff, the
    reference's fp16-cast restore) independent of the optimizer plane, same
    as the reference's mp_rank/zero_pp_rank file split.

    Multi-host: only process 0 writes (arrays here are either replicated or
    fully addressable in the single-controller runs this framework targets;
    reference engine.py:415-416 likewise writes from DP rank 0 only).
    """
    from .engine import TrainState  # local import to avoid cycle

    state: TrainState = engine.state
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    ckpt_dir = os.path.join(save_dir, str(tag))
    if jax.process_count() > 1 and jax.process_index() != 0:
        return ckpt_dir
    tmp_dir = ckpt_dir + ".tmp"
    if os.path.isdir(tmp_dir):
        import shutil
        shutil.rmtree(tmp_dir)
    os.makedirs(tmp_dir, exist_ok=True)

    from . import precision
    # canonical (per-parameter tree) form: the XLA offload tier stores flat
    # host vectors internally, but the checkpoint keeps the logical tree so
    # offload <-> non-offload restores compose (reference merge/re-partition
    # analogue, stage2.py:1712-1778)
    master_tree, opt_tree = engine._canonical_state()
    module_params = precision.cast_to_compute(
        master_tree, engine.compute_dtype)
    save_tree(os.path.join(tmp_dir, "model"), {"module": module_params})
    save_tree(os.path.join(tmp_dir, "optim"), {
        "master_params": master_tree,
        "opt_state": opt_tree,
        "scaler": state.scaler,
        "rng": state.rng,
        "data_rng": engine._data_rng,
    })

    meta = {
        "tag": str(tag),
        "global_steps": int(engine.global_steps),
        "micro_steps": int(engine.micro_steps),
        "skipped_steps": int(state.skipped_steps),
        "dp_world_size": int(engine.dp_world_size),
        "zero_stage": int(engine.config.zero_optimization_stage),
        "client_state": client_state or {},
    }
    with open(os.path.join(tmp_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    if os.path.isdir(ckpt_dir):
        import shutil
        shutil.rmtree(ckpt_dir)
    os.rename(tmp_dir, ckpt_dir)
    if save_latest:
        latest_tmp = os.path.join(save_dir, LATEST_FILE + ".tmp")
        with open(latest_tmp, "w") as f:
            f.write(str(tag))
        os.replace(latest_tmp, os.path.join(save_dir, LATEST_FILE))
    log_dist(f"saved checkpoint {ckpt_dir}", ranks=[0])
    return ckpt_dir


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True,
                    load_lr_scheduler_states: bool = True,
                    load_module_only: bool = False):
    """Restore engine state; returns ``(load_path, client_state)`` like the
    reference (engine.py:1292-1324).  Handles a different current DP size /
    ZeRO stage / mesh than the one that saved (elastic restore).

    ``load_lr_scheduler_states`` is accepted for API parity but has no
    distinct effect: all lr schedules here are pure functions of the
    restored step count, so there is no separate scheduler state to load.
    """
    from .engine import TrainState
    import jax.numpy as jnp

    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        if not os.path.isfile(latest):
            log_dist(f"no 'latest' file in {load_dir}; nothing to load",
                     ranks=[0])
            return None, None
        with open(latest) as f:
            tag = f.read().strip()
    ckpt_dir = os.path.join(load_dir, str(tag))
    # meta.json is written last inside the atomic rename; its absence means
    # the checkpoint doesn't exist (or is a corrupt partial) — report
    # missing rather than crash.
    if not os.path.isfile(os.path.join(ckpt_dir, "meta.json")):
        return None, None

    with open(os.path.join(ckpt_dir, "meta.json")) as f:
        meta = json.load(f)

    state: TrainState = engine.state
    optim_dir = os.path.join(ckpt_dir, "optim")
    use_optim = (load_optimizer_states and not load_module_only
                 and os.path.isdir(optim_dir))
    rng = state.rng
    tmpl_master, tmpl_opt = engine._canonical_templates()
    if use_optim:
        # fp32 master restore (reference 'load_from_fp32_weights',
        # stage2.py:1780-1835); rng restore keeps dropout masks identical
        # to an uninterrupted run.
        loaded = load_tree(optim_dir, {
            "master_params": tmpl_master,
            "opt_state": tmpl_opt,
            "scaler": state.scaler,
            "rng": state.rng,
            "data_rng": engine._data_rng,
        })
        master, opt_state = engine._adopt_loaded(
            loaded["master_params"], loaded["opt_state"])
        scaler = loaded["scaler"]
        rng = loaded["rng"]
        engine._data_rng = loaded["data_rng"]
    else:
        # fp16-cast restore: module weights promoted to a fresh fp32 master
        from . import precision
        module_tmpl = precision.cast_to_compute(
            tmpl_master, engine.compute_dtype)
        loaded = load_tree(os.path.join(ckpt_dir, "model"),
                           {"module": module_tmpl})
        def _promote(cur, new):
            arr = np.asarray(jax.device_get(new)).astype(cur.dtype)
            sharding = getattr(cur, "sharding", None)  # numpy (offload): none
            from jax.sharding import NamedSharding
            if isinstance(sharding, NamedSharding):
                return jax.device_put(arr, sharding)
            return arr

        master = jax.tree.map(_promote, tmpl_master, loaded["module"])
        if getattr(engine, "_offload", False):
            # offload tiers rebuild their own fresh moments (host tier in
            # _sync_offload_from_state, xla tier in _adopt_loaded);
            # materializing device fp32 moments here would transiently cost
            # 2× model size in HBM — the exact memory offload exists to avoid
            opt_state = None
        else:
            # engine-internal form (e.g. 1-bit Adam's stacked per-worker
            # error buffers at dp>1 — plain optimizer.init would build a
            # world=1 state the compiled shard_map step cannot consume)
            opt_state = engine._fresh_opt_state(master)
        master, opt_state = engine._adopt_loaded(master, opt_state)
        scaler = state.scaler

    # Scalars get the same explicit replicated placement as engine init
    # (cache-key stability; see DeepSpeedEngine._place_scalar).
    place_scalar = engine._place_scalar
    engine.state = TrainState(
        master_params=master,
        opt_state=opt_state,
        scaler=jax.tree.map(place_scalar, scaler),
        global_steps=place_scalar(
            jnp.asarray(meta["global_steps"], jnp.int32)),
        skipped_steps=place_scalar(
            jnp.asarray(meta["skipped_steps"], jnp.int32)),
        rng=place_scalar(rng),
    )
    engine.global_steps = meta["global_steps"]
    engine.micro_steps = meta["micro_steps"]
    engine.skipped_steps = meta["skipped_steps"]
    if getattr(engine, "_offload_host", False):
        # host tier: copy the loaded arrays back into the native host-Adam
        # buffers here (not in the engine wrapper) so calling this public
        # function directly leaves the engine consistent too
        engine._sync_offload_from_state()
    log_dist(
        f"loaded checkpoint {ckpt_dir} (saved at dp={meta['dp_world_size']} "
        f"zero={meta['zero_stage']}; now dp={engine.dp_world_size} "
        f"zero={engine.config.zero_optimization_stage})", ranks=[0])
    return ckpt_dir, meta.get("client_state", {})
