"""Checkpoint save / load — two-plane scheme with reshard-on-load.

The reference writes a *model plane* (fp16 module weights + engine counters,
one file per MP rank: reference deepspeed/runtime/engine.py:1211-1236) and a
*ZeRO plane* (per-DP-rank partitioned fp32 master weights + optimizer state:
engine.py:1218-1229, zero/stage2.py:1675-1706), and supports loading ZeRO
checkpoints at a *different* DP world size by merging and re-partitioning
(stage2.py:1712-1778, stage1.py:836-941).

On TPU the partitioning is a sharding annotation, not a file layout, so the
natural design is: save the *logical* (unpartitioned) arrays once, and
re-apply the current engine's shardings at load time.  Resharding across any
mesh change (DP resize, ZeRO stage change, TP change) then falls out of
``jax.device_put`` — the elastic-restore feature costs nothing.

Layout of ``<save_dir>/<tag>/``:
  - ``meta.json``                       counters, world info, client_state,
                                        ``format_version`` + manifest digests
  - ``model/manifest.json  + *.npy``    module weights in compute dtype
  - ``optim/manifest.json  + *.npy``    fp32 master + optimizer state + scaler

``<save_dir>/latest`` holds the most recent tag (reference engine.py:1406).
Non-numpy-native dtypes (bfloat16) are stored as bit-pattern views with the
logical dtype recorded in the manifest.

Fault tolerance (docs/checkpointing.md; primitives in ``resilience.py``):

  - **Integrity plane** — every manifest entry records a per-leaf CRC32 and
    byte length; ``meta.json`` records a ``format_version`` and the SHA-256
    of each plane's manifest.  ``load_tree`` verifies lazily per leaf read
    and raises a typed ``CheckpointCorruptError`` naming the leaf/file.
  - **Async saves** — ``save_checkpoint(..., async_write=True)`` snapshots
    device state to host (D2H drained inside a ``checkpoint/snapshot``
    span), then the engine's daemon writer serializes + fsyncs + atomically
    renames off the hot path.  Async and sync saves share ONE write path,
    so their bytes are identical.
  - **Fallback chain** — ``load_checkpoint(tag=None)`` distinguishes
    MISSING / CORRUPT / OK; a corrupt or vanished latest walks back to the
    newest tag that verifies (bounded by ``checkpoint.load_fallback``).
    An EXPLICIT ``tag=`` that doesn't verify raises instead of masquerading
    as "nothing to load".
  - **Retention** — ``checkpoint.keep_last_n`` GCs old tags and orphaned
    ``*.tmp`` dirs only AFTER a new save verifies.
  - **Retry** — every read/write retries with exponential backoff + jitter
    (``checkpoint.io_retry_*``); ``DS_CKPT_FAULT`` injects failures for
    tests and ``DS_CKPT_DELAY_S`` injects write latency for overlap proofs.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import threading
import time
import weakref
import zlib
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import numpy as np

from ..utils.logging import log_dist, logger
from .resilience import (AsyncCheckpointWriter, CheckpointCorruptError,
                         CheckpointError, CheckpointJob,
                         CheckpointMissingError, CKPT_CORRUPT,
                         CKPT_FORMAT_VERSION, CKPT_MISSING, CKPT_OK,
                         DEFAULT_RETRY, RetryPolicy, fault_point,
                         io_retry, retention_gc, list_tags, sweep_tmp)

LATEST_FILE = "latest"


def _tel_span(engine, name: str, **args):
    """Per-plane telemetry span via the engine's hub (nullcontext when
    telemetry is off or the caller isn't a full engine — this module's
    public API also accepts engine-shaped ducks in tests)."""
    span = getattr(engine, "_tel_span", None)
    if span is None:
        return contextlib.nullcontext()
    return span(name, cat="checkpoint", **args)


# ---------------------------------------------------------------------------
# telemetry sink (counters reachable from helpers + the writer thread)
# ---------------------------------------------------------------------------
_TEL = threading.local()


@contextlib.contextmanager
def _tel_sink(engine):
    """Bind the engine's metrics registry for this thread so the deep
    write/read helpers (and retention GC) can bump counters without
    threading a handle through every call."""
    reg = getattr(getattr(engine, "telemetry", None), "registry", None)
    prev = getattr(_TEL, "reg", None)
    _TEL.reg = reg
    try:
        yield
    finally:
        _TEL.reg = prev


def _count(name: str, help: str, n: float = 1):
    reg = getattr(_TEL, "reg", None)
    if reg is not None and n:
        reg.counter(name, help).inc(n)


def _on_retry(_attempt, _exc):
    _count("ckpt_retries_total",
           "checkpoint I/O retries (transient OSError, backed off)")


# ---------------------------------------------------------------------------
# resolved checkpoint config (engine-shaped ducks get defaults)
# ---------------------------------------------------------------------------
class _CkptCfg(NamedTuple):
    retry: RetryPolicy = DEFAULT_RETRY
    keep_last_n: int = 0
    load_fallback: int = 2


def _ckpt_config(engine) -> _CkptCfg:
    cc = getattr(getattr(engine, "config", None), "checkpoint_config", None)
    if cc is None:
        return _CkptCfg()
    return _CkptCfg(
        retry=RetryPolicy(attempts=int(cc.io_retry_attempts),
                          base_s=float(cc.io_retry_base_s)),
        keep_last_n=int(cc.keep_last_n),
        load_fallback=int(cc.load_fallback))


# ---------------------------------------------------------------------------
# leaf codec
# ---------------------------------------------------------------------------
def _to_storage(arr: np.ndarray) -> Tuple[np.ndarray, str]:
    """Return (storable array, logical dtype name)."""
    logical = arr.dtype.name
    if arr.dtype.kind == "V" or logical in ("bfloat16", "float8_e4m3fn",
                                            "float8_e5m2"):
        itemsize = arr.dtype.itemsize
        view_dtype = {1: np.uint8, 2: np.uint16, 4: np.uint32}[itemsize]
        return arr.view(view_dtype), logical
    return arr, logical


def _from_storage(arr: np.ndarray, logical: str) -> np.ndarray:
    if arr.dtype.name != logical:
        import ml_dtypes
        return arr.view(np.dtype(getattr(ml_dtypes, logical)))
    return arr


def _crc32_arr(arr: np.ndarray) -> int:
    """CRC32 of the array's raw data bytes (the integrity record every
    manifest entry carries).  Computed on the STORAGE array, so it matches
    what ``np.load`` returns before any logical-dtype view."""
    a = np.ascontiguousarray(arr)
    try:
        buf = memoryview(a).cast("B")
    except (TypeError, ValueError):
        buf = a.tobytes()
    return zlib.crc32(buf) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# fsync'd, retried, fault-injectable file primitives
# ---------------------------------------------------------------------------
def _fsync_enabled() -> bool:
    """Per-file fsync before the atomic rename (power-loss durability).
    Default ON.  ``DS_CKPT_FSYNC=0`` is the test/CI escape hatch: unit
    tests simulate process death, which the page cache survives, and on
    slow test filesystems (9p, overlay) each fsync costs tens of ms per
    file.  Even with fsync off, a power loss that corrupts the newest
    checkpoint is caught by the CRC plane and recovered via the
    fallback chain — fsync narrows the window, the integrity plane
    closes it."""
    return os.environ.get("DS_CKPT_FSYNC", "1") != "0"


def _write_npy(path: str, store: np.ndarray,
               retry: RetryPolicy, point: str = "leaf") -> None:
    def write():
        fault_point(point, path)
        with open(path, "wb") as f:
            np.save(f, store, allow_pickle=False)
            f.flush()
            if _fsync_enabled():
                os.fsync(f.fileno())
    io_retry(write, f"write {path}", retry, on_retry=_on_retry)


def _write_bytes(path: str, data: bytes, retry: RetryPolicy,
                 point: str) -> None:
    def write():
        fault_point(point, path)
        with open(path, "wb") as f:
            f.write(data)
            f.flush()
            if _fsync_enabled():
                os.fsync(f.fileno())
    io_retry(write, f"write {path}", retry, on_retry=_on_retry)


def _read_npy(path: str, retry: RetryPolicy, key: str) -> np.ndarray:
    def read():
        fault_point("read", path)
        return np.load(path, allow_pickle=False)
    try:
        return io_retry(read, f"read {path}", retry, on_retry=_on_retry)
    except FileNotFoundError:
        raise CheckpointCorruptError(
            f"checkpoint leaf {key!r}: file {path} is missing")
    except (ValueError, EOFError, OSError) as e:
        raise CheckpointCorruptError(
            f"checkpoint leaf {key!r}: file {path} is unreadable "
            f"({type(e).__name__}: {e})")


def _read_json(path: str, what: str, retry: RetryPolicy) -> Any:
    def read():
        fault_point("read", path)
        with open(path, "rb") as f:
            return f.read()
    try:
        data = io_retry(read, f"read {path}", retry, on_retry=_on_retry)
    except OSError as e:
        # same typed contract as _read_npy: a missing/unreadable piece of
        # a checkpoint IS corruption — the fallback chain catches this
        # and walks back instead of crashing the resume
        raise CheckpointCorruptError(
            f"checkpoint {what} at {path} is unreadable "
            f"({type(e).__name__}: {e})")
    try:
        return json.loads(data)
    except ValueError as e:
        raise CheckpointCorruptError(
            f"checkpoint {what} at {path} is unparseable: {e}")


def _fsync_dir(path: str) -> None:
    """POSIX durability for the atomic rename itself."""
    if not _fsync_enabled():
        return
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _verify_leaf(arr: np.ndarray, entry: Dict[str, Any], key: str,
                 path: str) -> None:
    """Lazy per-leaf integrity check (manifest entries without a CRC are
    pre-integrity-plane checkpoints — loaded on trust, like the reference)."""
    want_crc = entry.get("crc32")
    if want_crc is None:
        return
    want_bytes = entry.get("nbytes")
    if want_bytes is not None and int(arr.nbytes) != int(want_bytes):
        raise CheckpointCorruptError(
            f"checkpoint leaf {key!r}: file {path} has {arr.nbytes} data "
            f"bytes, manifest records {want_bytes} (truncated write?)")
    got = _crc32_arr(arr)
    if got != int(want_crc):
        raise CheckpointCorruptError(
            f"checkpoint leaf {key!r}: file {path} CRC32 mismatch "
            f"(stored {int(want_crc):#010x}, computed {got:#010x}) — "
            "bit corruption or partial write")


def _split_merge_compatible(src: tuple, dst: tuple) -> bool:
    """True iff ``dst`` is reachable from ``src`` by only SPLITTING a dim
    into adjacent factors or MERGING adjacent dims — the reshapes that
    preserve the logical row-major layout (qkv [d, 3d] <-> [d, 3, d]).
    Greedy boundary alignment: walk both shapes accumulating products
    until they agree; within each aligned group at least one side must be
    a single dim (pure split or pure merge).  A permutation like
    (768, 2304) -> (2304, 768) forms one multi-dim x multi-dim group and
    is rejected even though the element counts match."""
    if int(np.prod(src, dtype=np.int64)) != int(np.prod(dst,
                                                        dtype=np.int64)):
        return False
    # Size-1 dims are layout-neutral in row-major order — drop them first
    # so e.g. (1, 4) -> (2, 2) aligns as the pure split it is instead of
    # the 1-dim getting absorbed into a multi x multi group.
    src = tuple(d for d in src if d != 1)
    dst = tuple(d for d in dst if d != 1)
    i = j = 0
    while i < len(src) and j < len(dst):
        a, b = int(src[i]), int(dst[j])
        ni, nj = 1, 1
        while a != b:
            if a < b:
                i += 1
                if i >= len(src):
                    return False
                a *= int(src[i])
                ni += 1
            else:
                j += 1
                if j >= len(dst):
                    return False
                b *= int(dst[j])
                nj += 1
        if ni > 1 and nj > 1:
            return False
        i += 1
        j += 1
    return all(d == 1 for d in src[i:]) and all(d == 1 for d in dst[j:])


def _keystr(path) -> str:
    return jax.tree_util.keystr(path)


# ---------------------------------------------------------------------------
# tree save / load
# ---------------------------------------------------------------------------
def _is_fully_addressable(leaf) -> bool:
    return bool(getattr(leaf, "is_fully_addressable", True))


def save_tree(dirpath: str, tree: Any,
              retry: RetryPolicy = DEFAULT_RETRY) -> str:
    """Write every leaf of ``tree`` as .npy files plus a manifest mapping
    pytree key-paths to files (with per-leaf CRC32 + byte length).
    Returns the SHA-256 hex digest of the manifest file as written
    (process 0; "" elsewhere) so ``meta.json`` can pin it.

    Multi-host: a leaf that is NOT fully addressable (its shards live on
    several processes) is written as per-process shard files — each
    process saves only the shards it owns (replica 0 of each), with the
    global index recorded per shard.  This is the analogue of the
    reference's per-DP-rank ``zero_pp_rank_D_...`` partitioned files
    (reference engine.py:1218-1229); load merges them
    (``stage2.py:1712-1778``'s merge without the repartition math, which
    reshard-on-load makes unnecessary).  Every process must call this
    function; process 0 writes the manifest.
    """
    os.makedirs(dirpath, exist_ok=True)
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    pid = jax.process_index()
    manifest: Dict[str, Dict[str, Any]] = {}
    for i, (path, leaf) in enumerate(flat):
        if _is_fully_addressable(leaf):
            if pid == 0:
                arr = np.asarray(jax.device_get(leaf))
                store, logical = _to_storage(arr)
                fname = f"leaf_{i:05d}.npy"
                _write_npy(os.path.join(dirpath, fname), store, retry)
                manifest[_keystr(path)] = {
                    "file": fname,
                    "dtype": logical,
                    "shape": list(arr.shape),
                    "crc32": _crc32_arr(store),
                    "nbytes": int(store.nbytes),
                }
            continue
        # process-local shards (multi-host)
        indices = []
        logical = str(leaf.dtype)
        store_dtype = logical
        for k, shard in enumerate(leaf.addressable_shards):
            if shard.replica_id != 0:
                continue
            arr = np.asarray(shard.data)
            store, logical = _to_storage(arr)
            store_dtype = store.dtype.name
            fname = f"leaf_{i:05d}.proc{pid}_{k}.npy"
            _write_npy(os.path.join(dirpath, fname), store, retry)
            indices.append({
                "file": fname,
                "index": [[s.start, s.stop] for s in
                          _normalize_index(shard.index, leaf.shape)],
                "crc32": _crc32_arr(store),
                "nbytes": int(store.nbytes),
            })
        if pid == 0:
            manifest[_keystr(path)] = {
                "sharded": True,
                "leaf": i,
                "dtype": logical,
                "store_dtype": store_dtype,
                "shape": list(leaf.shape),
            }
        # every process records its own shard index file
        _write_bytes(
            os.path.join(dirpath, f"leaf_{i:05d}.proc{pid}.json"),
            json.dumps(indices).encode(), retry, point="shard_index")
    if pid == 0:
        data = json.dumps(manifest, indent=1).encode()
        _write_bytes(os.path.join(dirpath, "manifest.json"), data, retry,
                     point="manifest")
        return hashlib.sha256(data).hexdigest()
    return ""


def _normalize_index(index, shape):
    """Shard index (tuple of slices) → concrete [start, stop] per dim."""
    out = []
    for dim, s in enumerate(index):
        start = 0 if s.start is None else int(s.start)
        stop = shape[dim] if s.stop is None else int(s.stop)
        out.append(slice(start, stop))
    return out


def _addressable_ranges(tleaf):
    """This process's addressable [start, stop] index boxes for a target
    leaf, or None when unknown (numpy template / no sharding) — used to
    skip reading other hosts' shard files on load."""
    from jax.sharding import NamedSharding
    sharding = getattr(tleaf, "sharding", None)
    shape = tuple(getattr(tleaf, "shape", ()))
    if not isinstance(sharding, NamedSharding) or jax.process_count() == 1:
        return None
    try:
        imap = sharding.devices_indices_map(shape)
    except Exception:
        return None
    boxes = []
    for dev, idx in imap.items():
        if dev.process_index != jax.process_index():
            continue
        boxes.append([[0 if s.start is None else int(s.start),
                       shape[d] if s.stop is None else int(s.stop)]
                      for d, s in enumerate(idx)])
    return boxes


def _ranges_intersect(shard_index, boxes) -> bool:
    for box in boxes:
        if all(a < bstop and b > bstart
               for (a, b), (bstart, bstop) in zip(shard_index, box)):
            return True
    return False


def load_tree(dirpath: str, target: Any, strict: bool = True,
              retry: RetryPolicy = DEFAULT_RETRY) -> Any:
    """Load leaves by key-path into the structure of ``target``.  Each loaded
    array is placed with the corresponding target leaf's sharding — this is
    the reshard-on-load that makes DP-resize restore work (reference
    stage2.py:1712-1778 does this with explicit merge/repartition).

    Integrity: each leaf read is verified lazily against the manifest's
    CRC32/byte-length record (when present — pre-integrity checkpoints
    load on trust); a mismatch raises ``CheckpointCorruptError`` naming
    the leaf and file, BEFORE any engine state is touched."""
    manifest = _read_json(os.path.join(dirpath, "manifest.json"),
                          "manifest", retry)
    flat, treedef = jax.tree_util.tree_flatten_with_path(target)
    out = []
    for path, tleaf in flat:
        key = _keystr(path)
        entry = manifest.get(key)
        if entry is None:
            if strict:
                raise KeyError(
                    f"checkpoint at {dirpath} has no entry for {key!r}")
            log_dist(f"checkpoint {dirpath}: no entry for {key!r}; "
                     "keeping the engine's current value", ranks=[0])
            out.append(tleaf)
            continue
        if entry.get("sharded"):
            # merge-on-load of per-process shard files (reference
            # stage2.py:1712-1778 merges per-rank partitions the same way)
            import glob as _glob
            store_dtype = entry.get("store_dtype", entry["dtype"])
            # a manifest written by a process that owned no replica-0
            # shards records the LOGICAL dtype; map it to the storage view
            # the shard files actually contain.  Must branch on the NAME:
            # np.dtype('bfloat16') succeeds (ml_dtypes registers it), and
            # an arr of bfloat16 would VALUE-cast the uint16 bit patterns
            # instead of reinterpreting them.
            sd = {"bfloat16": np.dtype(np.uint16),
                  "float8_e4m3fn": np.dtype(np.uint8),
                  "float8_e5m2": np.dtype(np.uint8)}.get(
                store_dtype, None) or np.dtype(store_dtype)
            # np.zeros is calloc-backed: pages only materialize where
            # shards are written, so RAM cost ≈ the bytes actually needed
            arr = np.zeros(tuple(entry["shape"]), sd)
            idx_files = sorted(_glob.glob(os.path.join(
                dirpath, f"leaf_{entry['leaf']:05d}.proc*.json")))
            if not idx_files:
                raise CheckpointCorruptError(
                    f"sharded checkpoint leaf {key!r}: no shard index "
                    f"files in {dirpath} (were all processes' files "
                    "copied to a shared location?)")
            need = _addressable_ranges(tleaf)
            for jf in idx_files:
                for shard in _read_json(jf, "shard index", retry):
                    if need is not None and not _ranges_intersect(
                            shard["index"], need):
                        continue  # another host's slice — skip the I/O
                    spath = os.path.join(dirpath, shard["file"])
                    data = _read_npy(spath, retry, key)
                    _verify_leaf(data, shard, key, spath)
                    sl = tuple(slice(a, b) for a, b in shard["index"])
                    arr[sl] = data
            arr = _from_storage(arr, entry["dtype"])
        else:
            fpath = os.path.join(dirpath, entry["file"])
            arr = _read_npy(fpath, retry, key)
            _verify_leaf(arr, entry, key, fpath)
            arr = _from_storage(arr, entry["dtype"])
        tshape = tuple(getattr(tleaf, "shape", ()))
        if tuple(arr.shape) != tshape:
            # Pipeline-resize elastic restore: stage-local stacked leaves
            # are [num_stages, layers_per_stage, ...]; stage ranges are
            # contiguous, so flattening the two leading dims is a canonical
            # layer order and a checkpoint saved at pp=2 reshapes losslessly
            # onto a pp=4 engine (reference analogue: ZeRO checkpoint
            # merge/re-partition across DP sizes, stage2.py:1712-1778).
            if ("stack_" in key
                    and len(arr.shape) >= 2 and len(tshape) >= 2
                    and arr.shape[2:] == tshape[2:]
                    and arr.shape[0] * arr.shape[1]
                    == tshape[0] * tshape[1]):
                arr = arr.reshape(tshape)
                log_dist(
                    f"checkpoint leaf {key!r}: restacked "
                    f"{entry['shape']} -> {list(tshape)} (pipeline resize)",
                    ranks=[0])
            elif _split_merge_compatible(tuple(arr.shape), tshape):
                # Size-preserving layout evolution: dims purely split or
                # merged (e.g. the qkv [.., d, 3d] -> [.., d, 3, d]
                # re-layout — row-major order unchanged) reshape
                # losslessly.  Equal element count alone is NOT enough: a
                # permuted layout like [768, 2304] -> [2304, 768] would
                # reshape into numeric garbage, so those still raise.
                # Logged loudly so the restore log shows every re-layout.
                arr = arr.reshape(tshape)
                log_dist(
                    f"checkpoint leaf {key!r}: reshaped "
                    f"{entry['shape']} -> {list(tshape)} (size-preserving "
                    "layout change)", ranks=[0])
            else:
                raise ValueError(
                    f"checkpoint leaf {key!r} has shape {arr.shape}, engine "
                    f"expects {tshape} — model/optimizer config mismatch")
        sharding = getattr(tleaf, "sharding", None)
        tdtype = getattr(tleaf, "dtype", arr.dtype)
        arr = arr.astype(tdtype) if arr.dtype != tdtype else arr
        # Re-apply only mesh-aware placements; committing scalars to a single
        # device would pin them and conflict with the mesh under jit.  numpy
        # targets (offload host/flat staging templates) stay numpy — putting
        # a multi-GB offloaded master on device here would defeat offload.
        from jax.sharding import NamedSharding
        if isinstance(sharding, NamedSharding):
            if jax.process_count() > 1:
                # multi-controller: each process materializes only its own
                # addressable shards of the global array
                out.append(jax.make_array_from_callback(
                    tuple(arr.shape), sharding,
                    lambda idx, a=arr: a[idx]))
            else:
                out.append(jax.device_put(arr, sharding))
        elif isinstance(tleaf, np.ndarray):
            out.append(arr)
        else:
            out.append(jax.numpy.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# data-iterator plane codec (sample-exact resume; docs/elastic.md)
# ---------------------------------------------------------------------------
def _iter_state_plane(state: Any) -> Any:
    """Encode a JSON-able iterator state as a one-leaf tree so the data
    plane rides the SAME machinery as model/optim (save_tree → per-leaf
    CRC32 + manifest + meta digest, every DS_CKPT_FAULT write point)."""
    data = json.dumps(state).encode()
    return {"state": np.frombuffer(data, np.uint8)}


def _load_iter_state_plane(ckpt_dir: str, retry: RetryPolicy) -> Any:
    """Decode the data-iterator plane: manifest-driven, CRC-verified per
    leaf like the other planes (the manifest has exactly one entry)."""
    ddir = os.path.join(ckpt_dir, "data")
    manifest = _read_json(os.path.join(ddir, "manifest.json"),
                          "data-iterator manifest", retry)
    if len(manifest) != 1:
        raise CheckpointCorruptError(
            f"data-iterator plane at {ddir} has {len(manifest)} manifest "
            "entries, expected exactly 1")
    (key, entry), = manifest.items()
    fpath = os.path.join(ddir, entry["file"])
    arr = _read_npy(fpath, retry, key)
    _verify_leaf(arr, entry, key, fpath)
    try:
        return json.loads(bytes(arr.tobytes()).decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise CheckpointCorruptError(
            f"data-iterator plane at {fpath} is unparseable: {e}")


def _capture_iter_state(engine) -> Optional[Any]:
    """The engine's data-iterator state, or None (engine-shaped ducks in
    tests / engines without a checkpointable loader save no data plane —
    those checkpoints load exactly like legacy ones)."""
    fn = getattr(engine, "data_iterator_state", None)
    return fn() if callable(fn) else None


# ---------------------------------------------------------------------------
# verification (status without loading)
# ---------------------------------------------------------------------------
def _manifest_digest_error(ckpt_dir: str, plane: str, want: str,
                           retry: RetryPolicy = DEFAULT_RETRY
                           ) -> Tuple[Optional[str], Optional[dict]]:
    """ONE implementation of the manifest-digest check (used by both
    checkpoint_status and the load path, so they can never disagree on
    what counts as corrupt): returns (error, parsed_manifest)."""
    mpath = os.path.join(ckpt_dir, plane, "manifest.json")

    def read():
        fault_point("read", mpath)
        with open(mpath, "rb") as f:
            return f.read()
    try:
        # retried like every other checkpoint read: a transient blip
        # here would otherwise condemn a good checkpoint as corrupt
        data = io_retry(read, f"read {mpath}", retry, on_retry=_on_retry)
    except OSError as e:
        return f"{mpath}: {e}", None
    if hashlib.sha256(data).hexdigest() != want:
        return (f"{mpath}: manifest digest mismatch — the manifest was "
                "modified or truncated after the save"), None
    try:
        return None, json.loads(data)
    except ValueError as e:
        return f"{mpath}: unparseable ({e})", None


def checkpoint_status(ckpt_dir: str, deep: bool = False,
                      retry: RetryPolicy = DEFAULT_RETRY
                      ) -> Tuple[str, str]:
    """Classify a checkpoint directory: ``(CKPT_OK | CKPT_CORRUPT |
    CKPT_MISSING, detail)``.  Structural check: meta parses, manifest
    digests match, every referenced file exists with a plausible size.
    ``deep=True`` additionally re-reads every leaf and verifies its CRC
    (what the fallback chain uses before committing to a candidate)."""
    if not os.path.isdir(ckpt_dir):
        return CKPT_MISSING, f"no directory at {ckpt_dir}"
    meta_path = os.path.join(ckpt_dir, "meta.json")
    if not os.path.isfile(meta_path):
        return CKPT_CORRUPT, (f"{ckpt_dir} has no meta.json "
                              "(crashed or partial save)")
    try:
        meta = _read_json(meta_path, "meta.json", retry)
    except (CheckpointCorruptError, OSError) as e:
        return CKPT_CORRUPT, str(e)
    digests = meta.get("manifest_digests") or {}
    for plane, want in sorted(digests.items()):
        err, manifest = _manifest_digest_error(ckpt_dir, plane, want,
                                               retry)
        if err:
            return CKPT_CORRUPT, err
        plane_dir = os.path.join(ckpt_dir, plane)
        err = _verify_manifest_files(plane_dir, manifest, deep, retry)
        if err:
            return CKPT_CORRUPT, err
    return CKPT_OK, ""


def _verify_manifest_files(plane_dir: str, manifest: dict, deep: bool,
                           retry: RetryPolicy) -> Optional[str]:
    import glob as _glob
    for key, entry in manifest.items():
        if entry.get("sharded"):
            idx_files = sorted(_glob.glob(os.path.join(
                plane_dir, f"leaf_{entry['leaf']:05d}.proc*.json")))
            if not idx_files:
                return f"{key!r}: no shard index files in {plane_dir}"
            try:
                shards = [s for jf in idx_files
                          for s in _read_json(jf, "shard index", retry)]
            except CheckpointCorruptError as e:
                return str(e)
        else:
            shards = [entry]
        for shard in shards:
            fpath = os.path.join(plane_dir, shard["file"])
            if not os.path.isfile(fpath):
                return f"{key!r}: file {fpath} is missing"
            nbytes = shard.get("nbytes")
            if nbytes is not None and os.path.getsize(fpath) < int(nbytes):
                return (f"{key!r}: file {fpath} is "
                        f"{os.path.getsize(fpath)} bytes, smaller than "
                        f"its {nbytes} recorded data bytes (truncated)")
            if deep and shard.get("crc32") is not None:
                try:
                    arr = _read_npy(fpath, retry, key)
                    _verify_leaf(arr, shard, key, fpath)
                except CheckpointCorruptError as e:
                    return str(e)
    return None


# ---------------------------------------------------------------------------
# engine-level save
# ---------------------------------------------------------------------------
def _host_snapshot(tree: Any) -> Any:
    """Materialize a tree fully on host, COPYING numpy leaves: the host
    offload tier's master/moments alias live staging buffers the next
    step's CPU Adam mutates in place, so an async writer must own its
    bytes.  ``device_get`` already copies device arrays (and is the D2H
    drain the ``checkpoint/snapshot`` span measures)."""
    def snap(x):
        if isinstance(x, np.ndarray):
            return np.array(x, copy=True)
        return np.asarray(jax.device_get(x))
    return jax.tree.map(snap, tree)


def _surface_writer_error(engine, err):
    if err is None:
        return
    logger.error(
        "previous async checkpoint save failed (that save was lost; "
        "this save proceeds from the current state): %s", err)
    # the training thread's advertised surface must record it too —
    # draining here would otherwise swallow the error before the
    # pre-step tick could pop it
    engine.last_ckpt_error = err
    with _tel_sink(engine):
        _count("ckpt_save_failures_total",
               "checkpoint saves that failed (async writer or sync)")


def _write_checkpoint_files(save_dir: str, tag: str, ckpt_dir: str,
                            tmp_dir: str, model_plane: Any,
                            optim_plane: Any, meta: dict,
                            save_latest: bool, keep_last_n: int,
                            retry: RetryPolicy, span=None,
                            data_plane: Any = None) -> str:
    """The single serialization path both sync and async saves share
    (which is what makes async==sync bitwise): tmp-dir staging, per-plane
    manifests with CRCs, meta with manifest digests, fsync, verification
    of the STAGED dir, swap-rename, ``latest`` update, then retention GC
    — destruction strictly AFTER the new save verifies.  ``span`` is an
    optional ``name -> context`` factory for the per-plane telemetry
    spans (the writer thread stamps its own tid).  ``data_plane`` is the
    optional data-iterator plane (sample-exact resume) — same CRC +
    digest discipline, absent when no checkpointable iterator is bound."""
    span = span or (lambda name: contextlib.nullcontext())
    # injected write latency (CPU overlap proofs): the unified
    # DS_STAGE_DELAY_S=ckpt:sec spec, or its legacy DS_CKPT_DELAY_S alias
    from .stages import injected_delay
    delay = injected_delay("ckpt")
    if delay > 0:
        time.sleep(delay)
    if os.path.isdir(tmp_dir):
        import shutil
        io_retry(lambda: shutil.rmtree(tmp_dir), f"clear {tmp_dir}", retry,
                 on_retry=_on_retry)
    os.makedirs(tmp_dir, exist_ok=True)
    with span("checkpoint/save_model_plane"):
        model_digest = save_tree(os.path.join(tmp_dir, "model"),
                                 model_plane, retry=retry)
    with span("checkpoint/save_optim_plane"):
        optim_digest = save_tree(os.path.join(tmp_dir, "optim"),
                                 optim_plane, retry=retry)
    meta = dict(meta)
    meta["format_version"] = CKPT_FORMAT_VERSION
    meta["manifest_digests"] = {"model": model_digest,
                                "optim": optim_digest}
    if data_plane is not None:
        with span("checkpoint/save_data_plane"):
            meta["manifest_digests"]["data"] = save_tree(
                os.path.join(tmp_dir, "data"), data_plane, retry=retry)
    _write_bytes(os.path.join(tmp_dir, "meta.json"),
                 json.dumps(meta, indent=1).encode(), retry, point="meta")
    # verify the STAGED dir before anything is destroyed or published: a
    # failed verify leaves an existing same-tag checkpoint AND `latest`
    # untouched (a load_fallback=0 resume keeps working) — the fallback
    # chain must always have a verified checkpoint to land on
    status, why = checkpoint_status(tmp_dir, deep=False, retry=retry)
    if status != CKPT_OK:
        raise CheckpointCorruptError(
            f"freshly written checkpoint staging {tmp_dir} failed "
            f"verification ({why}); `{LATEST_FILE}` untouched, retention "
            "GC skipped, nothing was deleted")
    _publish_staged(save_dir, tag, ckpt_dir, tmp_dir, save_latest,
                    keep_last_n, retry)
    log_dist(f"saved checkpoint {ckpt_dir}", ranks=[0])
    return ckpt_dir


def _publish_staged(save_dir: str, tag: str, ckpt_dir: str, tmp_dir: str,
                    save_latest: bool, keep_last_n: int,
                    retry: RetryPolicy) -> None:
    """Publish a VERIFIED staged checkpoint: swap-rename (an existing
    same-tag checkpoint is parked at ``<tag>.replaced.tmp`` and restored
    if the publish fails — a re-save can never destroy the only copy),
    fsync the dir, move ``latest``, then retention GC.  ONE copy of this
    sequence serves both the single-process and multi-host save paths."""
    import shutil
    old_dir = None
    if os.path.isdir(ckpt_dir):
        old_dir = ckpt_dir + ".replaced.tmp"
        if os.path.isdir(old_dir):
            io_retry(lambda: shutil.rmtree(old_dir),
                     f"clear {old_dir}", retry, on_retry=_on_retry)
        io_retry(lambda: os.rename(ckpt_dir, old_dir),
                 f"park {ckpt_dir}", retry, on_retry=_on_retry)

    def rename():
        fault_point("rename", ckpt_dir)
        os.rename(tmp_dir, ckpt_dir)
    try:
        io_retry(rename, f"rename {tmp_dir} -> {ckpt_dir}", retry,
                 on_retry=_on_retry)
    except Exception:
        if old_dir is not None:
            try:
                os.rename(old_dir, ckpt_dir)  # restore the old good copy
            except OSError as e:
                logger.error("could not restore %s after failed publish: "
                             "%s (parked at %s)", ckpt_dir, e, old_dir)
        raise
    if old_dir is not None:
        try:
            shutil.rmtree(old_dir)
        except OSError:
            pass  # orphan sweep reclaims it on the next save
    _fsync_dir(save_dir)
    _count("ckpt_saves_total", "checkpoints written and verified")
    if save_latest:
        def write_latest():
            fault_point("latest", save_dir)
            latest_tmp = os.path.join(save_dir, LATEST_FILE + ".tmp")
            with open(latest_tmp, "w") as f:
                f.write(tag)
                f.flush()
                if _fsync_enabled():
                    os.fsync(f.fileno())
            os.replace(latest_tmp, os.path.join(save_dir, LATEST_FILE))
        io_retry(write_latest, f"update {save_dir}/{LATEST_FILE}", retry,
                 on_retry=_on_retry)
    if keep_last_n > 0:
        # protect the tag `latest` names too: with save_latest=False side
        # tags, the latest-named checkpoint can fall outside the
        # newest-N window and must never be GC'd
        protect = {tag}
        latest_path = os.path.join(save_dir, LATEST_FILE)
        try:
            with open(latest_path) as f:
                protect.add(f.read().strip())
        except OSError:
            pass
        removed = retention_gc(save_dir, keep_last_n, protect=protect,
                               retry=retry)
        _count("ckpt_gc_removed_total",
               "old checkpoint tags + orphaned tmp dirs reclaimed",
               removed)


def _build_save_job(engine, save_dir: str, tag: str, ckpt_dir: str,
                    tmp_dir: str, client_state: Optional[dict],
                    save_latest: bool, cfg: _CkptCfg,
                    async_write: bool) -> CheckpointJob:
    """Snapshot device state to host NOW (D2H drained inside the
    ``checkpoint/snapshot`` span — the only step-loop-exposed cost of an
    async save), and return a fully host-resident write job."""
    from . import precision

    state = engine.state
    tracer = getattr(getattr(engine, "telemetry", None), "tracer", None)
    ctx = None
    with _tel_span(engine, "checkpoint/snapshot", tag=tag):
        if tracer is not None:
            # causal arrow: flow opened inside the submitting step's
            # save/snapshot span, terminated inside the writer's
            # async_write span (host-side appends only)
            from ..telemetry.tracing import TraceContext
            ctx = TraceContext.new()
            tracer.flow_start("checkpoint/job", ctx, cat="checkpoint",
                              tag=tag)
        master_tree, opt_tree = engine._canonical_state()
        module_params = precision.cast_to_compute(
            master_tree, engine.compute_dtype)
        model_plane = {"module": module_params}
        optim_plane = {
            "master_params": master_tree,
            "opt_state": opt_tree,
            "scaler": state.scaler,
            "rng": state.rng,
            "data_rng": engine._data_rng,
        }
        if async_write:
            # the host COPY is what makes the job immune to the training
            # that continues while the writer serializes (the host-offload
            # staging buffers are mutated in place by the next step's CPU
            # Adam).  A sync save runs the job before returning, so it
            # streams the live leaves straight into np.save instead of
            # paying a full master+moments copy (18+ GB at 1.5B).
            model_plane = _host_snapshot(model_plane)
            optim_plane = _host_snapshot(optim_plane)
        # data-iterator plane: captured NOW (at snapshot time, so an
        # async save records the consumption point matching the model
        # state) and already a private bytes copy — training that
        # continues while the writer runs cannot bleed into it
        iter_state = _capture_iter_state(engine)
        data_plane = (_iter_state_plane(iter_state)
                      if iter_state is not None else None)
    meta = {
        "tag": tag,
        "global_steps": int(engine.global_steps),
        "micro_steps": int(engine.micro_steps),
        "skipped_steps": int(state.skipped_steps),
        "dp_world_size": int(engine.dp_world_size),
        "zero_stage": int(engine.config.zero_optimization_stage),
        "client_state": client_state or {},
    }
    eng_ref = weakref.ref(engine)

    def run():
        eng = eng_ref()
        t0 = time.perf_counter()
        span = (_tel_span(eng, "checkpoint/async_write", tag=tag)
                if async_write and eng is not None
                else contextlib.nullcontext())
        with _tel_sink(eng), span:
            run_tracer = getattr(getattr(eng, "telemetry", None),
                                 "tracer", None)
            if ctx is not None and run_tracer is not None:
                # inside the write span: sync saves close the flow in
                # the save span itself, async saves on the writer thread
                run_tracer.flow_end("checkpoint/job", ctx,
                                    cat="checkpoint", tag=tag)
            _write_checkpoint_files(
                save_dir, tag, ckpt_dir, tmp_dir, model_plane,
                optim_plane, meta, save_latest, cfg.keep_last_n,
                cfg.retry,
                span=lambda name: _tel_span(eng, name, tag=tag),
                data_plane=data_plane)
        if async_write and eng is not None:
            acc = getattr(eng, "_ckpt_interval_acc", None)
            if acc is not None:
                # write wall time hidden behind training (the
                # ckpt_async_overlap_s telemetry scalar); under the
                # engine's acc lock — the telemetry sync's read-and-reset
                # runs on the training thread
                with getattr(eng, "_ckpt_acc_lock", contextlib.nullcontext()):
                    acc["overlap_s"] += time.perf_counter() - t0
                    # written saves, not submissions: coalesced-away
                    # saves never wrote, so dividing overlap by the
                    # submission count would under-report hidden time
                    acc["writes"] = acc.get("writes", 0) + 1
        return ckpt_dir

    return CheckpointJob(tag=tag, tmp_dir=tmp_dir, final_dir=ckpt_dir,
                         run=run, ctx=ctx)


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[dict] = None,
                    save_latest: bool = True,
                    async_write: bool = False) -> str:
    """Two-plane checkpoint write (reference engine.py:1211-1290).

    The write is atomic: everything lands in ``<tag>.tmp`` and is renamed
    into place only after ``meta.json`` (written last) is on disk, so a
    killed save can never leave a loadable-looking partial checkpoint.

    ``async_write=True`` (single-controller only) snapshots device state
    to host and hands serialization to the engine's daemon writer: the
    step loop pays only the D2H drain.  A second async save while one is
    in flight coalesces (latest wins); a sync save first drains the
    writer (ordering); a writer failure poisons only that save.

    The model plane intentionally duplicates a down-cast of the fp32 master
    (~0.5× extra bytes): it keeps module-only loads (inference handoff, the
    reference's fp16-cast restore) independent of the optimizer plane, same
    as the reference's mp_rank/zero_pp_rank file split.

    Multi-host: EVERY process MUST call this (same contract as the
    reference, where every rank writes its ZeRO partition files,
    engine.py:1218-1229) — guarding with ``if process_index() == 0`` will
    DEADLOCK the job at the internal barrier.  Fully-addressable leaves
    are written by process 0, non-addressable leaves as per-process shard
    files (see save_tree), with a cross-process barrier before the atomic
    rename.  Assumes a shared checkpoint directory (the pod-filesystem /
    GCS-fuse case); per-host local dirs need the shard files merged before
    load, which load_tree reports explicitly if missing.  (reference
    engine.py:415-416 writes model files from DP rank 0 and ZeRO
    partitions from every rank, engine.py:1218-1229.)
    """
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    tag = str(tag)
    ckpt_dir = os.path.join(save_dir, tag)
    tmp_dir = ckpt_dir + ".tmp"
    multiproc = jax.process_count() > 1
    proc0 = jax.process_index() == 0
    cfg = _ckpt_config(engine)
    writer: Optional[AsyncCheckpointWriter] = getattr(
        engine, "_ckpt_writer", None)
    if async_write and multiproc:
        log_dist("async checkpoint save is single-controller only; "
                 "writing synchronously", ranks=[0])
        async_write = False
    if not async_write and writer is not None and writer.in_flight():
        # ordering: a pending async save must land (or fail) before a
        # synchronous one renames over it / moves `latest` past it —
        # through the stage graph's own ckpt entry, so sync-save and
        # engine.close() share ONE drain code path (docs/stages.md)
        from .engine_stages import drain_ckpt_stage
        drain_ckpt_stage(engine)

    with _tel_sink(engine):
        if proc0:
            # hygiene: reclaim orphaned <*>.tmp dirs from crashed saves
            # (NOT just this tag's — the old code leaked every other
            # tag's debris forever), skipping the live writer's dirs
            keep = writer.active_tmp() if writer is not None else set()
            removed = sweep_tmp(save_dir, keep=keep, retry=cfg.retry)
            _count("ckpt_gc_removed_total",
                   "old checkpoint tags + orphaned tmp dirs reclaimed",
                   removed)
    if multiproc:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("ds_ckpt_clean")
        return _save_multiproc(engine, save_dir, tag, ckpt_dir, tmp_dir,
                               client_state, save_latest, cfg)

    job = _build_save_job(engine, save_dir, tag, ckpt_dir, tmp_dir,
                          client_state, save_latest, cfg, async_write)
    if async_write:
        if writer is None:
            writer = engine._ckpt_writer = AsyncCheckpointWriter(
                stage=getattr(engine, "_stage_records",
                              {}).get("ckpt_writer"))
        writer.submit(job)
        return ckpt_dir
    with _tel_sink(engine):
        try:
            job.run()
        except OSError as e:
            # exhausted-retry I/O failure: surface with the same typed
            # vocabulary the load side uses
            _count("ckpt_save_failures_total",
                   "checkpoint saves that failed (async writer or sync)")
            raise CheckpointError(
                f"checkpoint save to {ckpt_dir} failed after "
                f"{cfg.retry.attempts} attempts: {e}") from e
        except CheckpointError:
            # already typed (e.g. the fresh save failed its own verify);
            # count it the same way the writer path does
            _count("ckpt_save_failures_total",
                   "checkpoint saves that failed (async writer or sync)")
            raise
    return ckpt_dir


def _save_multiproc(engine, save_dir, tag, ckpt_dir, tmp_dir,
                    client_state, save_latest, cfg: _CkptCfg) -> str:
    """Multi-controller save: every process writes its shard files into
    the shared tmp dir; process 0 writes manifests + meta and performs
    the atomic rename behind a barrier (the pre-existing flow, now with
    the integrity plane + retry + retention)."""
    from . import precision
    from jax.experimental import multihost_utils

    state = engine.state
    proc0 = jax.process_index() == 0
    retry = cfg.retry
    os.makedirs(tmp_dir, exist_ok=True)
    master_tree, opt_tree = engine._canonical_state()
    module_params = precision.cast_to_compute(
        master_tree, engine.compute_dtype)
    with _tel_sink(engine):
        with _tel_span(engine, "checkpoint/save_model_plane"):
            model_digest = save_tree(os.path.join(tmp_dir, "model"),
                                     {"module": module_params}, retry=retry)
        with _tel_span(engine, "checkpoint/save_optim_plane"):
            optim_digest = save_tree(os.path.join(tmp_dir, "optim"), {
                "master_params": master_tree,
                "opt_state": opt_tree,
                "scaler": state.scaler,
                "rng": state.rng,
                "data_rng": engine._data_rng,
            }, retry=retry)
        # data-iterator plane: ONE global state from process 0.  The
        # loader contract already requires identical seeds/order on
        # every process (each feeds its own slice of the same global
        # batch sequence), so proc0's (epoch, batch_idx, rng) IS the
        # global consumption point — and stays meaningful when an
        # elastic restart resumes at a different process count.
        data_digest = None
        if proc0:
            iter_state = _capture_iter_state(engine)
            if iter_state is not None:
                data_digest = save_tree(
                    os.path.join(tmp_dir, "data"),
                    _iter_state_plane(iter_state), retry=retry)
        # every process's shard files must be on disk before the rename
        multihost_utils.sync_global_devices("ds_ckpt_written")
        if proc0:
            meta = {
                "tag": tag,
                "global_steps": int(engine.global_steps),
                "micro_steps": int(engine.micro_steps),
                "skipped_steps": int(state.skipped_steps),
                "dp_world_size": int(engine.dp_world_size),
                "zero_stage": int(engine.config.zero_optimization_stage),
                "client_state": client_state or {},
                "format_version": CKPT_FORMAT_VERSION,
                "manifest_digests": (
                    {"model": model_digest, "optim": optim_digest}
                    | ({"data": data_digest} if data_digest else {})),
            }
            _write_bytes(os.path.join(tmp_dir, "meta.json"),
                         json.dumps(meta, indent=1).encode(), retry,
                         point="meta")
            # same invariants as the single-process path: verify the
            # STAGED dir before anything is destroyed or published, and
            # replace a same-tag checkpoint by SWAP so the old copy
            # survives a failed publish.  On verify failure the raise is
            # DEFERRED past the final barrier so the other processes
            # don't hang at sync_global_devices while rank 0 unwinds.
            verify_err = None
            status, why = checkpoint_status(tmp_dir, deep=False,
                                            retry=retry)
            if status != CKPT_OK:
                verify_err = (
                    f"freshly written checkpoint staging {tmp_dir} "
                    f"failed verification ({why}); `{LATEST_FILE}` "
                    "untouched, retention GC skipped, nothing was "
                    "deleted")
                logger.error(verify_err)
            else:
                _publish_staged(save_dir, tag, ckpt_dir, tmp_dir,
                                save_latest, cfg.keep_last_n, retry)
        multihost_utils.sync_global_devices("ds_ckpt_done")
        if proc0 and verify_err is not None:
            raise CheckpointCorruptError(verify_err)
    log_dist(f"saved checkpoint {ckpt_dir}", ranks=[0])
    return ckpt_dir


# ---------------------------------------------------------------------------
# engine-level load
# ---------------------------------------------------------------------------
def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True,
                    load_lr_scheduler_states: bool = True,
                    load_module_only: bool = False):
    """Restore engine state; returns ``(load_path, client_state)`` like the
    reference (engine.py:1292-1324).  Handles a different current DP size /
    ZeRO stage / mesh than the one that saved (elastic restore).

    Availability semantics (the MISSING / CORRUPT / OK distinction the
    old code collapsed into ``(None, None)``):

      - ``tag=None`` with no ``latest`` file and no tag dirs → a fresh
        run: ``(None, None)``.
      - ``tag=None`` where ``latest`` names a missing or corrupt tag →
        logs LOUDLY and walks back to the newest on-disk tag that loads
        with every per-leaf CRC verified, bounded by
        ``checkpoint.load_fallback`` older candidates; raises
        ``CheckpointCorruptError`` if none do.  A resume never silently trains from scratch because one
        file rotted.
      - an EXPLICIT ``tag=`` that is absent raises
        ``CheckpointMissingError``; one that fails verification raises
        ``CheckpointCorruptError`` — both name the path.  An explicit
        request can never masquerade as "nothing to load".

    ``load_lr_scheduler_states`` is accepted for API parity but has no
    distinct effect: all lr schedules here are pure functions of the
    restored step count, so there is no separate scheduler state to load.
    """
    cfg = _ckpt_config(engine)
    retry = cfg.retry
    with _tel_sink(engine):
        if tag is not None:
            ckpt_dir = os.path.join(load_dir, str(tag))
            if not os.path.isdir(ckpt_dir):
                raise CheckpointMissingError(
                    f"checkpoint tag {str(tag)!r} was explicitly "
                    f"requested but {ckpt_dir} does not exist")
            if not os.path.isfile(os.path.join(ckpt_dir, "meta.json")):
                _count("ckpt_corrupt_total",
                       "checkpoints that failed integrity verification")
                raise CheckpointCorruptError(
                    f"checkpoint tag {str(tag)!r} at {ckpt_dir} has no "
                    "meta.json — a crashed or partial save, not a "
                    "loadable checkpoint")
            return _load_into_engine(
                engine, ckpt_dir, load_optimizer_states,
                load_module_only, retry)

        # tag=None: resolve `latest`, then walk the fallback chain
        latest_path = os.path.join(load_dir, LATEST_FILE)
        if not os.path.isfile(latest_path):
            hint = ""
            tags = list_tags(load_dir)
            if tags:
                hint = (f" ({len(tags)} tag dir(s) exist but no "
                        f"'{LATEST_FILE}' file names one — pass tag= "
                        "explicitly to load them)")
            log_dist(f"no 'latest' file in {load_dir}; nothing to "
                     f"load{hint}", ranks=[0])
            return None, None
        with open(latest_path) as f:
            latest_tag = f.read().strip()
        candidates = [latest_tag] + [t for t in list_tags(load_dir)
                                     if t != latest_tag]
        limit = 1 + max(int(cfg.load_fallback), 0)
        errors = []
        for i, t in enumerate(candidates[:limit]):
            d = os.path.join(load_dir, t)
            if not os.path.isfile(os.path.join(d, "meta.json")):
                _count("ckpt_corrupt_total",
                       "checkpoints that failed integrity verification")
                logger.error(
                    "checkpoint fallback: tag %r at %s is %s — trying "
                    "the next newest on-disk tag",
                    t, d, "missing" if not os.path.isdir(d)
                    else "missing its meta.json")
                errors.append(f"{t}: missing or no meta.json")
                continue
            # no deep pre-verify here: every leaf read inside the load
            # is CRC-checked lazily and a corrupt candidate raises
            # BEFORE any engine state is touched, so the except below
            # walks on — a pre-pass would just read multi-GB planes
            # twice per candidate
            try:
                return _load_into_engine(
                    engine, d, load_optimizer_states, load_module_only,
                    retry)
            except CheckpointCorruptError as e:
                _count("ckpt_corrupt_total",
                       "checkpoints that failed integrity verification")
                logger.error(
                    "checkpoint tag %r is CORRUPT (%s) — falling back to "
                    "the next newest tag that verifies", t, e)
                errors.append(f"{t}: {e}")
        raise CheckpointCorruptError(
            f"no loadable checkpoint under {load_dir}: tried "
            f"{min(len(candidates), limit)} candidate(s) "
            f"(checkpoint.load_fallback={cfg.load_fallback}); "
            + "; ".join(errors))


def _load_into_engine(engine, ckpt_dir: str, load_optimizer_states: bool,
                      load_module_only: bool, retry: RetryPolicy):
    """Restore from one verified-enough candidate dir.  All reads are
    integrity-checked lazily (manifest digest first, then per-leaf CRC
    inside load_tree); any corruption raises BEFORE engine state is
    replaced, so a caller can walk to an older tag safely."""
    from .engine import TrainState
    import jax.numpy as jnp

    meta = _read_json(os.path.join(ckpt_dir, "meta.json"), "meta.json",
                      retry)
    digests = meta.get("manifest_digests") or {}

    def check_digest(plane):
        want = digests.get(plane)
        if want is None:
            return  # pre-integrity checkpoint: load on trust
        err, _ = _manifest_digest_error(ckpt_dir, plane, want, retry)
        if err:
            raise CheckpointCorruptError(f"checkpoint {err}")

    state: TrainState = engine.state
    optim_dir = os.path.join(ckpt_dir, "optim")
    use_optim = (load_optimizer_states and not load_module_only
                 and os.path.isdir(optim_dir))
    # data-iterator plane (sample-exact resume): read + CRC/digest-verify
    # it NOW, before any engine state is replaced — a corrupt plane must
    # make the fallback chain walk to an older tag with the engine still
    # intact, exactly like the model/optim planes.  APPLICATION to the
    # loader happens at the end, after the state restore succeeds.
    # Module-only loads (inference handoff / fine-tune warmstart) skip
    # it: they are not a resume, so replaying data from the top is the
    # intended behavior.
    iter_state = None
    has_data_plane = ("data" in digests
                      or os.path.isdir(os.path.join(ckpt_dir, "data")))
    if has_data_plane and use_optim:
        check_digest("data")
        with _tel_span(engine, "checkpoint/load_data_plane"):
            iter_state = _load_iter_state_plane(ckpt_dir, retry)
    elif (not has_data_plane and use_optim
          and _capture_iter_state(engine) is not None):
        logger.warning(
            "checkpoint %s predates the data-iterator plane (or was "
            "saved without a checkpointable loader): the training data "
            "iterator starts FRESH — the resumed run will replay or "
            "skip data relative to the interrupted one (model/optimizer "
            "state restore exactly; see docs/elastic.md)", ckpt_dir)
    rng = state.rng
    tmpl_master, tmpl_opt = engine._canonical_templates()
    if use_optim:
        # fp32 master restore (reference 'load_from_fp32_weights',
        # stage2.py:1780-1835); rng restore keeps dropout masks identical
        # to an uninterrupted run.
        check_digest("optim")
        with _tel_span(engine, "checkpoint/load_optim_plane"):
            loaded = load_tree(optim_dir, {
                "master_params": tmpl_master,
                "opt_state": tmpl_opt,
                "scaler": state.scaler,
                "rng": state.rng,
                "data_rng": engine._data_rng,
            }, retry=retry)
        master, opt_state = engine._adopt_loaded(
            loaded["master_params"], loaded["opt_state"])
        scaler = loaded["scaler"]
        rng = loaded["rng"]
        engine._data_rng = loaded["data_rng"]
    else:
        # fp16-cast restore: module weights promoted to a fresh fp32 master
        from . import precision
        check_digest("model")
        module_tmpl = precision.cast_to_compute(
            tmpl_master, engine.compute_dtype)
        with _tel_span(engine, "checkpoint/load_model_plane"):
            loaded = load_tree(os.path.join(ckpt_dir, "model"),
                               {"module": module_tmpl}, retry=retry)

        def _promote(cur, new):
            sharding = getattr(cur, "sharding", None)  # numpy (offload): none
            from jax.sharding import NamedSharding
            if isinstance(sharding, NamedSharding):
                # on-device cast keeps this multi-host safe: `new` may be a
                # global array spanning non-addressable devices, which
                # device_get cannot fetch
                return jax.jit(lambda x: x.astype(cur.dtype),
                               out_shardings=sharding)(new)
            return np.asarray(jax.device_get(new)).astype(cur.dtype)

        master = jax.tree.map(_promote, tmpl_master, loaded["module"])
        if getattr(engine, "_offload", False):
            # offload tiers rebuild their own fresh moments (host tier in
            # _sync_offload_from_state, xla tier in _adopt_loaded);
            # materializing device fp32 moments here would transiently cost
            # 2× model size in HBM — the exact memory offload exists to avoid
            opt_state = None
        else:
            # engine-internal form (e.g. 1-bit Adam's stacked per-worker
            # error buffers at dp>1 — plain optimizer.init would build a
            # world=1 state the compiled shard_map step cannot consume)
            opt_state = engine._fresh_opt_state(master)
        master, opt_state = engine._adopt_loaded(master, opt_state)
        scaler = state.scaler

    # Scalars get the same explicit replicated placement as engine init
    # (cache-key stability; see DeepSpeedEngine._place_scalar).
    place_scalar = engine._place_scalar
    engine.state = TrainState(
        master_params=master,
        opt_state=opt_state,
        scaler=jax.tree.map(place_scalar, scaler),
        global_steps=place_scalar(
            jnp.asarray(meta["global_steps"], jnp.int32)),
        skipped_steps=place_scalar(
            jnp.asarray(meta["skipped_steps"], jnp.int32)),
        rng=place_scalar(rng),
    )
    engine.global_steps = meta["global_steps"]
    engine.micro_steps = meta["micro_steps"]
    engine.skipped_steps = meta["skipped_steps"]
    if getattr(engine, "_offload_xla", False):
        # continue the DPU rng stream past the restored run: global_steps
        # is the total dispatch count after a flush INCLUDING overflow-
        # skipped steps — seeding from opt_state.count (applied steps
        # only) would replay dropout seeds consumed before the save
        engine._xla_dpu_dispatch = int(meta["global_steps"])
    if getattr(engine, "_offload_host", False):
        # host tier: copy the loaded arrays back into the native host-Adam
        # buffers here (not in the engine wrapper) so calling this public
        # function directly leaves the engine consistent too
        engine._sync_offload_from_state()
    if iter_state is not None:
        apply_fn = getattr(engine, "load_data_iterator_state", None)
        if callable(apply_fn):
            apply_fn(iter_state)
    log_dist(
        f"loaded checkpoint {ckpt_dir} (saved at dp={meta['dp_world_size']} "
        f"zero={meta['zero_stage']}; now dp={engine.dp_world_size} "
        f"zero={engine.config.zero_optimization_stage})", ranks=[0])
    return ckpt_dir, meta.get("client_state", {})
