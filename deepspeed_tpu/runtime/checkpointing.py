"""Checkpoint save / load — two-plane scheme with reshard-on-load.

The reference writes a *model plane* (fp16 module weights + engine counters,
one file per MP rank: reference deepspeed/runtime/engine.py:1211-1236) and a
*ZeRO plane* (per-DP-rank partitioned fp32 master weights + optimizer state:
engine.py:1218-1229, zero/stage2.py:1675-1706), and supports loading ZeRO
checkpoints at a *different* DP world size by merging and re-partitioning
(stage2.py:1712-1778, stage1.py:836-941).

On TPU the partitioning is a sharding annotation, not a file layout, so the
natural design is: save the *logical* (unpartitioned) arrays once, and
re-apply the current engine's shardings at load time.  Resharding across any
mesh change (DP resize, ZeRO stage change, TP change) then falls out of
``jax.device_put`` — the elastic-restore feature costs nothing.

Layout of ``<save_dir>/<tag>/``:
  - ``meta.json``                       counters, world info, client_state
  - ``model/manifest.json  + *.npy``    module weights in compute dtype
  - ``optim/manifest.json  + *.npy``    fp32 master + optimizer state + scaler

``<save_dir>/latest`` holds the most recent tag (reference engine.py:1406).
Non-numpy-native dtypes (bfloat16) are stored as bit-pattern views with the
logical dtype recorded in the manifest.
"""
from __future__ import annotations

import contextlib
import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from ..utils.logging import log_dist

LATEST_FILE = "latest"


def _tel_span(engine, name: str, **args):
    """Per-plane telemetry span via the engine's hub (nullcontext when
    telemetry is off or the caller isn't a full engine — this module's
    public API also accepts engine-shaped ducks in tests)."""
    span = getattr(engine, "_tel_span", None)
    if span is None:
        return contextlib.nullcontext()
    return span(name, cat="checkpoint", **args)


# ---------------------------------------------------------------------------
# leaf codec
# ---------------------------------------------------------------------------
def _to_storage(arr: np.ndarray) -> Tuple[np.ndarray, str]:
    """Return (storable array, logical dtype name)."""
    logical = arr.dtype.name
    if arr.dtype.kind == "V" or logical in ("bfloat16", "float8_e4m3fn",
                                            "float8_e5m2"):
        itemsize = arr.dtype.itemsize
        view_dtype = {1: np.uint8, 2: np.uint16, 4: np.uint32}[itemsize]
        return arr.view(view_dtype), logical
    return arr, logical


def _from_storage(arr: np.ndarray, logical: str) -> np.ndarray:
    if arr.dtype.name != logical:
        import ml_dtypes
        return arr.view(np.dtype(getattr(ml_dtypes, logical)))
    return arr


def _split_merge_compatible(src: tuple, dst: tuple) -> bool:
    """True iff ``dst`` is reachable from ``src`` by only SPLITTING a dim
    into adjacent factors or MERGING adjacent dims — the reshapes that
    preserve the logical row-major layout (qkv [d, 3d] <-> [d, 3, d]).
    Greedy boundary alignment: walk both shapes accumulating products
    until they agree; within each aligned group at least one side must be
    a single dim (pure split or pure merge).  A permutation like
    (768, 2304) -> (2304, 768) forms one multi-dim x multi-dim group and
    is rejected even though the element counts match."""
    if int(np.prod(src, dtype=np.int64)) != int(np.prod(dst,
                                                        dtype=np.int64)):
        return False
    # Size-1 dims are layout-neutral in row-major order — drop them first
    # so e.g. (1, 4) -> (2, 2) aligns as the pure split it is instead of
    # the 1-dim getting absorbed into a multi x multi group.
    src = tuple(d for d in src if d != 1)
    dst = tuple(d for d in dst if d != 1)
    i = j = 0
    while i < len(src) and j < len(dst):
        a, b = int(src[i]), int(dst[j])
        ni, nj = 1, 1
        while a != b:
            if a < b:
                i += 1
                if i >= len(src):
                    return False
                a *= int(src[i])
                ni += 1
            else:
                j += 1
                if j >= len(dst):
                    return False
                b *= int(dst[j])
                nj += 1
        if ni > 1 and nj > 1:
            return False
        i += 1
        j += 1
    return all(d == 1 for d in src[i:]) and all(d == 1 for d in dst[j:])


def _keystr(path) -> str:
    return jax.tree_util.keystr(path)


# ---------------------------------------------------------------------------
# tree save / load
# ---------------------------------------------------------------------------
def _is_fully_addressable(leaf) -> bool:
    return bool(getattr(leaf, "is_fully_addressable", True))


def save_tree(dirpath: str, tree: Any) -> None:
    """Write every leaf of ``tree`` as .npy files plus a manifest mapping
    pytree key-paths to files.

    Multi-host: a leaf that is NOT fully addressable (its shards live on
    several processes) is written as per-process shard files — each
    process saves only the shards it owns (replica 0 of each), with the
    global index recorded per shard.  This is the analogue of the
    reference's per-DP-rank ``zero_pp_rank_D_...`` partitioned files
    (reference engine.py:1218-1229); load merges them
    (``stage2.py:1712-1778``'s merge without the repartition math, which
    reshard-on-load makes unnecessary).  Every process must call this
    function; process 0 writes the manifest.
    """
    os.makedirs(dirpath, exist_ok=True)
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    pid = jax.process_index()
    manifest: Dict[str, Dict[str, Any]] = {}
    for i, (path, leaf) in enumerate(flat):
        if _is_fully_addressable(leaf):
            if pid == 0:
                arr = np.asarray(jax.device_get(leaf))
                store, logical = _to_storage(arr)
                fname = f"leaf_{i:05d}.npy"
                np.save(os.path.join(dirpath, fname), store,
                        allow_pickle=False)
                manifest[_keystr(path)] = {
                    "file": fname,
                    "dtype": logical,
                    "shape": list(arr.shape),
                }
            continue
        # process-local shards (multi-host)
        indices = []
        logical = str(leaf.dtype)
        store_dtype = logical
        for k, shard in enumerate(leaf.addressable_shards):
            if shard.replica_id != 0:
                continue
            arr = np.asarray(shard.data)
            store, logical = _to_storage(arr)
            store_dtype = store.dtype.name
            fname = f"leaf_{i:05d}.proc{pid}_{k}.npy"
            np.save(os.path.join(dirpath, fname), store, allow_pickle=False)
            indices.append({
                "file": fname,
                "index": [[s.start, s.stop] for s in
                          _normalize_index(shard.index, leaf.shape)],
            })
        if pid == 0:
            manifest[_keystr(path)] = {
                "sharded": True,
                "leaf": i,
                "dtype": logical,
                "store_dtype": store_dtype,
                "shape": list(leaf.shape),
            }
        # every process records its own shard index file
        with open(os.path.join(
                dirpath, f"leaf_{i:05d}.proc{pid}.json"), "w") as f:
            json.dump(indices, f)
    if pid == 0:
        with open(os.path.join(dirpath, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1)


def _normalize_index(index, shape):
    """Shard index (tuple of slices) → concrete [start, stop] per dim."""
    out = []
    for dim, s in enumerate(index):
        start = 0 if s.start is None else int(s.start)
        stop = shape[dim] if s.stop is None else int(s.stop)
        out.append(slice(start, stop))
    return out


def _addressable_ranges(tleaf):
    """This process's addressable [start, stop] index boxes for a target
    leaf, or None when unknown (numpy template / no sharding) — used to
    skip reading other hosts' shard files on load."""
    from jax.sharding import NamedSharding
    sharding = getattr(tleaf, "sharding", None)
    shape = tuple(getattr(tleaf, "shape", ()))
    if not isinstance(sharding, NamedSharding) or jax.process_count() == 1:
        return None
    try:
        imap = sharding.devices_indices_map(shape)
    except Exception:
        return None
    boxes = []
    for dev, idx in imap.items():
        if dev.process_index != jax.process_index():
            continue
        boxes.append([[0 if s.start is None else int(s.start),
                       shape[d] if s.stop is None else int(s.stop)]
                      for d, s in enumerate(idx)])
    return boxes


def _ranges_intersect(shard_index, boxes) -> bool:
    for box in boxes:
        if all(a < bstop and b > bstart
               for (a, b), (bstart, bstop) in zip(shard_index, box)):
            return True
    return False


def load_tree(dirpath: str, target: Any, strict: bool = True) -> Any:
    """Load leaves by key-path into the structure of ``target``.  Each loaded
    array is placed with the corresponding target leaf's sharding — this is
    the reshard-on-load that makes DP-resize restore work (reference
    stage2.py:1712-1778 does this with explicit merge/repartition)."""
    with open(os.path.join(dirpath, "manifest.json")) as f:
        manifest = json.load(f)
    flat, treedef = jax.tree_util.tree_flatten_with_path(target)
    out = []
    for path, tleaf in flat:
        key = _keystr(path)
        entry = manifest.get(key)
        if entry is None:
            if strict:
                raise KeyError(
                    f"checkpoint at {dirpath} has no entry for {key!r}")
            log_dist(f"checkpoint {dirpath}: no entry for {key!r}; "
                     "keeping the engine's current value", ranks=[0])
            out.append(tleaf)
            continue
        if entry.get("sharded"):
            # merge-on-load of per-process shard files (reference
            # stage2.py:1712-1778 merges per-rank partitions the same way)
            import glob as _glob
            store_dtype = entry.get("store_dtype", entry["dtype"])
            # a manifest written by a process that owned no replica-0
            # shards records the LOGICAL dtype; map it to the storage view
            # the shard files actually contain.  Must branch on the NAME:
            # np.dtype('bfloat16') succeeds (ml_dtypes registers it), and
            # an arr of bfloat16 would VALUE-cast the uint16 bit patterns
            # instead of reinterpreting them.
            sd = {"bfloat16": np.dtype(np.uint16),
                  "float8_e4m3fn": np.dtype(np.uint8),
                  "float8_e5m2": np.dtype(np.uint8)}.get(
                store_dtype, None) or np.dtype(store_dtype)
            # np.zeros is calloc-backed: pages only materialize where
            # shards are written, so RAM cost ≈ the bytes actually needed
            arr = np.zeros(tuple(entry["shape"]), sd)
            idx_files = sorted(_glob.glob(os.path.join(
                dirpath, f"leaf_{entry['leaf']:05d}.proc*.json")))
            if not idx_files:
                raise FileNotFoundError(
                    f"sharded checkpoint leaf {key!r}: no shard index "
                    f"files in {dirpath} (were all processes' files "
                    "copied to a shared location?)")
            need = _addressable_ranges(tleaf)
            for jf in idx_files:
                with open(jf) as jfh:
                    for shard in json.load(jfh):
                        if need is not None and not _ranges_intersect(
                                shard["index"], need):
                            continue  # another host's slice — skip the I/O
                        data = np.load(os.path.join(
                            dirpath, shard["file"]), allow_pickle=False)
                        sl = tuple(slice(a, b) for a, b in shard["index"])
                        arr[sl] = data
            arr = _from_storage(arr, entry["dtype"])
        else:
            arr = np.load(os.path.join(dirpath, entry["file"]),
                          allow_pickle=False)
            arr = _from_storage(arr, entry["dtype"])
        tshape = tuple(getattr(tleaf, "shape", ()))
        if tuple(arr.shape) != tshape:
            # Pipeline-resize elastic restore: stage-local stacked leaves
            # are [num_stages, layers_per_stage, ...]; stage ranges are
            # contiguous, so flattening the two leading dims is a canonical
            # layer order and a checkpoint saved at pp=2 reshapes losslessly
            # onto a pp=4 engine (reference analogue: ZeRO checkpoint
            # merge/re-partition across DP sizes, stage2.py:1712-1778).
            if ("stack_" in key
                    and len(arr.shape) >= 2 and len(tshape) >= 2
                    and arr.shape[2:] == tshape[2:]
                    and arr.shape[0] * arr.shape[1]
                    == tshape[0] * tshape[1]):
                arr = arr.reshape(tshape)
                log_dist(
                    f"checkpoint leaf {key!r}: restacked "
                    f"{entry['shape']} -> {list(tshape)} (pipeline resize)",
                    ranks=[0])
            elif _split_merge_compatible(tuple(arr.shape), tshape):
                # Size-preserving layout evolution: dims purely split or
                # merged (e.g. the qkv [.., d, 3d] -> [.., d, 3, d]
                # re-layout — row-major order unchanged) reshape
                # losslessly.  Equal element count alone is NOT enough: a
                # permuted layout like [768, 2304] -> [2304, 768] would
                # reshape into numeric garbage, so those still raise.
                # Logged loudly so the restore log shows every re-layout.
                arr = arr.reshape(tshape)
                log_dist(
                    f"checkpoint leaf {key!r}: reshaped "
                    f"{entry['shape']} -> {list(tshape)} (size-preserving "
                    "layout change)", ranks=[0])
            else:
                raise ValueError(
                    f"checkpoint leaf {key!r} has shape {arr.shape}, engine "
                    f"expects {tshape} — model/optimizer config mismatch")
        sharding = getattr(tleaf, "sharding", None)
        tdtype = getattr(tleaf, "dtype", arr.dtype)
        arr = arr.astype(tdtype) if arr.dtype != tdtype else arr
        # Re-apply only mesh-aware placements; committing scalars to a single
        # device would pin them and conflict with the mesh under jit.  numpy
        # targets (offload host/flat staging templates) stay numpy — putting
        # a multi-GB offloaded master on device here would defeat offload.
        from jax.sharding import NamedSharding
        if isinstance(sharding, NamedSharding):
            if jax.process_count() > 1:
                # multi-controller: each process materializes only its own
                # addressable shards of the global array
                out.append(jax.make_array_from_callback(
                    tuple(arr.shape), sharding,
                    lambda idx, a=arr: a[idx]))
            else:
                out.append(jax.device_put(arr, sharding))
        elif isinstance(tleaf, np.ndarray):
            out.append(arr)
        else:
            out.append(jax.numpy.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# engine-level save / load
# ---------------------------------------------------------------------------
def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[dict] = None,
                    save_latest: bool = True) -> str:
    """Two-plane checkpoint write (reference engine.py:1211-1290).

    The write is atomic: everything lands in ``<tag>.tmp`` and is renamed
    into place only after ``meta.json`` (written last) is on disk, so a
    killed save can never leave a loadable-looking partial checkpoint.

    The model plane intentionally duplicates a down-cast of the fp32 master
    (~0.5× extra bytes): it keeps module-only loads (inference handoff, the
    reference's fp16-cast restore) independent of the optimizer plane, same
    as the reference's mp_rank/zero_pp_rank file split.

    Multi-host: EVERY process MUST call this (same contract as the
    reference, where every rank writes its ZeRO partition files,
    engine.py:1218-1229) — guarding with ``if process_index() == 0`` will
    DEADLOCK the job at the internal barrier.  Fully-addressable leaves
    are written by process 0, non-addressable leaves as per-process shard
    files (see save_tree), with a cross-process barrier before the atomic
    rename.  Assumes a shared checkpoint directory (the pod-filesystem /
    GCS-fuse case); per-host local dirs need the shard files merged before
    load, which load_tree reports explicitly if missing.  (reference
    engine.py:415-416 writes model files from DP rank 0 and ZeRO
    partitions from every rank, engine.py:1218-1229.)
    """
    from .engine import TrainState  # local import to avoid cycle

    state: TrainState = engine.state
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    ckpt_dir = os.path.join(save_dir, str(tag))
    multiproc = jax.process_count() > 1
    proc0 = jax.process_index() == 0
    tmp_dir = ckpt_dir + ".tmp"
    if proc0 and os.path.isdir(tmp_dir):
        import shutil
        shutil.rmtree(tmp_dir)
    if multiproc:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("ds_ckpt_clean")
    os.makedirs(tmp_dir, exist_ok=True)

    from . import precision
    # canonical (per-parameter tree) form: the XLA offload tier stores flat
    # host vectors internally, but the checkpoint keeps the logical tree so
    # offload <-> non-offload restores compose (reference merge/re-partition
    # analogue, stage2.py:1712-1778)
    master_tree, opt_tree = engine._canonical_state()
    module_params = precision.cast_to_compute(
        master_tree, engine.compute_dtype)
    with _tel_span(engine, "checkpoint/save_model_plane"):
        save_tree(os.path.join(tmp_dir, "model"),
                  {"module": module_params})
    with _tel_span(engine, "checkpoint/save_optim_plane"):
        save_tree(os.path.join(tmp_dir, "optim"), {
            "master_params": master_tree,
            "opt_state": opt_tree,
            "scaler": state.scaler,
            "rng": state.rng,
            "data_rng": engine._data_rng,
        })

    if multiproc:
        # every process's shard files must be on disk before the rename
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("ds_ckpt_written")
    if proc0:
        meta = {
            "tag": str(tag),
            "global_steps": int(engine.global_steps),
            "micro_steps": int(engine.micro_steps),
            "skipped_steps": int(state.skipped_steps),
            "dp_world_size": int(engine.dp_world_size),
            "zero_stage": int(engine.config.zero_optimization_stage),
            "client_state": client_state or {},
        }
        with open(os.path.join(tmp_dir, "meta.json"), "w") as f:
            json.dump(meta, f, indent=1)
        if os.path.isdir(ckpt_dir):
            import shutil
            shutil.rmtree(ckpt_dir)
        os.rename(tmp_dir, ckpt_dir)
        if save_latest:
            latest_tmp = os.path.join(save_dir, LATEST_FILE + ".tmp")
            with open(latest_tmp, "w") as f:
                f.write(str(tag))
            os.replace(latest_tmp, os.path.join(save_dir, LATEST_FILE))
    if multiproc:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("ds_ckpt_done")
    log_dist(f"saved checkpoint {ckpt_dir}", ranks=[0])
    return ckpt_dir


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True,
                    load_lr_scheduler_states: bool = True,
                    load_module_only: bool = False):
    """Restore engine state; returns ``(load_path, client_state)`` like the
    reference (engine.py:1292-1324).  Handles a different current DP size /
    ZeRO stage / mesh than the one that saved (elastic restore).

    ``load_lr_scheduler_states`` is accepted for API parity but has no
    distinct effect: all lr schedules here are pure functions of the
    restored step count, so there is no separate scheduler state to load.
    """
    from .engine import TrainState
    import jax.numpy as jnp

    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        if not os.path.isfile(latest):
            log_dist(f"no 'latest' file in {load_dir}; nothing to load",
                     ranks=[0])
            return None, None
        with open(latest) as f:
            tag = f.read().strip()
    ckpt_dir = os.path.join(load_dir, str(tag))
    # meta.json is written last inside the atomic rename; its absence means
    # the checkpoint doesn't exist (or is a corrupt partial) — report
    # missing rather than crash.
    if not os.path.isfile(os.path.join(ckpt_dir, "meta.json")):
        return None, None

    with open(os.path.join(ckpt_dir, "meta.json")) as f:
        meta = json.load(f)

    state: TrainState = engine.state
    optim_dir = os.path.join(ckpt_dir, "optim")
    use_optim = (load_optimizer_states and not load_module_only
                 and os.path.isdir(optim_dir))
    rng = state.rng
    tmpl_master, tmpl_opt = engine._canonical_templates()
    if use_optim:
        # fp32 master restore (reference 'load_from_fp32_weights',
        # stage2.py:1780-1835); rng restore keeps dropout masks identical
        # to an uninterrupted run.
        with _tel_span(engine, "checkpoint/load_optim_plane"):
            loaded = load_tree(optim_dir, {
                "master_params": tmpl_master,
                "opt_state": tmpl_opt,
                "scaler": state.scaler,
                "rng": state.rng,
                "data_rng": engine._data_rng,
            })
        master, opt_state = engine._adopt_loaded(
            loaded["master_params"], loaded["opt_state"])
        scaler = loaded["scaler"]
        rng = loaded["rng"]
        engine._data_rng = loaded["data_rng"]
    else:
        # fp16-cast restore: module weights promoted to a fresh fp32 master
        from . import precision
        module_tmpl = precision.cast_to_compute(
            tmpl_master, engine.compute_dtype)
        with _tel_span(engine, "checkpoint/load_model_plane"):
            loaded = load_tree(os.path.join(ckpt_dir, "model"),
                               {"module": module_tmpl})
        def _promote(cur, new):
            sharding = getattr(cur, "sharding", None)  # numpy (offload): none
            from jax.sharding import NamedSharding
            if isinstance(sharding, NamedSharding):
                # on-device cast keeps this multi-host safe: `new` may be a
                # global array spanning non-addressable devices, which
                # device_get cannot fetch
                return jax.jit(lambda x: x.astype(cur.dtype),
                               out_shardings=sharding)(new)
            return np.asarray(jax.device_get(new)).astype(cur.dtype)

        master = jax.tree.map(_promote, tmpl_master, loaded["module"])
        if getattr(engine, "_offload", False):
            # offload tiers rebuild their own fresh moments (host tier in
            # _sync_offload_from_state, xla tier in _adopt_loaded);
            # materializing device fp32 moments here would transiently cost
            # 2× model size in HBM — the exact memory offload exists to avoid
            opt_state = None
        else:
            # engine-internal form (e.g. 1-bit Adam's stacked per-worker
            # error buffers at dp>1 — plain optimizer.init would build a
            # world=1 state the compiled shard_map step cannot consume)
            opt_state = engine._fresh_opt_state(master)
        master, opt_state = engine._adopt_loaded(master, opt_state)
        scaler = state.scaler

    # Scalars get the same explicit replicated placement as engine init
    # (cache-key stability; see DeepSpeedEngine._place_scalar).
    place_scalar = engine._place_scalar
    engine.state = TrainState(
        master_params=master,
        opt_state=opt_state,
        scaler=jax.tree.map(place_scalar, scaler),
        global_steps=place_scalar(
            jnp.asarray(meta["global_steps"], jnp.int32)),
        skipped_steps=place_scalar(
            jnp.asarray(meta["skipped_steps"], jnp.int32)),
        rng=place_scalar(rng),
    )
    engine.global_steps = meta["global_steps"]
    engine.micro_steps = meta["micro_steps"]
    engine.skipped_steps = meta["skipped_steps"]
    if getattr(engine, "_offload_xla", False):
        # continue the DPU rng stream past the restored run: global_steps
        # is the total dispatch count after a flush INCLUDING overflow-
        # skipped steps — seeding from opt_state.count (applied steps
        # only) would replay dropout seeds consumed before the save
        engine._xla_dpu_dispatch = int(meta["global_steps"])
    if getattr(engine, "_offload_host", False):
        # host tier: copy the loaded arrays back into the native host-Adam
        # buffers here (not in the engine wrapper) so calling this public
        # function directly leaves the engine consistent too
        engine._sync_offload_from_state()
    log_dist(
        f"loaded checkpoint {ckpt_dir} (saved at dp={meta['dp_world_size']} "
        f"zero={meta['zero_stage']}; now dp={engine.dp_world_size} "
        f"zero={engine.config.zero_optimization_stage})", ranks=[0])
    return ckpt_dir, meta.get("client_state", {})
