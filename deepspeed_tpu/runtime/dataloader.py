"""Data loading for SPMD training.

The reference auto-wraps datasets in a DistributedSampler keyed on DP rank
(reference: deepspeed/runtime/dataloader.py:48-58).  Under single-controller
SPMD there are no per-rank samplers: the loader yields *global* batches and
the engine shards them over the ``data`` mesh axis with one device_put.
``RepeatingLoader`` (reference: dataloader.py:10-30) ports unchanged.
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

import numpy as np

from ..utils.logging import logger


class RepeatingLoader:
    """Wrap an iterable so it restarts instead of raising StopIteration."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedDataLoader:
    """Batch iterator over an indexable dataset of pytrees (dicts/tuples of
    arrays, or (x, y) pairs), yielding stacked global batches."""

    def __init__(self, dataset, batch_size: int,
                 collate_fn: Optional[Callable] = None,
                 mesh=None, shuffle: bool = False, seed: int = 0,
                 drop_last: bool = True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _default_collate
        self.mesh = mesh
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = np.random.default_rng(seed)
        self.len = len(dataset) // batch_size
        if not self.drop_last and len(dataset) % batch_size:
            self.len += 1
            # every distinct leading shape compiles a SEPARATE XLA
            # program (the jaxlint JL005 hazard class): the short tail
            # batch silently retraces eval/model steps once per shape —
            # visible as a recompiles_total{program=...} bump — and the
            # engine's train_batch rejects it outright (batch-dim
            # validation).  Loud at construction, once, because the
            # per-epoch recompile itself is silent.
            logger.warning(
                "DeepSpeedDataLoader: drop_last=False with len(dataset)="
                "%d %% batch_size=%d != 0 — the final batch of each "
                "epoch has %d rows instead of %d. A different leading "
                "shape recompiles the step it feeds every epoch (jaxlint "
                "JL005; watch recompiles_total). Pad the tail to a full "
                "batch or drop it (drop_last=True).",
                len(dataset), batch_size, len(dataset) % batch_size,
                batch_size)

    def __len__(self):
        return self.len

    def __iter__(self):
        order = np.arange(len(self.dataset))
        if self.shuffle:
            self._rng.shuffle(order)
        for i in range(self.len):
            idx = order[i * self.batch_size:(i + 1) * self.batch_size]
            yield self.collate_fn([self.dataset[int(j)] for j in idx])


def _default_collate(samples):
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples])
                for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(
            np.stack([np.asarray(s[i]) for s in samples])
            for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])
