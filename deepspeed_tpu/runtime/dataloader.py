"""Data loading for SPMD training.

The reference auto-wraps datasets in a DistributedSampler keyed on DP rank
(reference: deepspeed/runtime/dataloader.py:48-58).  Under single-controller
SPMD there are no per-rank samplers: the loader yields *global* batches and
the engine shards them over the ``data`` mesh axis with one device_put.
``RepeatingLoader`` (reference: dataloader.py:10-30) ports unchanged.

Sample-exact resume (docs/elastic.md): both loaders are CHECKPOINTABLE —
``state_dict()`` captures (epoch, step-in-epoch, the RNG state at epoch
start) and ``load_state_dict()`` restores it so the next batch drawn is
exactly the one an uninterrupted run would have drawn: the epoch-start
RNG state re-derives the SAME shuffle permutation, and the batch index
skips what was already consumed.  The engine persists this as the
checkpoint's data-iterator plane; a resumed run neither replays nor
skips data.  (The reference has no analogue — its resumed runs re-seed
the sampler and replay the epoch.)
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Iterable, Optional

import numpy as np

from ..utils.logging import logger


def supports_iter_state(obj) -> bool:
    """True when ``obj`` carries the checkpointable-iterator protocol
    (``state_dict``/``load_state_dict``) — what the engine probes before
    writing the data-iterator checkpoint plane."""
    return (callable(getattr(obj, "state_dict", None))
            and callable(getattr(obj, "load_state_dict", None)))


class RepeatingLoader:
    """Wrap an iterable so it restarts instead of raising StopIteration."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)

    # -- sample-exact resume -------------------------------------------
    # The repeater holds no position of its own: epoch wrap is derivable
    # from the inner loader's (epoch, batch_idx), so its state IS the
    # inner loader's state.
    def state_dict(self) -> dict:
        if not supports_iter_state(self.loader):
            raise TypeError(
                "RepeatingLoader.state_dict: the wrapped loader "
                f"({type(self.loader).__name__}) has no state_dict/"
                "load_state_dict — sample-exact resume needs a "
                "checkpointable loader (e.g. DeepSpeedDataLoader)")
        return {"loader": self.loader.state_dict()}

    def load_state_dict(self, state: dict) -> None:
        if not supports_iter_state(self.loader):
            raise TypeError(
                "RepeatingLoader.load_state_dict: the wrapped loader "
                f"({type(self.loader).__name__}) is not checkpointable")
        self.loader.load_state_dict(state["loader"])
        # fresh iterator over the RESTORED position (the old one, if any,
        # still points at the pre-restore epoch)
        self.data_iter = iter(self.loader)


class DeepSpeedDataLoader:
    """Batch iterator over an indexable dataset of pytrees (dicts/tuples of
    arrays, or (x, y) pairs), yielding stacked global batches."""

    def __init__(self, dataset, batch_size: int,
                 collate_fn: Optional[Callable] = None,
                 mesh=None, shuffle: bool = False, seed: int = 0,
                 drop_last: bool = True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _default_collate
        self.mesh = mesh
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = np.random.default_rng(seed)
        self.len = len(dataset) // batch_size
        if not self.drop_last and len(dataset) % batch_size:
            self.len += 1
            # every distinct leading shape compiles a SEPARATE XLA
            # program (the jaxlint JL005 hazard class): the short tail
            # batch silently retraces eval/model steps once per shape —
            # visible as a recompiles_total{program=...} bump — and the
            # engine's train_batch rejects it outright (batch-dim
            # validation).  Loud at construction, once, because the
            # per-epoch recompile itself is silent.
            logger.warning(
                "DeepSpeedDataLoader: drop_last=False with len(dataset)="
                "%d %% batch_size=%d != 0 — the final batch of each "
                "epoch has %d rows instead of %d. A different leading "
                "shape recompiles the step it feeds every epoch (jaxlint "
                "JL005; watch recompiles_total). Pad the tail to a full "
                "batch or drop it (drop_last=True).",
                len(dataset), batch_size, len(dataset) % batch_size,
                batch_size)
        # -- iteration-position tracking (sample-exact resume) ----------
        # epoch = index of the epoch currently being iterated (-1 before
        # the first __iter__); batch_idx = batches PRODUCED so far in it
        # (advanced BEFORE each yield, so a state captured between
        # next() calls names the next batch to draw, not the last drawn);
        # _epoch_rng_state = the RNG state at the current epoch's start,
        # from which its shuffle permutation re-derives on resume.
        self._epoch = -1
        self._batch_idx = 0
        self._epoch_rng_state = copy.deepcopy(self._rng.bit_generator.state)
        self._resume_idx: Optional[int] = None

    def __len__(self):
        return self.len

    def __iter__(self):
        if self._resume_idx is not None:
            # resuming the epoch captured by load_state_dict: replay the
            # epoch-start RNG state so the SAME permutation re-derives,
            # then skip the batches the saved run already consumed
            start = self._resume_idx
            self._resume_idx = None
            self._rng.bit_generator.state = copy.deepcopy(
                self._epoch_rng_state)
        else:
            start = 0
            self._epoch += 1
            self._epoch_rng_state = copy.deepcopy(
                self._rng.bit_generator.state)
        order = np.arange(len(self.dataset))
        if self.shuffle:
            self._rng.shuffle(order)
        self._batch_idx = start
        for i in range(start, self.len):
            idx = order[i * self.batch_size:(i + 1) * self.batch_size]
            # position advances BEFORE the yield: a state_dict taken
            # after this batch is consumed must not re-draw it
            self._batch_idx = i + 1
            yield self.collate_fn([self.dataset[int(j)] for j in idx])

    # -- sample-exact resume -------------------------------------------
    def state_dict(self) -> dict:
        """JSON-able iteration position: restoring it into a freshly
        built loader (same dataset/batch_size/seed/shuffle) makes the
        next batch drawn exactly the one this loader would draw next."""
        return {
            "version": 1,
            "epoch": int(self._epoch),
            "batch_idx": int(self._batch_idx),
            # numpy Generator state is a plain dict of ints/strings —
            # JSON-serializable as-is (PCG64 ints exceed 64 bits; JSON
            # integers are arbitrary precision)
            "rng_state": copy.deepcopy(self._epoch_rng_state),
            "len": int(self.len),
            "shuffle": bool(self.shuffle),
        }

    def load_state_dict(self, state: dict) -> None:
        if int(state.get("len", self.len)) != self.len:
            logger.warning(
                "DeepSpeedDataLoader.load_state_dict: checkpointed "
                "batches/epoch %s != this loader's %s (dataset or batch "
                "size changed) — resuming at the saved batch index "
                "modulo the new epoch length",
                state.get("len"), self.len)
        if bool(state.get("shuffle", self.shuffle)) != self.shuffle:
            logger.warning(
                "DeepSpeedDataLoader.load_state_dict: checkpoint was "
                "taken with shuffle=%s but this loader has shuffle=%s — "
                "the resumed sample order will not match the saved run",
                state.get("shuffle"), self.shuffle)
        self._epoch = int(state["epoch"])
        bi = int(state["batch_idx"])
        if bi > self.len:
            # epoch length changed under the checkpoint (warned above):
            # clamp into this loader's epoch instead of yielding nothing
            bi = bi % max(self.len, 1)
        self._batch_idx = bi
        self._epoch_rng_state = copy.deepcopy(state["rng_state"])
        self._rng.bit_generator.state = copy.deepcopy(state["rng_state"])
        # epoch -1 = the saved loader was never iterated: the next
        # __iter__ must start epoch 0 fresh, not "resume" a non-epoch
        self._resume_idx = (None if self._epoch < 0
                            else int(self._batch_idx))


def _default_collate(samples):
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples])
                for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(
            np.stack([np.asarray(s[i]) for s in samples])
            for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])
