"""ZeRO-Offload — host-resident optimizer tier.

The reference's offload path (reference: deepspeed/runtime/zero/
stage2.py:743-900 + csrc/adam/cpu_adam.cpp) stages gradients into pinned
host buffers during backward, runs the AVX CPU Adam over host fp32
partitions, and copies fp16 params back to the GPU with a fused kernel.

The TPU shape of the same idea, given XLA's execution model:

  device (one jitted function): forward + backward + grad unscale/clip +
      overflow check — everything that wants the MXU.
  host: fp32 master + both moments live in numpy (host RAM — the HBM
      those buffers would occupy is what ZeRO-Offload frees); the native
      CPU Adam (ops/cpu_adam.py) updates them and emits bf16 upload copies
      in the same pass, which are device_put back as the next step's
      compute params.

Scope note: this is the single-controller tier — the host stages the FULL
gradient and owns the full master.  Multi-host offload (each process
pulling only its reduce-scattered shard, the reference's per-DP-rank
partitions) is future work and is called out where it matters.

Loss-scale skip/update bookkeeping runs on host (it is per-step control
flow, exactly what the reference does in Python, stage2.py:1341-1362).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.cpu_adam import DeepSpeedCPUAdam


class HostOffloadOptimizer:
    """Owns the host-side master params + moments and the upload cast."""

    def __init__(self, master_params, lr, betas, eps, weight_decay,
                 adamw_mode: bool = True, bias_correction: bool = True,
                 compute_dtype=jnp.bfloat16,
                 use_native: Optional[bool] = None):
        # pull master to host numpy once; it never goes back whole.
        # fp32-promote only floating leaves — integer/bool buffers keep
        # their dtype and are never touched by Adam (same rule the engine
        # applies building the master, engine.py master cast).
        def to_host(x):
            arr = np.asarray(jax.device_get(x))
            if np.issubdtype(arr.dtype, np.floating) or \
                    arr.dtype.name == "bfloat16":
                return np.array(arr, dtype=np.float32)
            return np.array(arr)

        self._probe_transfer_path(master_params)
        self.master = jax.tree.map(to_host, master_params)
        self.opt = DeepSpeedCPUAdam(
            lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
            adamw_mode=adamw_mode, bias_correction=bias_correction,
            use_native=use_native)
        self.compute_dtype = compute_dtype
        self._out_dtype = ("bfloat16" if compute_dtype == jnp.bfloat16
                           else "float16" if compute_dtype == jnp.float16
                           else None)

    @staticmethod
    def _probe_transfer_path(master_params, min_mbps: float = None,
                             probe_timeout: float = None):
        """Fail FAST if bulk device->host transfers are broken.

        The host tier is single-controller: it pulls the full fp32 master
        to this process and re-uploads compute params every step.  On a
        tunneled dev platform (axon websocket relay) bulk transfers were
        observed to stall *indefinitely* — un-interruptible by SIGALRM
        because the wait is inside one native call (round-3 root cause,
        BENCH_NOTES.md).  Probing a single ~4 MB pull in a worker thread
        converts that forever-stall into a clean RuntimeError, letting
        callers fall back (engine attempt chains, bench.py).  On a real
        TPU VM the probe costs one microseconds-scale PCIe copy.

        Knobs: DS_OFFLOAD_MIN_MBPS (default 8; 0 disables),
        DS_OFFLOAD_PROBE_TIMEOUT seconds (default 60).
        """
        import os
        import threading
        import time

        if min_mbps is None:
            min_mbps = float(os.environ.get("DS_OFFLOAD_MIN_MBPS", "8"))
        if probe_timeout is None:
            probe_timeout = float(
                os.environ.get("DS_OFFLOAD_PROBE_TIMEOUT", "60"))
        if min_mbps <= 0:
            return
        leaves = [x for x in jax.tree.leaves(master_params)
                  if hasattr(x, "nbytes")]
        if not leaves:
            return
        # largest leaf capped to ~4 MB worth of leading rows
        leaf = max(leaves, key=lambda x: x.nbytes)
        if leaf.nbytes > 4 << 20 and leaf.ndim >= 1 and leaf.shape[0] > 1:
            rows = max(1, int(leaf.shape[0] * (4 << 20) / leaf.nbytes))
            leaf = leaf[:rows]
        nbytes = leaf.nbytes
        if nbytes < 1 << 20:  # tiny models: nothing worth probing
            return
        # Daemon thread, NOT ThreadPoolExecutor: the executor's interpreter
        # exit hook join()s its (non-daemon) worker, so a probe thread
        # wedged forever inside the native device_get would turn the
        # intended fast-fail into a hang at process exit.  A daemon thread
        # is simply abandoned.
        done = threading.Event()

        def pull():
            try:
                np.asarray(jax.device_get(leaf))
            finally:
                done.set()

        t0 = time.perf_counter()
        threading.Thread(target=pull, daemon=True).start()
        if not done.wait(timeout=probe_timeout):
            raise RuntimeError(
                f"device->host transfer probe ({nbytes >> 20} MB) did not "
                f"complete within {probe_timeout:.0f}s: bulk D2H appears "
                "stalled on this platform (tunneled dev harness?). The "
                "'host' offload tier needs working bulk transfers — use "
                "offload_impl='xla' (remote-host pinned staging) here. "
                "Override: DS_OFFLOAD_MIN_MBPS=0 disables this probe.")
        dt = time.perf_counter() - t0
        mbps = (nbytes / (1 << 20)) / max(dt, 1e-9)
        if mbps < min_mbps:
            raise RuntimeError(
                f"device->host transfer probe measured {mbps:.1f} MB/s "
                f"(< {min_mbps} MB/s): the host offload tier would take "
                "minutes per step at this bandwidth. Use "
                "offload_impl='xla', or set DS_OFFLOAD_MIN_MBPS=0 to "
                "proceed anyway.")

    @property
    def is_native(self) -> bool:
        return self.opt.is_native

    def compute_params(self):
        """Initial low-precision copies for the device (non-floating
        leaves pass through unchanged)."""
        from ..ops.cpu_adam import lowp_np_dtype
        dt = lowp_np_dtype(self._out_dtype)

        def cast(x):
            if dt is None or x.dtype != np.float32:
                return x.copy()
            return x.astype(dt)

        return jax.tree.map(cast, self.master)

    def step(self, host_grads):
        """Update master/moments in place; return upload copies in the
        configured compute dtype (fp32 configs get fp32 copies — no silent
        bf16 downgrade).  Grad leaves may be numpy OR jax Arrays — the
        inner optimizer converts per leaf via np.asarray, which lets the
        engine overlap D2H transfers with the C++ Adam compute."""
        out = self.opt.step(self.master, host_grads,
                            out_dtype=self._out_dtype)
        if self._out_dtype is None:
            return jax.tree.map(lambda x: x.copy(), self.master)
        return out

    # -- checkpoint plumbing -------------------------------------------
    def state_tree(self):
        """Optimizer state as a pytree aligned with the master params
        (what the engine stores in TrainState.opt_state and the
        checkpointer serializes)."""
        leaves, treedef = jax.tree.flatten(self.master)
        mu, nu = [], []
        for i, leaf in enumerate(leaves):
            m, v = self.opt._moments(i, leaf)
            mu.append(m)
            nu.append(v)
        return {"step": np.asarray(self.opt.step_count, np.int64),
                "mu": jax.tree.unflatten(treedef, mu),
                "nu": jax.tree.unflatten(treedef, nu)}

    def load_state_tree(self, master_tree, opt_tree):
        """In-place restore (buffer identity preserved so the numpy views
        the native kernel updates stay the engine's state)."""
        def copy_into(dst, src):
            dst[...] = np.asarray(jax.device_get(src), dtype=np.float32)
        jax.tree.map(copy_into, self.master, master_tree)
        self.opt.step_count = int(np.asarray(
            jax.device_get(opt_tree["step"])))
        leaves = jax.tree.leaves(self.master)
        mu = jax.tree.leaves(opt_tree["mu"])
        nu = jax.tree.leaves(opt_tree["nu"])
        for i, leaf in enumerate(leaves):
            m, v = self.opt._moments(i, leaf)
            m[...] = np.asarray(jax.device_get(mu[i]), np.float32)
            v[...] = np.asarray(jax.device_get(nu[i]), np.float32)
