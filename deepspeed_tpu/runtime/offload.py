"""ZeRO-Offload — host-resident optimizer tier.

The reference's offload path (reference: deepspeed/runtime/zero/
stage2.py:743-900 + csrc/adam/cpu_adam.cpp) stages gradients into pinned
host buffers during backward, runs the AVX CPU Adam over host fp32
partitions, and copies fp16 params back to the GPU with a fused kernel.

The TPU shape of the same idea, given XLA's execution model:

  device (one jitted function): forward + backward + grad unscale/clip +
      overflow check — everything that wants the MXU.
  host: fp32 master + both moments live in numpy (host RAM — the HBM
      those buffers would occupy is what ZeRO-Offload frees); the native
      CPU Adam (ops/cpu_adam.py) updates them and emits bf16 upload copies
      in the same pass, which are device_put back as the next step's
      compute params.

Two controllers' worth of scope:

  HostOffloadOptimizer — single-controller: the one host stages the FULL
      gradient and owns the full master (the dp=1 / single-process case).
  ShardedHostOffloadOptimizer — multi-host: each process stages ONLY its
      addressable shards of the dp-sharded master/gradients (the
      reference's per-DP-rank fp32 partitions, stage2.py:743-900) and
      C++-Adams them; compute params are reassembled ON DEVICE by one
      jitted all-gather, so no host ever touches another rank's bytes.

Loss-scale skip/update bookkeeping runs on host (it is per-step control
flow, exactly what the reference does in Python, stage2.py:1341-1362).
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.cpu_adam import DeepSpeedCPUAdam, is_adam_float, lowp_np_dtype
from ..utils.logging import logger
from .stages import (Stage, WatchdogPool, fault_point, injected_delay,
                     spawn)

# ---------------------------------------------------------------------------
# telemetry hook: per-pull transfer spans.  Module-level because the pull
# helpers below are free functions shared by both offload tiers; the
# engine installs its hub's tracer at construction (last telemetry-
# enabled engine wins — acceptable for a process-wide transfer log).
# Spans stamp host wall-clock around calls that ALREADY block on the
# transfer, so no sync is added anywhere.
# ---------------------------------------------------------------------------
_TRANSFER_TRACER = None


def set_transfer_tracer(tracer):
    """Install (or clear, with None) the tracer that receives
    ``offload/d2h`` spans from the guarded pull helpers."""
    global _TRANSFER_TRACER
    _TRANSFER_TRACER = tracer


def _transfer_span(name: str, cat: str = "transfer", **args):
    tracer = _TRANSFER_TRACER
    if tracer is None:
        return contextlib.nullcontext()
    return tracer.span(name, cat=cat, **args)


class UploadAborted(RuntimeError):
    """``StreamingUploader.finish()`` raced a concurrent ``abort()``:
    the upload set is incomplete by design — the caller's poison path
    (not a partial publish) is the only valid continuation."""


#: the shared watchdog plane for every guarded D2H pull in this process
#: (the PR 3 ``_PullWorker`` idiom, now the stage runtime's
#: ``WatchdogPool`` — see runtime/stages.py / docs/stages.md).
_PULL_POOL = WatchdogPool("ds-offload-pull")


def _watchdog_get(x, timeout_s: float, what: str = "D2H transfer"):
    """jax.device_get guarded by the shared watchdog pool.

    Bulk transfers on a tunneled dev platform can stall *inside one
    native call* — un-interruptible by signals (round-3 root cause,
    BENCH_NOTES.md).  Running the pull on the pool's persistent worker
    converts the forever-stall into a RuntimeError after ``timeout_s``;
    the wedged worker is abandoned (replaced lazily on the next pull),
    which costs this process its device handle but keeps the failure
    clean and lets the caller fall back to another tier instead of
    hanging the session.
    """
    nbytes = getattr(x, "nbytes", 0)

    def _pull():
        # the ``offload_pull:pull`` chaos boundary (docs/stages.md) runs
        # ON the pool's worker, so an injected delay exercises the real
        # watchdog timeout/abandon path, not just the caller's wait
        delay = injected_delay("offload_pull")
        if delay > 0:
            time.sleep(delay)
        fault_point("offload_pull", "pull")
        return np.asarray(jax.device_get(x))

    return _PULL_POOL.call(
        _pull, timeout_s, what,
        timeout_msg=(
            f"{what} ({nbytes >> 20} MB) did not complete within "
            f"{timeout_s:.0f}s: bulk D2H appears stalled on this "
            "platform (tunneled dev harness?). Aborting the pull "
            "piece-wise instead of wedging the session; use "
            "offload_impl='xla' here."))


def pull_chunk_bytes() -> int:
    """Piece size for guarded device->host pulls (DS_OFFLOAD_PULL_CHUNK_MB,
    default 64 MB; <=0 disables chunking).  Exposed so the engine can
    skip ``copy_to_host_async`` for leaves that will be pulled piece-wise
    anyway — a full-leaf async copy alongside the slice pulls would move
    every large leaf over the wire twice."""
    return int(float(os.environ.get("DS_OFFLOAD_PULL_CHUNK_MB", "64"))
               * (1 << 20))


def chunked_device_get(x, chunk_mb: Optional[float] = None,
                       piece_timeout: Optional[float] = None,
                       what: str = "master pull", out=None):
    """Piece-wise device->host pull with a per-piece watchdog.

    The reference's offload path never moves its state in one shot — it
    staggers pinned-buffer copies tile by tile (reference:
    csrc/adam/cpu_adam.cpp:64-113).  Here the motivation is robustness as
    much as overlap: one monolithic ``device_get`` of a multi-GB stacked
    scan leaf is a single native call that can stall forever on a sick
    link, and nothing can interrupt it.  Pulling ~chunk_mb slices along
    the leading axis bounds each native call, so a sick tunnel costs a
    clean per-tier RuntimeError within one piece-timeout instead of a
    wedged device.

    Pieces are FLAT element ranges (the leaf is viewed 1-D on device, a
    free row-major rebitcast), so every piece is <= chunk_mb regardless
    of the leaf's shape — a (2, huge) or (1, huge) leaf must not sneak a
    multi-GB native call under the per-piece timeout, or slow links
    misclassify as stalled on exactly the leaves that matter.

    ``out``: optional preallocated destination (any assignment-compatible
    dtype) — pieces are written straight into its slices, keeping peak
    host memory at 1x the leaf (the offload host is RAM-pressured by
    design; a transient second copy is exactly what it cannot afford).

    Knobs: DS_OFFLOAD_PULL_CHUNK_MB (default 64, 0 disables chunking),
    DS_OFFLOAD_PULL_TIMEOUT seconds per piece (default 120, 0 disables
    the watchdog too).
    """
    if chunk_mb is not None:
        chunk_bytes = int(chunk_mb * (1 << 20))
    else:
        chunk_bytes = pull_chunk_bytes()
    if piece_timeout is None:
        piece_timeout = float(
            os.environ.get("DS_OFFLOAD_PULL_TIMEOUT", "120"))

    def _deliver(arr):
        if out is None:
            return arr
        out[...] = arr
        return out

    if not isinstance(x, jax.Array):
        return _deliver(np.asarray(x))
    with _transfer_span("offload/d2h", what=what,
                        bytes=int(getattr(x, "nbytes", 0))):
        if piece_timeout <= 0:
            return _deliver(np.asarray(jax.device_get(x)))
        if chunk_bytes <= 0 or x.nbytes <= chunk_bytes or x.ndim == 0:
            return _deliver(_watchdog_get(x, piece_timeout, what))
        dt = np.dtype(x.dtype)
        elems_per = max(1, chunk_bytes // dt.itemsize)
        flat = x.reshape(-1)
        n = flat.shape[0]
        if out is None:
            out = np.empty(x.shape, dt)
        if out.flags.c_contiguous and out.size == n:
            out_flat = out.reshape(-1)
        else:  # exotic destination: pull to a temp flat, assign once
            out_flat = np.empty(n, out.dtype)
        for start in range(0, n, elems_per):
            out_flat[start:start + elems_per] = _watchdog_get(
                flat[start:start + elems_per], piece_timeout,
                f"{what} piece [{start}:{start + elems_per}]")
        if out_flat.base is not out and out_flat is not out:
            out[...] = out_flat.reshape(out.shape)
        return out


class _PrefetchPuller:
    """Chunked, watchdogged, bounded-lookahead grad pull — ONE worker
    thread per step.

    The construction-time probe certifies the link ONCE; this guard holds
    for every step after.  Each leaf goes through ``chunked_device_get``,
    so stall detection is PROGRESS-based (per ~64 MB piece): a slow but
    working link keeps completing pieces and never misfires the watchdog,
    while a genuine stall raises within one piece-timeout — the
    distinction a whole-leaf deadline cannot make on multi-GB stacked
    scan leaves.

    The single daemon worker pulls leaves in flatten order AHEAD of the
    consumer (the C++ Adam loop), so later transfers overlap earlier
    leaves' compute without a thread spawn per leaf.  Lookahead is
    bounded: the worker stays at most LOOKAHEAD leaves past the highest
    index the consumer has asked for, keeping the prefetch buffer at a
    few leaves — not a full extra gradient tree on the RAM-pressured
    offload host.  Dtypes are preserved (casting is the consumer's
    business).  A pull failure poisons all remaining slots with the same
    error and surfaces to the engine's attempt chain.
    """

    LOOKAHEAD = 2

    def __init__(self, tree, what: str = "grad pull"):
        self._cond = threading.Condition()
        self._want = -1
        self._closed = False
        # best-effort transfer accounting (written by the worker, read by
        # the owner after consumption finishes) — feeds the pipeline's
        # d2h row in the engine's per-step breakdown
        self.seconds = 0.0
        self.bytes = 0
        order = []
        self._slots: dict = {}
        for idx, g in enumerate(jax.tree.leaves(tree)):
            ev, box = threading.Event(), {}
            self._slots.setdefault(id(g), []).append((idx, ev, box))
            order.append((idx, g, ev, box))

        def work():
            for pos, (idx, g, ev, box) in enumerate(order):
                with self._cond:
                    self._cond.wait_for(
                        lambda: self._closed
                        or self._want + self.LOOKAHEAD >= idx)
                    if self._closed:
                        return  # consumer is done; drop the tree refs
                try:
                    t0 = time.perf_counter()
                    box["v"] = chunked_device_get(g, what=what)
                    self.seconds += time.perf_counter() - t0
                    self.bytes += int(getattr(g, "nbytes", 0))
                except BaseException as e:
                    box["e"] = e
                    ev.set()
                    # the link is sick: fail every later slot immediately
                    # rather than burning one piece-timeout per leaf
                    for _, _, ev2, box2 in order[pos + 1:]:
                        box2["e"] = e
                        ev2.set()
                    return
                ev.set()

        spawn(work, name="ds-offload-grad-prefetch", restarts=0)

    def __call__(self, g):
        idx, ev, box = self._slots[id(g)].pop(0)
        with self._cond:
            if idx > self._want:
                self._want = idx
                self._cond.notify_all()
        # no outer deadline needed: the worker cannot wedge (every native
        # pull inside it is piece-watchdogged) — it always sets the event
        ev.wait()
        if "e" in box:
            raise box["e"]
        return box["v"]

    def close(self):
        """Release the worker.  The consumer may legitimately skip
        trailing leaves (the Adam loop never requests non-fp32 ones), and
        a parked worker would otherwise wait forever holding a reference
        to every grad Array — one leaked thread plus one pinned gradient
        tree PER STEP.  Call from a finally block once consumption is
        done; un-pulled slots are failed so a late (buggy) request raises
        instead of hanging."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        for lst in self._slots.values():
            for _idx, ev, box in lst:
                if not ev.is_set():
                    box.setdefault("e", RuntimeError(
                        "_PrefetchPuller closed before this leaf was "
                        "requested"))
                    ev.set()


def guarded_tree_pull(tree):
    """Dtype-preserving watchdogged pull of every leaf in ``tree``.
    Used for the DPU pending-grad stash (engine keeps HOST copies so the
    device grad tree can be freed) — preserving dtype keeps the stash at
    1x the grads' bytes."""
    puller = _PrefetchPuller(tree)
    try:
        return jax.tree.map(puller, tree)
    finally:
        puller.close()


def device_put_leaf(arr, sharding):
    """H2D for ONE updated leaf (single-controller streaming pipeline).
    A module hook rather than an inline ``jax.device_put`` so tests can
    inject transfer failures/delays without patching jax globally."""
    return jax.device_put(arr, sharding)


def _batched_device_put_pairs(blks, devices):
    """ONE batched transfer call placing ``blks[i]`` on ``devices[i]``
    (the list form of ``jax.device_put`` dispatches them together) —
    replicated small leaves must not pay one client round-trip per
    replica device.  ``devices`` entries may be Devices OR Shardings
    (both are valid ``device_put`` destinations).  Falls back to the
    per-pair loop on jax versions without the list form.  The single
    fallback implementation: the serial ``_assemble``, the streamed
    ``upload_block``, and the engine's ``_shard_batch`` all route
    through here."""
    if not blks:
        return []
    try:
        return list(jax.device_put(list(blks), list(devices)))
    except (TypeError, ValueError):
        return [jax.device_put(b, d) for b, d in zip(blks, devices)]


def _batched_device_put(blk, devices):
    """Replicate one host block onto every device in ``devices`` with a
    single batched call."""
    return _batched_device_put_pairs([blk] * len(devices), devices)


class StreamingUploader:
    """Third stage of the streaming offload update pipeline: a single
    worker thread that issues H2D uploads for updated leaves WHILE the
    CPU Adam continues on later leaves.

    The consumer loop (``HostOffloadOptimizer.step`` /
    ``ShardedHostOffloadOptimizer`` with an ``on_leaf`` callback) calls
    ``submit(idx, arr)`` the moment leaf ``idx``'s block is written; the
    worker runs ``put_fn(idx, arr)`` off-thread, so a put that blocks on
    the actual transfer still overlaps the remaining host compute — with
    D2H prefetch (``_PrefetchPuller``) this closes the pipeline: leaf
    i+1's grad pull, leaf i's Adam, and leaf i-1's upload are all in
    flight at once.

    ``finish()`` drains the queue, re-raises the first failure, and
    returns ``(results, timings)``: ``results[idx]`` is ``put_fn``'s
    value, ``timings`` is ``[(idx, t_start, t_end, nbytes), ...]`` in
    host ``perf_counter`` seconds — the engine's overlap accounting
    (``offload/overlap_ratio``) reads these against the Adam window.
    Each upload also emits a per-leaf ``offload/h2d_params`` span on the
    module transfer tracer.

    On a NON-TRANSIENT failure the worker stops touching the device and
    ``finish()`` raises; the caller must then POISON the optimizer and
    leave its old compute-param tree in place (the master already
    carries step t, the device would keep step t-1 — the half-swapped
    state the pipeline contract forbids).  TRANSIENT failures (OSError —
    the stage runtime's retryable class) are retried against the same
    leaf up to the ``offload_h2d`` stage's failure budget; exhausting it
    DEGRADES the stage: this upload still completes (the inline
    equivalent, outside the injection plane) and the engine takes the
    serial update path from the next step on.

    Fault injection rides the unified spec (docs/stages.md):
    ``DS_STAGE_FAULT=offload_h2d:put:n[+]`` injects put failures and
    ``DS_STAGE_DELAY_S=offload_h2d:sec`` (alias: the legacy
    ``DS_OFFLOAD_H2D_DELAY_S``) sleeps INSIDE each span/timing window,
    emulating a slow PCIe link so a CPU run can measure real overlap.
    """

    def __init__(self, put_fn, what: str = "offload/h2d_params",
                 stage: Optional[Stage] = None):
        self._put = put_fn
        self._what = what
        # the engine threads its persistent ``offload_h2d`` Stage record
        # through so the failure budget counts across steps; standalone
        # constructions get a private one
        self._stage = stage if stage is not None else Stage("offload_h2d")
        self._q: list = []
        self._cond = threading.Condition()
        self._closed = False
        self._aborted = False
        self._err: Optional[BaseException] = None
        self._err_surfaced = False  # guarded by _cond: surface() once
        self._finish_owns_err = False  # finish() claimed it for re-raise
        self._done = threading.Event()
        self.results: dict = {}
        self.timings: list = []
        spawn(self._work, name="ds-offload-h2d", restarts=0)

    def _put_and_drain(self, idx: int, arr):
        out = self._put(idx, arr)
        # drain the transfer INSIDE the span/timing window: device_put
        # only dispatches, so without this the timings (and
        # overlap_ratio) would measure enqueue latency (the JL006 bug
        # class) — and an async transfer failure would escape the poison
        # contract by surfacing after finish() already succeeded.
        # Off-thread, so the Adam loop still overlaps.
        jax.block_until_ready(out)
        return out

    def _work(self):
        while True:
            with self._cond:
                self._cond.wait_for(lambda: self._q or self._closed)
                if not self._q:
                    break  # closed and drained
                idx, arr, ctx = self._q.pop(0)
            if self._err is not None:
                continue  # poisoned: drain submissions, touch nothing
            nbytes = int(getattr(arr, "nbytes", 0))
            t0 = time.perf_counter()
            try:
                with _transfer_span(self._what, leaf=idx, bytes=nbytes):
                    tracer = _TRANSFER_TRACER
                    if ctx is not None and tracer is not None:
                        # arrowhead inside this upload's span
                        tracer.flow_end("offload/upload", ctx,
                                        cat="offload", leaf=idx)
                    # the stage boundary: injected delay + fault,
                    # transient retry up to the budget, then degradation
                    # (the put still completes; the engine checks
                    # stage.degraded before the NEXT step)
                    out = self._stage.call(
                        "put", lambda: self._put_and_drain(idx, arr))
            except BaseException as e:  # re-raised from finish()
                with self._cond:
                    self._err = e
                    # exactly-once vs a concurrent abort(): whoever
                    # claims the flag under the lock does the surfacing
                    surface = self._aborted and not self._err_surfaced
                    if surface:
                        self._err_surfaced = True
                if surface:
                    # abort() already ran: nobody will call finish(), so
                    # without this the failure would vanish with the
                    # daemon thread — route it through the shared
                    # surfaced-error path (engine tick -> last_stage_error)
                    self._stage.surface(e)
                continue
            self.results[idx] = out
            self.timings.append((idx, t0, time.perf_counter(), nbytes))
        self._done.set()

    def submit(self, idx: int, arr):
        """Enqueue leaf ``idx``'s updated host block (called from the
        Adam loop; never blocks on the transfer).  Each upload carries a
        TraceContext: the flow opened here (inside the Adam loop's leaf
        span) terminates inside the worker's ``offload/h2d_params``
        span, drawing the Adam->upload causal arrow in trace.json."""
        ctx = None
        tracer = _TRANSFER_TRACER
        if tracer is not None and hasattr(tracer, "flow_start"):
            from ..telemetry.tracing import TraceContext
            ctx = TraceContext.new()
            tracer.flow_start("offload/upload", ctx, cat="offload",
                              leaf=idx)
        with self._cond:
            self._q.append((idx, arr, ctx))
            self._cond.notify_all()

    def finish(self):
        """Close the queue, wait for every upload, raise the first
        failure.  NOT watchdogged: the upload direction shares the probe
        warning's contract (a stalled H2D hangs — see
        ``_probe_transfer_path``).  A concurrent ``abort()`` (a close
        landing mid-step from another thread/signal handler) raises
        :class:`UploadAborted` instead of returning partial results —
        the caller's except path must poison, never publish a
        half-uploaded step."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._done.wait()
        with self._cond:
            err = self._err
            # claim the error under the exactly-once flag: a concurrent
            # abort() must not ALSO surface it through the stage record
            # (one failure, one report).  Ownership is remembered so a
            # REPEATED finish() keeps raising (poison invariant: never
            # return partial results while an error is recorded).
            if err is not None and not self._err_surfaced:
                self._err_surfaced = True
                self._finish_owns_err = True
            owns = self._finish_owns_err
            aborted = self._aborted
        if err is not None and owns:
            raise err
        # err set but surfaced by abort()/the worker before finish()
        # could claim it: the real error is on the stage record; the
        # step still must poison — fall through to the abort raise
        # (aborted is necessarily True on that arm)
        if aborted:
            raise UploadAborted(
                "streamed offload upload aborted mid-step (engine close/"
                "abort): queued uploads were dropped; the step must "
                "poison, not publish")
        return self.results, self.timings

    def abort(self):
        """Release the worker without waiting (the Adam side failed, or
        the engine is closing mid-flight: the caller's exception is the
        one that matters; queued uploads are dropped).  The in-flight
        put, if any, finishes in the background — a failure there (or
        one already recorded that no ``finish()`` has claimed for
        re-raise) is surfaced through the stage record instead of being
        dropped on the floor; the ``_err_surfaced`` flag keeps the
        worker/abort/finish triple exactly-once."""
        with self._cond:
            self._closed = True
            self._aborted = True
            self._q.clear()
            err = self._err
            # exactly-once vs the worker's own post-abort surfacing
            surface = err is not None and not self._err_surfaced
            if surface:
                self._err_surfaced = True
            self._cond.notify_all()
        if surface:
            self._stage.surface(err)


class HostOffloadOptimizer:
    """Owns the host-side master params + moments and the upload cast."""

    def __init__(self, master_params, lr, betas, eps, weight_decay,
                 adamw_mode: bool = True, bias_correction: bool = True,
                 compute_dtype=jnp.bfloat16,
                 use_native: Optional[bool] = None):
        # pull master to host numpy once; it never goes back whole.  The
        # pull is piece-wise with a per-piece watchdog (chunked_device_get)
        # so a sick link fails this tier cleanly instead of wedging the
        # device inside one un-interruptible multi-GB native call.
        # fp32-promote only floating leaves — integer/bool buffers keep
        # their dtype and are never touched by Adam (same rule the engine
        # applies building the master, engine.py master cast).
        def to_host(x):
            if is_adam_float(x.dtype):
                # pull pieces straight into the fp32 master buffer —
                # cast-on-assign, no transient full-leaf copy
                out = np.empty(np.shape(x), np.float32)
                return chunked_device_get(x, what="master pull", out=out)
            return np.array(chunked_device_get(x, what="master pull"))

        self._probe_transfer_path(master_params)
        self._poisoned: Optional[BaseException] = None
        self.last_d2h_seconds = 0.0  # last step's grad-pull wall time
        self.master = jax.tree.map(to_host, master_params)
        self.opt = DeepSpeedCPUAdam(
            lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
            adamw_mode=adamw_mode, bias_correction=bias_correction,
            use_native=use_native)
        self.compute_dtype = compute_dtype
        self._out_dtype = ("bfloat16" if compute_dtype == jnp.bfloat16
                           else "float16" if compute_dtype == jnp.float16
                           else None)

    @staticmethod
    def _probe_transfer_path(master_params, min_mbps: float = None,
                             probe_timeout: float = None):
        """Fail FAST if bulk device->host transfers are broken.

        The host tier is single-controller: it pulls the full fp32 master
        to this process and re-uploads compute params every step.  On a
        tunneled dev platform (axon websocket relay) bulk transfers were
        observed to stall *indefinitely* — un-interruptible by SIGALRM
        because the wait is inside one native call (round-3 root cause,
        BENCH_NOTES.md).  Probing a single ~4 MB pull in a worker thread
        converts that forever-stall into a clean RuntimeError, letting
        callers fall back (engine attempt chains, bench.py).  On a real
        TPU VM the probe costs one microseconds-scale PCIe copy.

        A probe that COMPLETES but measures under min_mbps is a working
        (just slow) link: that case logs a loud warning and proceeds —
        each subsequent bulk pull is chunked + watchdogged, so a link
        that later degrades into a stall still fails cleanly.  Set
        DS_OFFLOAD_SLOW_LINK=error to restore the hard failure (the
        bench chain does: a slow link there should fall through to the
        xla tier, not eat the measurement window).

        Knobs: DS_OFFLOAD_MIN_MBPS (default 8; 0 disables),
        DS_OFFLOAD_PROBE_TIMEOUT seconds (default 60),
        DS_OFFLOAD_SLOW_LINK = warn|error (default warn).
        """
        if min_mbps is None:
            min_mbps = float(os.environ.get("DS_OFFLOAD_MIN_MBPS", "8"))
        if probe_timeout is None:
            probe_timeout = float(
                os.environ.get("DS_OFFLOAD_PROBE_TIMEOUT", "60"))
        if min_mbps <= 0:
            return
        leaves = [x for x in jax.tree.leaves(master_params)
                  if hasattr(x, "nbytes")]
        if not leaves:
            return
        # largest leaf capped to ~4 MB worth of leading rows
        leaf = max(leaves, key=lambda x: x.nbytes)
        if leaf.nbytes > 4 << 20 and leaf.ndim >= 1 and leaf.shape[0] > 1:
            rows = max(1, int(leaf.shape[0] * (4 << 20) / leaf.nbytes))
            leaf = leaf[:rows]
        nbytes = leaf.nbytes
        if nbytes < 1 << 20:  # tiny models: nothing worth probing
            return
        # _watchdog_get runs the pull in an abandoned-on-timeout daemon
        # thread (see its docstring) AND propagates device_get exceptions
        # — a dead-tunnel XlaRuntimeError must fail the probe, not be
        # swallowed into a fast-looking measurement.
        t0 = time.perf_counter()
        _watchdog_get(leaf, probe_timeout, "device->host transfer probe")
        dt = time.perf_counter() - t0
        mbps = (nbytes / (1 << 20)) / max(dt, 1e-9)
        if mbps < min_mbps:
            msg = (
                f"device->host transfer probe measured {mbps:.1f} MB/s "
                f"(< {min_mbps} MB/s): the host offload tier would take "
                "minutes per step at this bandwidth. Use "
                "offload_impl='xla', or set DS_OFFLOAD_MIN_MBPS=0 to "
                "skip this probe.")
            if os.environ.get("DS_OFFLOAD_SLOW_LINK", "warn") == "error":
                raise RuntimeError(msg)
            logger.warning(
                "%s Proceeding anyway (DS_OFFLOAD_SLOW_LINK=warn); every "
                "device->host pull (bulk + per-step grads) is chunked "
                "with a per-piece progress watchdog, so slow links keep "
                "working and only a genuine pull-side stall fails "
                "cleanly. The per-step param re-UPLOAD is not guarded — "
                "if the upload direction stalls, the process hangs; set "
                "DS_OFFLOAD_SLOW_LINK=error to hard-fail instead.", msg)

    @property
    def is_native(self) -> bool:
        return self.opt.is_native

    def compute_params(self):
        """Initial low-precision copies for the device (non-floating
        leaves pass through unchanged)."""
        from ..ops.cpu_adam import lowp_np_dtype
        dt = lowp_np_dtype(self._out_dtype)

        def cast(x):
            if dt is None or x.dtype != np.float32:
                return x.copy()
            return x.astype(dt)

        return jax.tree.map(cast, self.master)

    def step(self, host_grads, on_leaf: Optional[Callable] = None):
        """Update master/moments in place; return upload copies in the
        configured compute dtype (fp32 configs get fp32 copies — no silent
        bf16 downgrade).  Grad leaves may be numpy OR jax Arrays — the
        inner optimizer converts per leaf via a watchdogged pull, which
        lets the engine overlap D2H transfers with the C++ Adam compute
        while a link that degrades into a stall MID-TRAINING still fails
        cleanly (the construction-time probe only certifies the link once;
        this guard holds for every step after; see _PrefetchPuller).

        ``on_leaf(i, upload_leaf)`` (optional) fires the moment leaf
        ``i``'s block is written — the streaming pipeline's hook: the
        engine submits each leaf to its H2D uploader while the Adam loop
        continues, so the re-upload overlaps the remaining host compute
        instead of serializing after it.  The returned tree holds the
        same objects the callback saw.

        A mid-step pull failure leaves master/moments PARTIALLY updated
        (leaves before the failing one carry step t, later ones do not,
        and the inner step counter advanced) — an inconsistency the
        old always-hang behavior could not produce.  The optimizer
        therefore POISONS itself: further step()/state_tree() calls
        refuse with a clear error so the inconsistent state can neither
        keep training nor be serialized; load_state_tree (checkpoint
        restore) clears the poison."""
        if self._poisoned is not None:
            raise RuntimeError(
                "HostOffloadOptimizer is poisoned: a previous step failed "
                "mid-update, leaving master/moments inconsistent. Restore "
                f"from a checkpoint. Original error: {self._poisoned!r}")
        leaf_get = _PrefetchPuller(host_grads)
        p_leaves, treedef = jax.tree.flatten(self.master)
        outs: list = [None] * len(p_leaves)
        try:
            for i, out in self.opt.step_leaves(
                    self.master, host_grads, out_dtype=self._out_dtype,
                    leaf_get=leaf_get,
                    leaf_span=lambda i: _transfer_span(
                        "offload/adam_leaf", cat="offload", leaf=i)):
                # fp32 configs upload fp32 copies of the freshly-updated
                # master leaf (the no-downgrade rule, same values the old
                # post-step tree.map(copy) produced)
                up = out if out is not None else p_leaves[i].copy()
                outs[i] = up
                if on_leaf is not None:
                    on_leaf(i, up)
        except BaseException as e:
            self._poisoned = e
            raise
        finally:
            self.last_d2h_seconds = leaf_get.seconds
            leaf_get.close()
        return jax.tree.unflatten(treedef, outs)

    def poison(self, err: BaseException):
        """Mark the optimizer inconsistent from OUTSIDE the step — the
        engine's streaming pipeline calls this when an H2D upload fails
        AFTER the Adam completed: the host master already carries step t
        while the device would keep step t-1 params, a mismatch that
        must not keep training or serialize (load_state_tree clears)."""
        self._poisoned = err

    # -- checkpoint plumbing -------------------------------------------
    def state_tree(self):
        """Optimizer state as a pytree aligned with the master params
        (what the engine stores in TrainState.opt_state and the
        checkpointer serializes).  Refuses while poisoned — serializing a
        partially-updated master/moment set would turn a clean failure
        into silent divergence on restore."""
        if self._poisoned is not None:
            raise RuntimeError(
                "refusing to serialize inconsistent optimizer state (a "
                "step failed mid-update). Restore from an earlier "
                f"checkpoint. Original error: {self._poisoned!r}")
        leaves, treedef = jax.tree.flatten(self.master)
        mu, nu = [], []
        for i, leaf in enumerate(leaves):
            m, v = self.opt._moments(i, leaf)
            mu.append(m)
            nu.append(v)
        return {"step": np.asarray(self.opt.step_count, np.int64),
                "mu": jax.tree.unflatten(treedef, mu),
                "nu": jax.tree.unflatten(treedef, nu)}

    def load_state_tree(self, master_tree, opt_tree):
        """In-place restore (buffer identity preserved so the numpy views
        the native kernel updates stay the engine's state)."""
        self._poisoned = None  # restore re-establishes a consistent state
        def copy_into(dst, src):
            chunked_device_get(src, what="restore pull", out=dst)
        jax.tree.map(copy_into, self.master, master_tree)
        self.opt.step_count = int(np.asarray(
            jax.device_get(opt_tree["step"])))
        leaves = jax.tree.leaves(self.master)
        mu = jax.tree.leaves(opt_tree["mu"])
        nu = jax.tree.leaves(opt_tree["nu"])
        for i, leaf in enumerate(leaves):
            m, v = self.opt._moments(i, leaf)
            chunked_device_get(mu[i], what="restore pull", out=m)
            chunked_device_get(nu[i], what="restore pull", out=v)


def _index_key(index) -> tuple:
    """Hashable key for a shard's global index (a tuple of slices)."""
    return tuple((s.start, s.stop, s.step) for s in index)


class ShardedHostOffloadOptimizer:
    """Multi-host ZeRO-Offload host tier.

    Each process pulls ONLY its addressable shards of the dp-sharded fp32
    master into host numpy — the reference's per-DP-rank fp32 partitions
    (reference: deepspeed/runtime/zero/stage2.py:743-900, where each rank
    stages its own ``get_grad_position`` ranges into pinned buffers) —
    and the native C++ Adam updates them in place.  Per step, each
    process stages only its shard of the reduce-scattered gradients
    (staged bytes per host ~ total/dp), and the updated low-precision
    shards are re-assembled into a global array whose all-gather to the
    compute sharding runs ON DEVICE over ICI (one jitted identity in the
    engine) — no host ever handles another rank's bytes, removing the
    single-controller tier's process-0 staging and master bottleneck.

    Replicated leaves (biases, norms) are deduplicated by shard index:
    one host block + one set of moments per UNIQUE slice, shared across
    the local devices that hold a replica.
    """

    def __init__(self, master_global, lr, betas, eps, weight_decay,
                 adamw_mode: bool = True, bias_correction: bool = True,
                 compute_dtype=jnp.bfloat16,
                 use_native: Optional[bool] = None):
        leaves = jax.tree.leaves(master_global)
        self._treedef = jax.tree.structure(master_global)
        self._shardings = [l.sharding for l in leaves]
        self._shapes = [tuple(l.shape) for l in leaves]
        self._poisoned: Optional[BaseException] = None
        self.opt = DeepSpeedCPUAdam(
            lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
            adamw_mode=adamw_mode, bias_correction=bias_correction,
            use_native=use_native)
        self.compute_dtype = compute_dtype
        self._out_dtype = ("bfloat16" if compute_dtype == jnp.bfloat16
                           else "float16" if compute_dtype == jnp.float16
                           else None)
        # per leaf: ordered unique-index groups
        #   {"index": shard index, "devices": [Device], "block": fp32 np}
        self._local = []
        for leaf in leaves:
            groups: dict = {}
            order = []
            # fp32-promote only floating shards — the same to_host rule
            # as the single-controller tier: integer/bool buffers keep
            # their dtype, cpu_adam's fp32-only check skips them, and
            # they round-trip through assemble/checkpoint uncast.
            ldt = np.dtype(leaf.dtype)
            promote = is_adam_float(ldt)
            for s in leaf.addressable_shards:
                k = _index_key(s.index)
                if k not in groups:
                    pulled = chunked_device_get(
                        s.data, what="master shard pull")
                    blk = np.array(
                        pulled,
                        dtype=np.float32 if promote else ldt)
                    groups[k] = {"index": s.index, "devices": [],
                                 "block": blk}
                    order.append(k)
                groups[k]["devices"].append(s.device)
            self._local.append([groups[k] for k in order])
        # flat-order view of the unique groups — the streaming pipeline's
        # addressing: on_leaf/upload_block/assemble_uploaded all speak
        # this index
        self._flat_groups = [(li, gi, g)
                             for li, leaf in enumerate(self._local)
                             for gi, g in enumerate(leaf)]
        self.last_d2h_seconds = 0.0  # last step's grad-pull wall time

    # -- introspection --------------------------------------------------
    def staged_bytes(self) -> int:
        """Host bytes this process stages for the master (the per-host
        partition size the multi-host design bounds to ~ total/dp)."""
        return sum(g["block"].nbytes
                   for leaf in self._local for g in leaf)

    @property
    def is_native(self) -> bool:
        return self.opt.is_native

    @property
    def master(self):
        """Local-blocks pytree (leaves = lists of fp32 numpy blocks) —
        the engine's TrainState view between checkpoints.  Canonical
        (global-array) form comes from ``canonical_master()``."""
        return jax.tree.unflatten(
            self._treedef,
            [[g["block"] for g in leaf] for leaf in self._local])

    # -- assembly -------------------------------------------------------
    def _assemble(self, block_fn, np_dtype):
        """Global jax arrays from per-group host blocks.  ``block_fn(li,
        gi, g)`` returns the host block to place for group ``g`` (index
        ``gi`` within leaf ``li``); each local device holding that index
        receives a copy and ``make_array_from_single_device_arrays``
        stitches the global view (non-addressable shards belong to the
        other processes).  ``np_dtype`` applies to FLOATING blocks only;
        integer/bool blocks keep their own dtype (the single-controller
        tier's rule — Adam never touched them, so no cast is correct).

        All H2D puts are issued as ONE batched ``jax.device_put`` call:
        replicated small leaves (biases, norms) must not pay a client
        round-trip per replica device per leaf.  The stitch is
        ``assemble_uploaded`` — the same tail the streamed path uses."""
        blks, devs, group_sizes = [], [], []
        for li, leaf_groups in enumerate(self._local):
            for gi, g in enumerate(leaf_groups):
                blk = np.asarray(block_fn(li, gi, g))
                if is_adam_float(blk.dtype):
                    blk = np.asarray(blk, dtype=np_dtype)
                blks.extend([blk] * len(g["devices"]))
                devs.extend(g["devices"])
                group_sizes.append(len(g["devices"]))
        puts = _batched_device_put_pairs(blks, devs)
        uploaded, pos = [], 0
        for n in group_sizes:
            uploaded.append(puts[pos:pos + n])
            pos += n
        return self.assemble_uploaded(uploaded)

    def upload_block(self, flat_idx: int, blk):
        """H2D for ONE updated group (streaming pipeline): apply
        ``_assemble``'s float cast rule, then one batched put to every
        replica device of the group.  Returns the per-device arrays in
        the group's device order — ``assemble_uploaded`` stitches them
        once every group is in."""
        li, gi, g = self._flat_groups[flat_idx]
        blk = np.asarray(blk)
        if is_adam_float(blk.dtype):
            dt = lowp_np_dtype(self._out_dtype)
            blk = np.asarray(blk,
                             dtype=dt if dt is not None else np.float32)
        return _batched_device_put(blk, g["devices"])

    def assemble_uploaded(self, uploaded):
        """Global arrays from already-uploaded per-group device arrays
        (``uploaded[flat_idx]`` = what ``upload_block`` returned).  The
        streaming pipeline's tail: every transfer was issued leaf by
        leaf under the Adam loop; this only stitches the global views —
        no host bytes move here."""
        assert len(uploaded) == len(self._flat_groups), (
            len(uploaded), len(self._flat_groups))
        out, i = [], 0
        for leaf_groups, sharding, shape in zip(
                self._local, self._shardings, self._shapes):
            arrays = []
            for _ in leaf_groups:
                arrays.extend(uploaded[i])
                i += 1
            out.append(jax.make_array_from_single_device_arrays(
                shape, sharding, arrays))
        return jax.tree.unflatten(self._treedef, out)

    def compute_params(self):
        """Initial compute-dtype global params (dp-sharded like the
        master; the engine's jitted gather reshard them to the compute
        sharding — the fused ZeRO param all-gather on ICI)."""
        dt = lowp_np_dtype(self._out_dtype)
        np_dt = dt if dt is not None else np.float32
        # one allocation per block: cast floating blocks here (a no-op
        # for _assemble's float cast), copy uncast ones so the device
        # buffer never aliases the live master block; int/bool blocks
        # pass through at their own dtype either way
        return self._assemble(
            lambda li, gi, g: (g["block"].astype(np_dt)
                               if dt is not None and
                               is_adam_float(g["block"].dtype)
                               else g["block"].copy()), np_dt)

    # -- the step -------------------------------------------------------
    def _local_grad_shards(self, grads):
        """This process's per-group grad shards (single-device jax
        arrays) in the blocks' flat order.  ``grads``: global jax arrays
        whose sharding must match the master's (the engine constrains
        them with the ZeRO plan)."""
        flat_g = []
        for leaf_groups, gleaf in zip(self._local, jax.tree.leaves(grads)):
            by_key = {}
            for s in gleaf.addressable_shards:
                by_key.setdefault(_index_key(s.index), s)
            for g in leaf_groups:
                k = _index_key(g["index"])
                if k not in by_key:
                    raise ValueError(
                        "gradient sharding does not match the master "
                        "sharding — the sharded host tier requires the "
                        "ZeRO plan's grad placement (engine constrains "
                        "this; custom grad trees must match)")
                flat_g.append(by_key[k].data)
        return flat_g

    def pull_local(self, grads):
        """Pull this process's grad shards to host numpy (dedup by
        index, dtype-preserving, chunked + watchdogged) — the DPU stash
        form: the device grad tree can be freed while the host copies
        wait for the overlapped ``step_local``."""
        flat_g = self._local_grad_shards(grads)
        cb = pull_chunk_bytes()
        for a in flat_g:
            if hasattr(a, "copy_to_host_async") and (
                    cb <= 0 or getattr(a, "nbytes", 0) <= cb):
                a.copy_to_host_async()
        return guarded_tree_pull(flat_g)

    def step(self, grads, on_leaf: Optional[Callable] = None):
        """C++ Adam over THIS process's shards only.  Returns global
        compute-dtype params (master-sharded; gather happens in the
        engine's jitted identity), or None when ``on_leaf`` is given —
        the streaming pipeline: ``on_leaf(flat_idx, block)`` fires per
        updated group, the engine uploads each via ``upload_block`` and
        stitches with ``assemble_uploaded``.  Poisons on mid-step
        failure exactly like the single-controller tier."""
        if self._poisoned is not None:
            raise RuntimeError(
                "ShardedHostOffloadOptimizer is poisoned: a previous "
                "step failed mid-update. Restore from a checkpoint. "
                f"Original error: {self._poisoned!r}")
        flat_g = self._local_grad_shards(grads)
        # async D2H only for shards the puller fetches in ONE native call
        # — larger shards stream piece-wise (chunked_device_get); a full-
        # shard async copy alongside the slice pulls would move the same
        # bytes over the wire twice (the _start_small_leaf_d2h rule)
        cb = pull_chunk_bytes()
        for a in flat_g:
            if hasattr(a, "copy_to_host_async") and (
                    cb <= 0 or getattr(a, "nbytes", 0) <= cb):
                a.copy_to_host_async()
        return self._adam_over_blocks(flat_g, prefetch=True,
                                      on_leaf=on_leaf)

    def step_local(self, blocks, on_leaf: Optional[Callable] = None):
        """The DPU apply half: C++ Adam over host blocks that
        ``pull_local`` staged earlier (numpy; no device access).
        ``on_leaf``: same streaming hook as ``step``."""
        if self._poisoned is not None:
            raise RuntimeError(
                "ShardedHostOffloadOptimizer is poisoned: a previous "
                "step failed mid-update. Restore from a checkpoint. "
                f"Original error: {self._poisoned!r}")
        return self._adam_over_blocks(list(blocks), prefetch=False,
                                      on_leaf=on_leaf)

    def _adam_over_blocks(self, flat_g, prefetch: bool,
                          on_leaf: Optional[Callable] = None):
        flat_p = [g["block"] for leaf in self._local for g in leaf]
        assert len(flat_p) == len(flat_g), (len(flat_p), len(flat_g))
        puller = _PrefetchPuller(flat_g) if prefetch else None
        outs: list = [None] * len(flat_p)
        try:
            for i, out in self.opt.step_leaves(
                    flat_p, flat_g, out_dtype=self._out_dtype,
                    leaf_get=puller,
                    leaf_span=lambda i: _transfer_span(
                        "offload/adam_leaf", cat="offload", leaf=i)):
                # fp32 configs stream fp32 copies of the updated block
                # (the single-controller no-downgrade rule)
                up = out if out is not None else flat_p[i].copy()
                outs[i] = up
                if on_leaf is not None:
                    on_leaf(i, up)
        except BaseException as e:
            self._poisoned = e
            raise
        finally:
            self.last_d2h_seconds = puller.seconds if puller else 0.0
            if puller is not None:
                puller.close()
        if on_leaf is not None:
            return None  # uploads already in flight; engine assembles
        dt = lowp_np_dtype(self._out_dtype)
        np_dt = dt if dt is not None else np.float32
        it = iter(outs)
        nested = [[next(it) for _ in leaf] for leaf in self._local]
        return self._assemble(
            lambda li, gi, g, _l=nested: _l[li][gi], np_dt)

    def poison(self, err: BaseException):
        """Engine-side poison (an H2D upload failed after the Adam
        completed) — same contract as the single-controller tier."""
        self._poisoned = err

    # -- checkpoint plumbing --------------------------------------------
    def state_tree(self):
        """Cheap per-step view (local moment blocks); the canonical
        global-array form for saving comes from canonical_state()."""
        if self._poisoned is not None:
            raise RuntimeError(
                "refusing to serialize inconsistent optimizer state (a "
                "step failed mid-update). Restore from an earlier "
                f"checkpoint. Original error: {self._poisoned!r}")
        flat = [g["block"] for leaf in self._local for g in leaf]
        mu, nu = [], []
        for i, blk in enumerate(flat):
            m, v = self.opt._moments(i, blk)
            mu.append(m)
            nu.append(v)
        it_m, it_v = iter(mu), iter(nu)
        return {"step": np.asarray(self.opt.step_count, np.int64),
                "mu": jax.tree.unflatten(
                    self._treedef,
                    [[next(it_m) for _ in leaf] for leaf in self._local]),
                "nu": jax.tree.unflatten(
                    self._treedef,
                    [[next(it_v) for _ in leaf] for leaf in self._local])}

    def canonical_state(self):
        """(master, {step, mu, nu}) as GLOBAL fp32 jax arrays (master-
        sharded, non-fully-addressable) — the save-time form: the
        checkpointer writes per-process shard files and merges on load.
        Costs one device round-trip per leaf, paid only at save."""
        if self._poisoned is not None:
            raise RuntimeError(
                "refusing to serialize inconsistent optimizer state; "
                f"original error: {self._poisoned!r}")
        master = self._assemble(lambda li, gi, g: g["block"], np.float32)
        flat = [g["block"] for leaf in self._local for g in leaf]
        moments = [self.opt._moments(i, b) for i, b in enumerate(flat)]
        it = iter(moments)
        per_leaf = [[next(it) for _ in leaf] for leaf in self._local]

        def pick(which):
            return lambda li, gi, g, _p=per_leaf: _p[li][gi][which]
        mu = self._assemble(pick(0), np.float32)
        nu = self._assemble(pick(1), np.float32)
        return master, {"step": np.asarray(self.opt.step_count, np.int64),
                        "mu": mu, "nu": nu}

    def load_state_tree(self, master_tree, opt_tree):
        """In-place restore from canonical global arrays (or full numpy):
        each process scatters ONLY its local shards back into its blocks."""
        self._poisoned = None

        def scatter(tree, which=None, moments=False):
            leaves = jax.tree.leaves(tree)
            flat_i = 0
            for li, leaf_groups in enumerate(self._local):
                src = leaves[li]
                for g in leaf_groups:
                    if isinstance(src, jax.Array) and not getattr(
                            src, "is_fully_addressable", True):
                        by_key = {_index_key(s.index): s
                                  for s in src.addressable_shards}
                        blk = chunked_device_get(
                            by_key[_index_key(g["index"])].data,
                            what="restore shard pull")
                    else:
                        arr = (np.asarray(src) if not isinstance(
                            src, jax.Array) else chunked_device_get(
                                src, what="restore pull"))
                        blk = arr[g["index"]]
                    # cast-on-assign preserves the destination dtype
                    # (fp32 for floating blocks, own dtype otherwise —
                    # an explicit fp32 hop would corrupt wide ints)
                    if moments:
                        m, v = self.opt._moments(flat_i, g["block"])
                        dst = m if which == 0 else v
                        dst[...] = np.asarray(blk)
                    else:
                        g["block"][...] = np.asarray(blk)
                    flat_i += 1

        scatter(master_tree)
        if opt_tree is None:
            for m, v in self.opt._state.values():
                m[...] = 0.0
                v[...] = 0.0
            self.opt.step_count = 0
            return
        self.opt.step_count = int(np.asarray(
            jax.device_get(opt_tree["step"])))
        scatter(opt_tree["mu"], which=0, moments=True)
        scatter(opt_tree["nu"], which=1, moments=True)

    def canonical_templates(self):
        """Zero-filled global arrays shaped/sharded like canonical_state()
        — the load targets: the checkpoint loader reads only each
        process's addressable ranges into them (per-process shard files,
        merge-on-load).  Block-size transients only."""
        def zeros(li, gi, g):
            # block dtype = fp32 for floating leaves, own dtype for
            # int/bool (moments of untouched leaves are zeros_like)
            return np.zeros(np.shape(g["block"]), g["block"].dtype)
        master = self._assemble(zeros, np.float32)
        mu = self._assemble(zeros, np.float32)
        nu = self._assemble(zeros, np.float32)
        return master, {"step": np.asarray(self.opt.step_count, np.int64),
                        "mu": mu, "nu": nu}
