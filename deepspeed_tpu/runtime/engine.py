"""DeepSpeedEngine — the core training engine, TPU-native.

The reference engine (reference: deepspeed/runtime/engine.py:91-1478) is an
imperative nn.Module wrapper: eager forward, autograd-hook-driven gradient
reduction, Python-side overflow bookkeeping, bucketed NCCL allreduce.  Here
the entire step — forward, loss scaling, backward, gradient reduction
(sharding-driven), overflow check, ``lax.cond`` skip-vs-update, clipping,
optimizer — is ONE jit-compiled function with donated state (SURVEY.md §7
layer 3).  Python keeps only un-traced concerns: counters for logging,
timers, checkpoint I/O, and the dataloader.

API surface preserved from the reference:
  - ``train_batch(batch)``   — the fast path (one compiled step incl. grad
                               accumulation via ``lax.scan``), mirroring
                               PipelineEngine.train_batch semantics.
  - ``forward`` / ``backward`` / ``step`` — the reference's imperative trio
    (engine.py:779/820/956) as a compatibility facade: ``forward`` runs a
    (jitted) forward for the loss, ``backward`` queues the micro-batch, and
    ``step`` executes the fused train step at the accumulation boundary.
    Costs one extra forward per micro-batch vs ``train_batch``; documented.
"""
from __future__ import annotations

import collections
import contextlib
import os
import statistics
import threading
import time
import weakref
from typing import Any, Callable, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import DeepSpeedConfig
from ..config import constants as C
from ..ops.adam import (FusedAdamState, adam_direction, adam_moments,
                        fused_adam)
from ..ops.lamb import fused_lamb
from ..parallel.mesh import DATA_AXIS, build_mesh, mesh_axis_size
from ..utils.logging import log_dist, logger
from . import precision
from .engine_stages import (finish_close, pop_stage_errors,
                            stage_degraded, wire_stage_plane)
from .lr_schedules import get_lr_schedule
from .module import TrainModule
from .prefetch import DevicePlacedBatch, DevicePrefetcher
from .precision import LossScaleState
from .utils import clip_by_global_norm, global_norm
from .zero import ZeroShardingPlan, constrain_grads

MEMORY_OPT_ALLREDUCE_SIZE = 500_000_000  # kept for parity (engine.py:41)


class TrainState(NamedTuple):
    """Everything the compiled step reads and writes (a single pytree so the
    whole update is donation-friendly)."""
    master_params: Any          # fp32 source of truth (placement: ZeRO plan)
    opt_state: Any
    scaler: LossScaleState
    global_steps: jnp.ndarray   # i32 — applied + skipped steps
    skipped_steps: jnp.ndarray  # i32 — overflow-skipped steps
    rng: jax.Array


class StepMetrics(NamedTuple):
    loss: jnp.ndarray
    grad_norm: jnp.ndarray
    loss_scale: jnp.ndarray
    overflow: jnp.ndarray
    lr: jnp.ndarray


class _HostBlockStash:
    """Explicit tag for the sharded host tier's DPU stash (the host
    blocks ``ShardedHostOffloadOptimizer.pull_local`` returns).  The tag
    exists so ``_apply_host_update`` can distinguish the stash from a
    live gradient pytree without sniffing container types — a model
    whose parameter tree is itself a top-level list must not be
    misrouted into ``step_local``."""

    __slots__ = ("blocks",)

    def __init__(self, blocks):
        self.blocks = list(blocks)


class _FlatLeaf(NamedTuple):
    """Per-leaf record of the offload tier's partition-major flat layout.

    ``data_dim`` is the leaf dim the ZeRO plan shards over ``data`` (the
    dim is moved to the front before flattening so each rank's chunk of
    the flat vector is exactly its shard — all reshapes stay sharding-
    natural and collective-free).  ``None`` means the leaf has no leading
    data sharding; it is padded to a multiple of dp and row-chunked.
    ``w`` is the leaf's per-rank width in the (dp, W) flat view."""
    shape: tuple
    size: int
    data_dim: Optional[int]
    w: int
    pad: int


def _flat_leaf_layout(shape: tuple, size: int, spec, dp: int) -> _FlatLeaf:
    """Choose the flat-layout record for one leaf from its ZeRO grad/param
    spec.  A dim qualifies as ``data_dim`` when the spec shards it over
    ``data`` either alone or as the MAJOR axis of a tuple entry (GSPMD
    tuple shardings are major-to-minor, so moving that dim to the front
    keeps the reshape split (dp, d/dp, ...) natural)."""
    data_dim = None
    for i, entry in enumerate(spec or ()):
        if entry == DATA_AXIS or (isinstance(entry, tuple) and entry
                                  and entry[0] == DATA_AXIS):
            data_dim = i
            break
    if dp > 1 and data_dim is not None and shape[data_dim] % dp == 0:
        return _FlatLeaf(shape, size, data_dim, size // dp, 0)
    pad = (-size) % dp
    return _FlatLeaf(shape, size, None, (size + pad) // dp, pad)


def _pack_leaf(x, rec: _FlatLeaf, dp: int, xp):
    """Leaf array (already dtype-cast) -> its (dp, w) flat piece.  ONE
    implementation parameterized over ``xp`` (jnp for the traceable pair,
    np for the checkpoint pair) so the device layout and the checkpoint
    layout cannot desynchronize."""
    if rec.data_dim is not None:
        return xp.moveaxis(x, rec.data_dim, 0).reshape(dp, rec.w)
    v = x.reshape(-1)
    if rec.pad:
        v = xp.concatenate([v, xp.zeros((rec.pad,), v.dtype)])
    return v.reshape(dp, rec.w)


def _unpack_leaf(sl, rec: _FlatLeaf, xp):
    """Inverse of ``_pack_leaf``: a (dp, w) slice -> the leaf shape."""
    if rec.data_dim is not None:
        moved = ((rec.shape[rec.data_dim],)
                 + tuple(d for i, d in enumerate(rec.shape)
                         if i != rec.data_dim))
        return xp.moveaxis(sl.reshape(moved), 0, rec.data_dim)
    return sl.reshape(-1)[:rec.size].reshape(rec.shape)


def _offload_update_scalars(count, finites, sumsqs, *, b1, b2,
                            bias_correction, clip, lr_at):
    """Shared scalar math for the offload update programs (fused update_fn
    AND the split-update stats program — one definition so the bias
    correction / clip / lr semantics cannot drift): combine per-group
    finiteness, cross-group global norm, Adam bias corrections at the
    next count, the scheduled lr, and the fp32 clip factor."""
    finite = finites[0]
    for f in finites[1:]:
        finite = jnp.logical_and(finite, f)
    grad_norm = jnp.sqrt(sum(sumsqs))
    count1 = count + 1
    count_f = count1.astype(jnp.float32)
    if bias_correction:
        c1 = 1 - b1 ** count_f
        c2 = 1 - b2 ** count_f
    else:
        c1 = c2 = jnp.asarray(1.0, jnp.float32)
    step_lr = lr_at(count1)
    # clip factor from the cross-group global norm, applied in fp32 on
    # the host (the single-program path clips on device pre-pack; same
    # linear scaling, fp32 here)
    cscale = (jnp.minimum(1.0, clip / (grad_norm + 1e-6))
              if clip > 0 else jnp.asarray(1.0, jnp.float32))
    return finite, grad_norm, c1, c2, step_lr, cscale


class DeepSpeedEngine:
    def __init__(self,
                 model: TrainModule,
                 config: DeepSpeedConfig,
                 mesh=None,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 lr_schedule: Optional[Callable] = None,
                 params: Optional[Any] = None,
                 seed: int = 0,
                 training_data=None,
                 collate_fn=None):
        self.module = model
        self.config = config
        self.mesh = mesh if mesh is not None else build_mesh()
        self.dp_world_size = mesh_axis_size(self.mesh, DATA_AXIS)
        if config.world_size != self.dp_world_size:
            # catch the mismatch at construction, not at batch-shape time
            # (round-1 verdict weak #8); initialize() derives world_size
            # from the mesh, so this only fires for hand-built configs
            raise ValueError(
                f"DeepSpeedConfig was built for world_size="
                f"{config.world_size} but the mesh's data axis is "
                f"{self.dp_world_size}; construct the config with the "
                f"mesh's data-axis size (deepspeed_tpu.initialize does "
                f"this automatically)")

        # Pallas kernels need interpret mode off-TPU; the mesh knows where
        # the computation actually runs (see ops/pallas/runtime.py).  The
        # scope is entered around compiled-step calls (_pallas_scope) so
        # engines on different meshes don't fight over a global.
        #
        # The scope ALSO establishes the ambient mesh (jax.set_mesh):
        # model-side code reads jax.sharding.get_abstract_mesh() during
        # trace — sequence-parallel attention discovers the 'seq' axis,
        # MoE binds its expert constraint, and the param-streaming fetch
        # builds its device placement from it.  Without the ambient mesh
        # those reads see an EMPTY AbstractMesh inside jit (argument
        # shardings do not populate it) and every one of those features
        # silently degrades.
        from ..ops.pallas.runtime import interpret_scope, mesh_wants_interpret
        self._pallas_interpret = mesh_wants_interpret(self.mesh)

        def _step_scope():
            import contextlib
            stack = contextlib.ExitStack()
            stack.enter_context(interpret_scope(self._pallas_interpret))
            if hasattr(jax, "set_mesh"):
                stack.enter_context(jax.set_mesh(self.mesh))
            else:
                # jax<0.6 compat: entering the Mesh context sets the same
                # ambient mesh for trace-time reads
                stack.enter_context(self.mesh)
            return stack

        self._pallas_scope = _step_scope

        self.compute_dtype = precision.select_compute_dtype(
            config.fp16_enabled, config.bf16_enabled)
        # _CallableInt/_CallableFloat: value semantics for this codebase's
        # attribute style AND the reference's method-call style
        # (engine.train_batch_size() at engine.py:296 there) in one name
        self.micro_batch_size = _CallableInt(
            config.train_micro_batch_size_per_gpu)
        self.gradient_accumulation_steps = _CallableInt(
            config.gradient_accumulation_steps)
        self.train_batch_size = _CallableInt(config.train_batch_size)

        # ---- optimizer + lr schedule (reference _configure_optimizer,
        # engine.py:527-615) ----
        self._lr_schedule = self._resolve_lr_schedule(lr_schedule)
        self.optimizer = (optimizer if optimizer is not None
                          else self._build_basic_optimizer())
        if config.gradient_clipping and config.gradient_clipping > 0:
            self.gradient_clipping = _CallableFloat(
                float(config.gradient_clipping))
        else:
            self.gradient_clipping = _CallableFloat(0.0)

        # ---- ZeRO placement plan ----
        init_rng, self._data_rng = jax.random.split(jax.random.PRNGKey(seed))

        def _cast_master(tree):
            return jax.tree.map(
                lambda x: x.astype(jnp.float32)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)

        # Offload-impl resolution must precede init: the xla tier stages
        # the master leaf-by-leaf during init (below) so the full fp32
        # tree never has to fit in device memory.
        self._offload = bool(config.zero_config.cpu_offload)
        if (os.environ.get("DS_OFFLOAD_SPLIT_UPDATE") == "1"
                and not self._offload):
            # The env knob is process-wide; an unrelated comparison/eval
            # engine constructed alongside the experiment engine must not
            # die on it (the config-flag path would not reject it either).
            # Warn instead of raising: the knob simply has nothing to
            # flip on an engine without cpu_offload.
            logger.warning(
                "DS_OFFLOAD_SPLIT_UPDATE=1 ignored: this engine has no "
                "zero_optimization.cpu_offload, so there is no offload "
                "update to split")
        # set when a partially-donated update leaves self.state pointing
        # at deleted buffers (offload_split_update mid-piece failure);
        # train/save must refuse rather than act on the corrupt state
        self._fatal_state_error = None
        self._offload_impl = None
        if self._offload:
            impl = config.zero_config.offload_impl
            if impl == "auto":
                platform = next(iter(self.mesh.devices.flat)).platform
                impl = "xla" if platform == "tpu" else "host"
            self._offload_impl = impl
        self._offload_host = self._offload_impl == "host"

        if params is not None:
            master = _cast_master(params)
        else:
            # ONE compiled program for init+fp32-cast.  Eager init
            # dispatches each leaf's random_normal/zeros as its own
            # program — on a remote-compile platform (axon tunnel) that
            # is ~15 sequential compile round-trips, observed as a
            # multi-minute "constructing engine" stall at 1.5B (round-2
            # BENCH_NOTES stall; the same wall hit both offload tiers).
            # The TrainModule protocol does not REQUIRE a traceable init
            # (a user init_fn may branch on concrete values or embed
            # numpy weights), so fall back to eager on trace failure.
            #
            # XLA-offload tier at large scale: init in COMPUTE dtype when
            # the fp32 tree would exceed DS_OFFLOAD_FP32_INIT_LIMIT bytes
            # (default 2 GiB) — the master is then the fp32 cast of
            # bf16-rounded random draws (statistically identical; the
            # reference also only ever trains on the half-precision view
            # of its init).  Halves the device-resident peak during
            # construction, which is what bounds trainable-params/chip
            # with offload.
            def _init_cast(r, dt):
                tree = model.init(r)
                if dt is None:
                    return _cast_master(tree)
                return jax.tree.map(
                    lambda x: x.astype(dt)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)

            try:
                init_out_dtype = None
                if self._offload and not self._offload_host:
                    # eval_shape traces too — keep it under the fallback
                    abstract = jax.eval_shape(model.init, init_rng)
                    total = sum(
                        4 * int(np.prod(l.shape)) if l.shape else 4
                        for l in jax.tree.leaves(abstract)
                        if jnp.issubdtype(l.dtype, jnp.floating))
                    limit = int(float(os.environ.get(
                        "DS_OFFLOAD_FP32_INIT_LIMIT", str(2 << 30))))
                    if total > limit:
                        init_out_dtype = self.compute_dtype
                # one-shot construction program: the master's placement is
                # settled by the zero plan / offload staging right below
                # jaxlint: disable=JL003
                master = jax.jit(
                    _init_cast, static_argnums=(1,))(init_rng,
                                                     init_out_dtype)
            except jax.errors.JAXTypeError:
                logger.warning(
                    "model.init is not jit-traceable; initializing "
                    "eagerly (slower on remote-compile platforms)")
                master = _init_cast(init_rng, None)
        self.zero_plan = ZeroShardingPlan(
            stage=config.zero_optimization_stage, mesh=self.mesh,
            base_param_specs=model.param_partition_specs(master),
            offload=config.zero_config.cpu_offload,
            params=master)
        # sanitized in the plan: indivisible dims fall back to replication
        # (e.g. 4 experts declared over an 8-way data axis)
        base_specs = self.zero_plan.base_param_specs

        scaler, self.loss_scale_config = precision.from_fp16_config(config.fp16)
        # 1-bit Adam engages a dedicated shard_map step (local grads feed
        # the compressed collective); ZeRO sharding does not compose with
        # it — reference parity: OnebitAdam is excluded from the ZeRO
        # whitelist (reference deepspeed/runtime/zero/utils.py:26-40) and
        # runs under the fp16 wrapper at stage 0 there too.
        self._onebit_path = (
            config.optimizer_name == C.ONEBIT_ADAM_OPTIMIZER
            and optimizer is None)
        if self._onebit_path and config.zero_optimization_stage >= 1:
            raise ValueError(
                "OneBitAdam is not a ZeRO-supported optimizer (reference "
                "zero/utils.py:26-40): its compressed collective replaces "
                "the data-parallel gradient reduction, which conflicts with "
                "ZeRO's sharded gradients/state. Use zero stage 0.")
        if self._offload:
            name = config.optimizer_name or C.ADAM_OPTIMIZER
            if name != C.ADAM_OPTIMIZER or optimizer is not None:
                raise ValueError(
                    "cpu_offload requires the built-in Adam optimizer "
                    "(the reference's offload whitelist likewise admits "
                    "only Adam-family, zero/utils.py:26-40)")
        if self._offload and not self._offload_host:
            # ZeRO-Offload, XLA-native tier: fp32 master + Adam moments
            # live in the TPU host's memory (``pinned_host`` kind) as one
            # partition-major [dp, w_i] piece PER PARAMETER, sharded over
            # ``data`` — each process's host stages only its own reduce-
            # scattered partition, the piece-wise analogue of the
            # reference's per-rank fp32 partitions (reference:
            # deepspeed/runtime/zero/stage2.py:262-269,743-900;
            # pinned-tile streaming: csrc/adam/cpu_adam.cpp:64-113, here
            # scheduled by XLA inside the one compiled step).  Pieces, not
            # one concatenated vector: staging then proceeds leaf-at-a-
            # time, so construction's device-resident peak is the init
            # tree plus ONE piece rather than 2× the full fp32 state —
            # this is what bounds peak trainable params/chip with offload
            # (per-piece transfers inside the compiled step are scheduled
            # and overlapped by XLA, unlike the eager per-leaf dispatches
            # that motivated the old single-vector design).
            leaves, treedef = jax.tree.flatten(master)
            if not all(jnp.issubdtype(l.dtype, jnp.floating)
                       for l in leaves):
                raise ValueError(
                    "cpu_offload (xla tier) requires an all-float parameter "
                    "tree; non-float leaves cannot be Adam-updated")
            self._flat_treedef = treedef
            self._flat_shapes = [tuple(l.shape) for l in leaves]
            self._flat_sizes = [int(np.prod(s)) if s else 1
                                for s in self._flat_shapes]
            dp = self.dp_world_size
            piece_dev = NamedSharding(self.mesh, P(DATA_AXIS, None))
            # Off-TPU (CPU test meshes) host and device memory are the same
            # space and XLA rejects sharded pinned_host placements — the
            # tier still runs, just without a distinct host memory kind.
            platform = next(iter(self.mesh.devices.flat)).platform
            # DS_OFFLOAD_PINNED_HOST=0 keeps master/moments in device
            # memory (diagnosis knob: discriminates a pinned_host/
            # compute_on platform stall from the program itself — only
            # feasible where HBM fits the fp32 state, e.g. 124M probes).
            self._offload_real_host = (
                platform == "tpu"
                and os.environ.get("DS_OFFLOAD_PINNED_HOST", "1") == "1")
            piece_host = (piece_dev.with_memory_kind("pinned_host")
                          if self._offload_real_host else piece_dev)
            self._piece_dev_sharding = piece_dev
            self._piece_host_sharding = piece_host
            cspecs = self.zero_plan.compute_param_specs(master)
            self._compute_shardings = jax.tree.map(
                lambda s: NamedSharding(self.mesh, s), cspecs,
                is_leaf=lambda x: isinstance(x, P))
            # Partition-major piece layout: each piece is (dp, w_i) with
            # row r holding rank r's data-shard of that leaf (the leaf's
            # data-sharded dim moved to the front).  This makes every
            # reshape between a piece and the leaf's ZeRO sharding
            # *sharding-natural*, so the SPMD partitioner emits zero
            # collectives for the data-sharded legs — the naive offset-
            # major layout forced an involuntary full rematerialization
            # (replicate + re-partition) of every ZeRO-3 param on the
            # cast-up path and of every reduce-scattered grad on the
            # flatten path.  Layout dims come from grad_specs: identical
            # to the stage-3 compute specs and additionally correct for
            # stage-2's reduce-scattered grads (compute params are
            # replicated there, so unflatten is local either way after
            # the stage<3 all-gather).
            gspec_leaves = jax.tree.leaves(
                self.zero_plan.grad_specs(master),
                is_leaf=lambda x: isinstance(x, P))
            self._flat_layout = [
                _flat_leaf_layout(shape, size, spec, dp)
                for shape, size, spec in zip(
                    self._flat_shapes, self._flat_sizes, gspec_leaves)]
            self._flat_w = sum(rec.w for rec in self._flat_layout)
            self._flat_pad = sum(rec.pad for rec in self._flat_layout)
            self._flat_n = dp * self._flat_w
            # ZeRO-Infinity-style param streaming: leaves the model marks
            # keep their compute copies in HOST memory; the model fetches
            # one layer per scan tick (streaming_param_spec contract).
            self._stream_mask = [False] * len(self._flat_sizes)
            if config.zero_config.param_streaming:
                if dp > 1 and config.zero_optimization_stage < 3:
                    raise ValueError(
                        "param_streaming with dp > 1 requires ZeRO-3 "
                        "(stage <= 2 would need host-side all-gathers of "
                        "the streamed leaves; stage 3 keeps them data-"
                        "sharded end to end)")
                spec = self.module.streaming_param_spec(
                    jax.tree.unflatten(treedef, leaves))
                if spec is None:
                    raise ValueError(
                        "param_streaming is enabled but the model's "
                        "streaming_param_spec returned None — the model "
                        "must mark its stacked scan leaves (for GPT2Model "
                        "set scan_layers=True and stream_scan=True)")
                mask_leaves = jax.tree.leaves(spec)
                if len(mask_leaves) != len(leaves):
                    raise ValueError(
                        "streaming_param_spec structure does not match "
                        f"the parameter tree ({len(mask_leaves)} vs "
                        f"{len(leaves)} leaves)")
                self._stream_mask = [bool(b) for b in mask_leaves]
                if not any(self._stream_mask):
                    raise ValueError(
                        "param_streaming is enabled but the model marked "
                        "no leaves as streamable")
            # Leaf-at-a-time staging: pack ONE leaf to its fp32 (dp, w)
            # piece on device, move it to host memory, drop the leaf.
            # Device peak = remaining init leaves + one piece, a strictly
            # decreasing footprint; the old whole-tree flatten held tree
            # AND flat vector simultaneously (2× fp32 state) and required
            # a host-side concatenate.
            master = None  # the tree would otherwise pin every leaf alive
            # ONE jitted pack function: _FlatLeaf is hashable, so repeated
            # leaf shapes (a transformer's dozens of same-shaped layers)
            # hit the jit cache instead of compiling per leaf.  The jit
            # outputs DIRECTLY into pinned_host (out_shardings): an eager
            # device_put between memory kinds goes through the client RPC
            # path on tunneled deployments — measured ~35 MB/s, 9 minutes
            # of construction for 1.5B fp32 state (round-5 window) —
            # while a program output lands in host memory at PCIe rate.
            pack_piece = jax.jit(
                lambda l, rec, dp: _pack_leaf(
                    l.astype(jnp.float32), rec, dp, jnp),
                static_argnums=(1, 2), out_shardings=piece_host)
            pieces = []
            for i, rec in enumerate(self._flat_layout):
                leaf, leaves[i] = leaves[i], None  # drop the last reference
                pieces.append(pack_piece(leaf, rec, dp))
                del leaf
            master = tuple(pieces)

            opt_state = FusedAdamState(
                count=jax.device_put(jnp.zeros([], jnp.int32),
                                     NamedSharding(self.mesh, P())),
                mu=self._zero_host_pieces(),
                nu=self._zero_host_pieces())
        elif self._offload:
            # ZeRO-Offload host tier: fp32 master + moments live in host
            # numpy and are updated by the native C++ CPU Adam
            # (runtime/offload.py); the device keeps only compute-dtype
            # params.  Single-process: one host owns the full master.
            # Multi-process: each host owns ONLY its dp-shard (the
            # reference's per-DP-rank fp32 partitions, stage2.py:743-900)
            # — see ShardedHostOffloadOptimizer.
            if int(getattr(config.zero_config,
                           "offload_grad_chunks", 1) or 1) > 1:
                # config-level sanity rejects impl='host' explicitly, but
                # 'auto' resolves per-platform — never ignore the knob
                raise ValueError(
                    "offload_grad_chunks > 1 is an xla-tier capacity "
                    "mode; offload_impl resolved to 'host' on this "
                    "platform. Set offload_impl='xla' explicitly.")
            if config.zero_config.param_streaming:
                raise ValueError(
                    "param_streaming is an xla-tier capacity mode; "
                    "offload_impl resolved to 'host' on this platform. "
                    "Set offload_impl='xla' explicitly.")
            if (getattr(config.zero_config, "offload_split_update", False)
                    or os.environ.get("DS_OFFLOAD_SPLIT_UPDATE") == "1"):
                # the env knob must fail as loudly as the config flag — a
                # hardware experiment silently measuring the host tier is
                # exactly the fallback confusion this raise prevents
                raise ValueError(
                    "offload_split_update is an xla-tier mode; "
                    "offload_impl resolved to 'host' on this platform. "
                    "Set offload_impl='xla' explicitly.")
            if config.zero_optimization_stage >= 3:
                raise ValueError(
                    "ZeRO-3 × cpu_offload requires offload_impl='xla' "
                    "(data-sharded compute params); the host tier places "
                    "replicated compute params and would silently lose "
                    "stage 3's memory savings.")
            from .offload import (HostOffloadOptimizer,
                                  ShardedHostOffloadOptimizer)
            oparams = dict(config.optimizer_params)
            lr = self._lr_schedule or float(oparams.get("lr", 1e-3))
            opt_kwargs = dict(
                lr=lr,
                betas=tuple(oparams.get("betas", (0.9, 0.999))),
                eps=oparams.get("eps", 1e-8),
                weight_decay=oparams.get("weight_decay", 0.0),
                adamw_mode=oparams.get("adam_w_mode", True),
                bias_correction=oparams.get("bias_correction", True),
                compute_dtype=self.compute_dtype)
            specs = base_specs if base_specs is not None else jax.tree.map(
                lambda _: P(), master)
            self._compute_shardings = jax.tree.map(
                lambda s: NamedSharding(self.mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P))
            # flat-order view + treedef of the compute shardings — the
            # streaming pipeline uploads leaf-by-leaf against these
            self._compute_shard_leaves = jax.tree.leaves(
                self._compute_shardings,
                is_leaf=lambda x: isinstance(x, NamedSharding))
            self._compute_treedef = jax.tree.structure(
                self._compute_shardings,
                is_leaf=lambda x: isinstance(x, NamedSharding))
            self._offload_sharded = jax.process_count() > 1
            self._offload_disk = config.offload_config.tier == "disk"
            if self._offload_disk and self._offload_sharded:
                raise ValueError(
                    "offload.tier='disk' is single-controller: the disk "
                    "tier streams per-leaf state files owned by ONE "
                    "process (multi-host disk sharding is a future "
                    "extension); use tier='host' under multi-process "
                    "runs")
            if self._offload_sharded:
                # multi-host: dp-shard the fp32 master on device, let each
                # process pull only ITS shards to host; compute params
                # come back via one jitted all-gather over ICI
                master_shardings = self.zero_plan.master_shardings(master)
                master_dev = _device_put_tree(master, master_shardings)
                self._host_opt = ShardedHostOffloadOptimizer(
                    master_dev, **opt_kwargs)
                del master_dev  # host blocks pulled; free the device fp32
                self._sharded_gather = jax.jit(
                    lambda t: t, out_shardings=self._compute_shardings)
                self._reshard_to_master = jax.jit(
                    lambda t: t, out_shardings=master_shardings)
                self._compute_params = self._sharded_gather(
                    self._host_opt.compute_params())
            elif self._offload_disk:
                # ZeRO-Infinity bottom tier (runtime/disk_offload.py):
                # master + moments live in per-leaf CRC'd files under
                # offload.disk_dir; host RAM holds only the io_depth-
                # bounded pipeline window.  API-compatible with the
                # host tier — everything below (streaming uploads, DPU,
                # checkpoints) works unchanged.
                from .disk_offload import DiskOffloadOptimizer
                off_cfg = config.offload_config
                self._host_opt = DiskOffloadOptimizer(
                    master, disk_dir=off_cfg.disk_dir,
                    io_depth=off_cfg.io_depth, fsync=off_cfg.fsync,
                    **opt_kwargs)
                self._compute_params = _device_put_tree(
                    self._host_opt.compute_params(),
                    self._compute_shardings)
            else:
                self._host_opt = HostOffloadOptimizer(master, **opt_kwargs)
                self._compute_params = _device_put_tree(
                    self._host_opt.compute_params(),
                    self._compute_shardings)
            self._dpu = bool(config.zero_config.delayed_param_update)
            self._dpu_pending = None
            # streaming offload update pipeline (tentpole, docs/
            # observability.md): while the C++ Adam updates leaf i, leaf
            # i+1's grad D2H is in flight AND leaf i-1's updated compute
            # copy is already uploading H2D.  DS_OFFLOAD_PIPELINE=0 is
            # the escape hatch back to the serial post-step upload.
            self._offload_pipeline = (
                bool(getattr(config.zero_config, "offload_pipeline", True))
                and os.environ.get("DS_OFFLOAD_PIPELINE", "1") != "0")
            self.last_offload_breakdown = None
            master = self._host_opt.master       # host numpy identity
            opt_state = self._host_opt.state_tree()
        elif self._onebit_path and self.dp_world_size > 1:
            master_shardings = self.zero_plan.master_shardings(master)
            master = _device_put_tree(master, master_shardings)
            opt_state = self._init_onebit_opt_state(master, master_shardings)
        else:
            master_shardings = self.zero_plan.master_shardings(master)
            master = _device_put_tree(master, master_shardings)
            opt_state = self.optimizer.init(master)
            opt_shardings = self.zero_plan.opt_state_shardings(
                opt_state, master)
            opt_state = _device_put_tree(opt_state, opt_shardings)

        self.state = TrainState(
            master_params=master,
            opt_state=opt_state,
            scaler=jax.tree.map(self._place_scalar, scaler),
            global_steps=self._place_scalar(jnp.asarray(0, jnp.int32)),
            skipped_steps=self._place_scalar(jnp.asarray(0, jnp.int32)),
            rng=self._place_scalar(jax.random.PRNGKey(seed + 1)),
        )

        # ---- compiled steps ----
        self._onebit_steps = None
        if self._offload_host:
            self._grad_step = self._build_offload_grad_step()
            self._offload_eval_step = self._build_offload_eval_step()
        elif self._offload:
            if config.offload_config.tier == "disk":
                # config sanity rejects an explicit impl='xla'; 'auto'
                # resolves per-platform and must not silently measure
                # the xla tier (the DS_OFFLOAD_SPLIT_UPDATE raise rule)
                raise ValueError(
                    "offload.tier='disk' is a host-impl structure "
                    "(per-leaf C++ Adam over disk-resident state); "
                    "offload_impl resolved to 'xla' on this platform. "
                    "Set offload_impl='host' explicitly.")
            if (getattr(config.zero_config, "offload_pipeline_explicit",
                        False) and config.zero_config.offload_pipeline):
                # explicit opt-in must not be silently ignored (the
                # DS_OFFLOAD_SPLIT_UPDATE warn-not-raise precedent):
                # the pipeline is a host-tier structure; the xla tier's
                # update is already scheduled end-to-end by XLA
                logger.warning(
                    "offload_pipeline is a host-tier knob; offload_impl "
                    "resolved to 'xla' on this platform, where the "
                    "update/upload overlap is XLA-scheduled — the flag "
                    "is ignored.")
            chunks = int(getattr(config.zero_config,
                                 "offload_grad_chunks", 1) or 1)
            chunks = min(chunks, len(self._flat_sizes))
            dpu_xla = bool(config.zero_config.delayed_param_update)
            # env override for hardware experiments: flip the update
            # structure without editing the config file
            split_update = (
                bool(getattr(config.zero_config,
                             "offload_split_update", False))
                or os.environ.get("DS_OFFLOAD_SPLIT_UPDATE") == "1")
            self._xla_dpu_pending = None
            self._xla_dpu_update = None
            self._xla_dpu_dispatch = 0
            if chunks > 1 or dpu_xla or split_update:
                self._train_step = self._build_chunked_offload_steps(
                    self._grad_group_indices(max(chunks, 1)),
                    delayed=dpu_xla, split_update=split_update)
            else:
                self._train_step = self._build_xla_offload_step()
            self._eval_step = self._build_xla_offload_eval_step()
        elif self._onebit_path and self.dp_world_size > 1:
            # two compiled programs selected host-side at the freeze
            # boundary: no collectives inside lax.cond (fragile in TPU SPMD
            # lowering), and the frozen program's only grad-sized
            # collective is the uint8 exchange — assertable from its HLO
            freeze = int(self.config.optimizer_params.get(
                "freeze_step", 100000))
            self._onebit_steps = (
                self._build_onebit_step("warm"),
                self._build_onebit_step("frozen"),
                freeze)
            self._eval_step = self._build_eval_step()
        elif self._use_sparse_grads():
            self._train_step = self._build_sparse_grad_step()
            self._eval_step = self._build_eval_step()
        else:
            self._train_step = self._build_train_step()
            self._eval_step = self._build_eval_step()

        # ---- python-side bookkeeping (untraced) ----
        self.global_steps = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        # partitioning-correctness sweep on the first step when enabled
        # (reference stage2.py:23-25 pg_correctness_test)
        self._train_mode = True
        self._pg_check_pending = bool(
            getattr(config.zero_config, "pg_correctness_test", False))
        if self._pg_check_pending and self._offload:
            logger.warning(
                "pg_correctness_test is not supported with cpu_offload "
                "(the offload tiers have their own differential tests); "
                "the requested check will NOT run")
            self._pg_check_pending = False
        self._pending_micros = []
        self._tb_pending = []
        self._last_metrics: Optional[StepMetrics] = None
        self._step_times = collections.deque(
            maxlen=max(min(config.steps_per_print, 1000), 10))

        self.training_dataloader = (
            self.deepspeed_io(training_data, collate_fn=collate_fn)
            if training_data is not None else None)
        # async input pipeline (docs/observability.md): _training_iter
        # wraps its loader in a DevicePrefetcher so collate + batch
        # sharding run off the step loop's thread.  DS_PREFETCH=0 is the
        # no-config escape hatch back to inline placement.
        pfc = config.data_prefetch_config
        self._prefetch_enabled = (bool(pfc.enabled)
                                  and os.environ.get("DS_PREFETCH", "1")
                                  != "0")
        self._prefetch_depth = int(pfc.depth)
        self._train_prefetcher: Optional[DevicePrefetcher] = None
        self._prefetch_prev_stats = None
        # every prefetcher this engine builds (train AND eval): close()
        # must drain them all — an abandoned worker would park forever
        # holding `depth` device-resident batches.  The finalizer covers
        # engines dropped without close(); it holds only the LIST (the
        # prefetchers hold the engine weakly — see prefetch()), so the
        # engine itself stays collectable.
        self._prefetchers: list = []
        weakref.finalize(self, _close_prefetchers, self._prefetchers)

        # ---- aux subsystems driven by config ----
        # progressive layer drop (reference engine.py:189-190,787-788)
        self.progressive_layer_drop = None
        if config.pld_config.enabled:
            from .progressive_layer_drop import ProgressiveLayerDrop
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=config.pld_config.theta,
                gamma=config.pld_config.gamma)
        # tensorboard scalars from rank 0 (reference engine.py:253-285)
        self.summary_writer = None
        if config.tensorboard_config.enabled and jax.process_index() == 0:
            from ..utils.monitor import SummaryWriter
            self.summary_writer = SummaryWriter(
                output_path=config.tensorboard_config.output_path,
                job_name=config.tensorboard_config.job_name)
            # scalars are buffered until the steps_per_print sync; make the
            # writer's own flush()/close() drain the buffer first so either
            # shutdown path sees every step.  The wrappers hold the engine
            # via weakref: the GC finalizer below keeps the WRITER alive
            # until the engine dies, and a strong capture here would turn
            # that into engine-keeps-itself-alive.
            _orig_flush = self.summary_writer.flush
            _orig_close = self.summary_writer.close
            eng_ref = weakref.ref(self)

            def _flush_all():
                eng = eng_ref()
                if eng is not None:
                    eng._flush_tensorboard()
                _orig_flush()

            def _close_all():
                eng = eng_ref()
                if eng is not None:
                    eng._flush_tensorboard()
                _orig_close()
            self.summary_writer.flush = _flush_all
            self.summary_writer.close = _close_all
        # unified telemetry hub (docs/observability.md): metrics registry,
        # span tracing, compile tracking, memory gauges — all riding the
        # engine's EXISTING sync points (per-step recording is host-only)
        self.telemetry = None
        if config.telemetry_config.enabled and jax.process_index() == 0:
            from ..telemetry import TelemetryHub
            tcfg = config.telemetry_config
            self.telemetry = TelemetryHub(
                tcfg.output_path or os.path.join(os.getcwd(), "telemetry"),
                trace=bool(tcfg.trace),
                compile_events=bool(tcfg.compile_events),
                memory=bool(tcfg.memory),
                storm_threshold=tcfg.recompile_storm_threshold,
                summary_writer=self.summary_writer,
                process_index=jax.process_index())
            # per-program retrace counters (track_program skips drivers
            # without a jit cache, e.g. the chunked offload python loops)
            for name, fn in (
                    ("train_step", getattr(self, "_train_step", None)),
                    ("eval_step", getattr(self, "_eval_step", None)),
                    ("grad_step", getattr(self, "_grad_step", None)),
                    ("offload_eval_step",
                     getattr(self, "_offload_eval_step", None))):
                if fn is not None:
                    self.telemetry.track_program(name, fn)
            if self._onebit_steps is not None:
                self.telemetry.track_program(
                    "onebit_warm", self._onebit_steps[0])
                self.telemetry.track_program(
                    "onebit_frozen", self._onebit_steps[1])
            if self.telemetry.tracer is not None:
                # offload D2H pulls emit transfer spans (module-level
                # hook: the last telemetry-enabled engine wins)
                from .offload import set_transfer_tracer
                set_transfer_tracer(self.telemetry.tracer)
        # elastic-training liveness (docs/elastic.md): EVERY process
        # beats a per-host heartbeat file each step when the supervisor
        # exported DS_HEARTBEAT_DIR (or telemetry.heartbeat is on); the
        # proc-0 straggler monitor reads the fleet's files at the
        # periodic telemetry sync.  Not gated on the telemetry hub — the
        # supervisor needs liveness even with telemetry off.
        self._heartbeat = None
        self._straggler_monitor = None
        tcfg = config.telemetry_config
        hb_dir = os.environ.get("DS_HEARTBEAT_DIR", "")
        if not hb_dir and tcfg.heartbeat:
            hb_dir = tcfg.heartbeat_dir or os.path.join(
                tcfg.output_path or os.path.join(os.getcwd(), "telemetry"),
                "heartbeats")
        if hb_dir:
            from ..telemetry.heartbeat import (HeartbeatWriter,
                                               StragglerMonitor)
            self._heartbeat = HeartbeatWriter(
                hb_dir, process_index=jax.process_index())
            if jax.process_index() == 0:
                self._straggler_monitor = StragglerMonitor(
                    ratio=float(tcfg.straggler_ratio))
        # one-shot anomaly trigger (docs/observability.md): opt-in via
        # telemetry.anomaly_ratio — a slow interval (vs the trailing
        # median) or a self-straggler flag fires ONE bounded profiler
        # capture + a flight-record dump while the episode is live
        self._anomaly_ratio = float(tcfg.anomaly_ratio)
        self._anomaly_trail = collections.deque(maxlen=32)
        self._anomaly_fired = False
        self._anomaly_profiling = False
        # flight recorder (docs/observability.md): one post-mortem dump
        # per failure class so a repeated-crash loop can't spam dumps
        self._flightrec_poison_dumped = False
        # one fault plane (docs/stages.md): stage records + drain graph
        wire_stage_plane(self)
        if getattr(self, "_offload_disk", False):
            # adopt the wired disk stage records (telemetry counters,
            # flight-recorder dump, budgets that persist across steps)
            # in place of the optimizer's construction-time private ones
            self._host_opt.bind_stages(self._stage_records["disk_read"],
                                       self._stage_records["disk_write"])
        # fault-tolerant checkpointing (docs/checkpointing.md): the async
        # daemon writer (lazy thread; created eagerly so the GC finalizer
        # below can drain a dropped engine's in-flight save), exposed-
        # stall accounting for the telemetry sync, and the opt-in SIGTERM
        # preemption hook
        from .resilience import AsyncCheckpointWriter
        self._ckpt_writer = AsyncCheckpointWriter(
            stage=self._stage_records["ckpt_writer"])
        self._ckpt_last_save_dir = None
        self._ckpt_interval_acc = {"save_s": 0.0, "overlap_s": 0.0,
                                   "saves": 0, "writes": 0}
        # guards the acc against the writer thread's overlap_s updates
        # racing the telemetry sync's read-and-reset
        self._ckpt_acc_lock = threading.Lock()
        self.last_ckpt_error = None
        self._in_step = False          # SIGTERM-save deferral fence
        self._deferred_preempt = None  # handler parked until step boundary
        self._preemption_handler = None
        ckc = config.checkpoint_config
        if ckc.sigterm_save:
            if jax.process_count() > 1:
                logger.warning(
                    "checkpoint.sigterm_save is single-controller only "
                    "(a pod-wide preemption save needs coordinated "
                    "barriers); NOT installing the SIGTERM hook")
            else:
                from .resilience import install_preemption_handler
                self._preemption_handler = install_preemption_handler(
                    self, ckc.save_dir or None)
        # GC/exit finalizer: buffered scalars and the trace file survive a
        # dropped engine even when close() is never called explicitly.
        # Holds only the output objects (not the engine — see the weakref
        # wrappers above), so the engine itself stays collectable.  The
        # checkpoint writer is closed FIRST so an in-flight async save
        # lands before the telemetry exporters flush.
        self._finalizer = None
        _closeables = (self._ckpt_writer,) + tuple(
            c for c in (self.summary_writer, self.telemetry)
            if c is not None)
        if _closeables:
            # the finalizer gets the buffer LIST (drained in place), the
            # raw writer, and the tracer so a dropped engine still
            # flushes its scalars and releases the process-wide hook
            self._finalizer = weakref.finalize(
                self, _close_quietly, _closeables,
                tb_pending=self._tb_pending,
                writer=self.summary_writer,
                tracer=(self.telemetry.tracer
                        if self.telemetry is not None else None))
        # xplane trace window (jax.profiler) — the TPU-native tracer slot
        # the reference leaves empty (SURVEY §5.1)
        self._profiler = None
        self._profiler_active = False
        if config.profiler_config.enabled and jax.process_index() == 0:
            self._profiler = config.profiler_config
        # per-phase timers; enabling them syncs the device every step
        # (reference wall_clock_breakdown likewise cuda-synchronizes,
        # engine.py:790-800) — the async dispatch overlap is traded for
        # measurement
        self.timers = None
        if config.wall_clock_breakdown:
            from ..utils.timer import SynchronizedWallClockTimer
            self.timers = SynchronizedWallClockTimer()

        log_dist(
            f"DeepSpeedEngine: dp={self.dp_world_size} "
            f"zero_stage={config.zero_optimization_stage} "
            f"dtype={self.compute_dtype.__name__} "
            f"micro_bs={self.micro_batch_size} "
            f"grad_acc={self.gradient_accumulation_steps}", ranks=[0])

    # ------------------------------------------------------------------
    # configuration
    # ------------------------------------------------------------------
    def _resolve_lr_schedule(self, client_schedule):
        if client_schedule is not None:
            if not callable(client_schedule):
                raise TypeError(
                    "lr_scheduler must be a callable step -> lr (got "
                    f"{type(client_schedule)}); reference-style scheduler "
                    "objects are not supported — use the config 'scheduler' "
                    "block or a callable")
            return client_schedule
        cfg = self.config
        if cfg.scheduler_name is not None:
            return get_lr_schedule(cfg.scheduler_name, cfg.scheduler_params)
        return None

    def _build_basic_optimizer(self) -> optax.GradientTransformation:
        cfg = self.config
        name = cfg.optimizer_name or C.ADAM_OPTIMIZER
        params = dict(cfg.optimizer_params)
        lr = params.pop("lr", 1e-3)
        if self._lr_schedule is not None:
            lr = self._lr_schedule
        betas = tuple(params.pop("betas", (0.9, 0.999)))
        eps = params.pop("eps", 1e-8)
        wd = params.pop("weight_decay", 0.0)
        if name == C.ADAM_OPTIMIZER:
            adam_w = params.pop("adam_w_mode", True)
            bias_corr = params.pop("bias_correction", True)
            return fused_adam(lr, betas, eps, wd, adam_w_mode=adam_w,
                              bias_correction=bias_corr)
        if name == C.LAMB_OPTIMIZER:
            max_coeff = params.pop("max_coeff", 10.0)
            min_coeff = params.pop("min_coeff", 0.01)
            return fused_lamb(lr, betas, eps, wd,
                              max_coeff=max_coeff, min_coeff=min_coeff)
        if name == C.ONEBIT_ADAM_OPTIMIZER:
            from ..compress.onebit import onebit_adam
            freeze_step = params.pop("freeze_step", 100000)
            return onebit_adam(lr, betas, eps, wd, freeze_step=freeze_step,
                               data_axis=DATA_AXIS)
        raise ValueError(f"Unknown optimizer {name!r}")

    # ------------------------------------------------------------------
    # compiled step construction
    # ------------------------------------------------------------------
    @property
    def _scan_grad_acc(self) -> int:
        """Micro-batches handled by the engine's outer accumulation scan.
        The pipeline engine overrides this to 1: there, all micro-batches
        live inside the pipelined program itself."""
        return self.gradient_accumulation_steps

    def _scan_scaled_grads(self, params, batch, scaler, step_rng,
                           cast: bool = True, constrain: bool = True,
                           keep_param_dtype: bool = False,
                           loss_fn=None, constrain_fn=None):
        """Shared grad-accumulation core of every step builder: scan the
        micro-batches, sum fp32 grads, unscale by loss_scale*grad_acc.
        Returns (grads, scaled_losses).  ``cast=False`` when ``params`` are
        already in compute dtype (offload tier casts on the host);
        ``constrain=False`` on the 1-bit path (grads stay LOCAL there).

        ``keep_param_dtype`` (offload tier only): at grad_acc == 1 there
        is nothing to accumulate, so skip the scan and return grads in
        the params' dtype — the fp32 loop carry would otherwise pin a 4N
        buffer live through the whole backward, which is what bounds
        trainable-params/chip in the capacity bench.  Numerically
        identical to scan-then-cast: the unscale still happens in fp32
        (elementwise, fused by XLA — never materialized), and the offload
        step ships compute-dtype pieces either way."""
        plan = self.zero_plan
        compute_dtype = self.compute_dtype
        grad_acc = self._scan_grad_acc
        if loss_fn is None:
            loss_fn = self.module.loss_fn
        if constrain_fn is not None:
            con = constrain_fn  # caller-supplied (subset trees)
        elif constrain:
            con = lambda g: constrain_grads(g, plan)  # noqa: E731
        else:
            con = lambda g: g  # noqa: E731

        def micro_loss(p, mb, rng):
            pp = precision.cast_to_compute(p, compute_dtype) if cast else p
            loss = loss_fn(pp, mb, rng, train=True)
            return precision.scale_loss(loss.astype(jnp.float32), scaler)

        grad_fn = jax.value_and_grad(micro_loss)

        if keep_param_dtype and grad_acc == 1:
            mb = jax.tree.map(lambda x: x[0], batch)
            scaled_loss, g = grad_fn(params, mb,
                                     jax.random.fold_in(step_rng, 0))
            inv = (1.0 / scaler.loss_scale).astype(jnp.float32)
            grads = con(jax.tree.map(
                lambda x: (x.astype(jnp.float32) * inv).astype(x.dtype),
                g))
            return grads, scaled_loss[None]

        def acc_body(carry, mb):
            gsum, i = carry
            rng = jax.random.fold_in(step_rng, i)
            scaled_loss, g = grad_fn(params, mb, rng)
            gsum = jax.tree.map(
                lambda a, b: a + b.astype(jnp.float32), gsum, con(g))
            return (gsum, i + 1), scaled_loss

        gsum0 = con(jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params))
        (gsum, _), scaled_losses = jax.lax.scan(
            acc_body, (gsum0, jnp.asarray(0, jnp.int32)), batch)
        inv = (1.0 / (scaler.loss_scale * grad_acc)).astype(jnp.float32)
        return con(jax.tree.map(lambda g: g * inv, gsum)), scaled_losses

    # ------------------------------------------------------------------
    # partitioning correctness sweep (the reference's pg_correctness_test,
    # stage2.py:23-25,1008-1022,1054-1055: clone-based unpartitioned
    # reduction diffed against the partitioned gradients)
    # ------------------------------------------------------------------
    def verify_gradient_partitioning(self, batch=None, data_iter=None,
                                     rtol: float = 2e-5, atol: float = 2e-5):
        """Compute one global batch's gradients twice — through the
        engine's ZeRO sharding plan (reduce-scatter placements) and with no
        plan constraints (plain replicated reduction) — and assert they
        match.  Same math, same dtype; only the GSPMD partitioning differs,
        so any disagreement beyond summation-order noise is a sharding bug.
        Returns ``{"max_abs_diff", "max_rel_diff"}`` on success."""
        if self._offload:
            raise NotImplementedError(
                "pg correctness check covers the on-device ZeRO tiers; the "
                "offload tiers have their own differential test "
                "(tests/test_cpu_adam.py, tests/test_offload_xla.py)")
        if batch is None:
            if data_iter is None:
                # like eval_batch: never silently consume (and skew) the
                # training data stream from a diagnostic call
                raise ValueError(
                    "verify_gradient_partitioning needs a batch or "
                    "data_iter")
            batch = next(data_iter)
        return self._run_pg_correctness(self._shard_batch(batch),
                                        rtol=rtol, atol=atol)

    def _run_pg_correctness(self, sharded, rtol=2e-5, atol=2e-5):
        state = self.state

        def grads_of(constrain):
            def f(master, batch_in, scaler, rng):
                g, _ = self._scan_scaled_grads(
                    master, batch_in, scaler, rng, constrain=constrain)
                return g
            return jax.jit(f, static_argnums=())

        rng = jax.random.fold_in(state.rng, state.global_steps)
        g_plan = jax.device_get(grads_of(True)(
            state.master_params, sharded, state.scaler, rng))
        g_ref = jax.device_get(grads_of(False)(
            state.master_params, sharded, state.scaler, rng))

        max_abs = 0.0
        max_rel = 0.0
        bad = []
        plan_with_paths = jax.tree_util.tree_flatten_with_path(g_plan)[0]
        flat_ref = jax.tree.leaves(g_ref)
        for (path_keys, a), b in zip(plan_with_paths, flat_ref):
            path = jax.tree_util.keystr(path_keys)
            a = np.asarray(a, np.float64)
            b = np.asarray(b, np.float64)
            diff = np.abs(a - b)
            denom = np.maximum(np.abs(b), 1e-12)
            max_abs = max(max_abs, float(diff.max(initial=0.0)))
            max_rel = max(max_rel, float((diff / denom).max(initial=0.0)))
            if not np.allclose(a, b, rtol=rtol, atol=atol):
                bad.append(path)
        if bad:
            raise AssertionError(
                f"pg_correctness_test FAILED: partitioned grads diverge "
                f"from the replicated reduction on {len(bad)} leaves "
                f"(max_abs={max_abs:.3e} max_rel={max_rel:.3e}): "
                f"{bad[:5]}")
        log_dist(f"pg_correctness_test OK: max_abs={max_abs:.3e} "
                 f"max_rel={max_rel:.3e}", ranks=[0])
        return {"max_abs_diff": max_abs, "max_rel_diff": max_rel}

    def _tel_span(self, name: str, cat: str = "runtime", **args):
        """Telemetry span context — a nullcontext when telemetry is off,
        so call sites stay unconditional.  Host-side stamps only; never
        a device sync."""
        tel = getattr(self, "telemetry", None)
        if tel is None:
            return contextlib.nullcontext()
        return tel.span(name, cat=cat, **args)

    def _profiler_window_tick(self):
        """Open/close the xplane capture window around train_batch calls:
        steps ``[start_step, start_step + num_steps)`` are traced."""
        p = self._profiler
        if p is None:
            return
        if (not self._profiler_active
                and p.start_step <= self.global_steps
                < p.start_step + p.num_steps):
            # upper bound matters: a run resumed from a checkpoint past the
            # window must not open a stray one-step trace
            self._anomaly_stop()  # defensive: one capture at a time
            jax.profiler.start_trace(p.output_path)
            self._profiler_active = True
        elif (self._profiler_active
              and self.global_steps >= p.start_step + p.num_steps):
            self.stop_profiler()

    def stop_profiler(self):
        """Finalize the xplane trace (idempotent; also the escape hatch if
        training ends inside the capture window)."""
        if not self._profiler_active:
            return
        with self._tel_span("profiler/stop_trace", cat="profiler",
                            step=self.global_steps):
            # device sync: the window must contain the work — one of the
            # engine's existing sync points telemetry rides
            _ = self.last_metrics
            jax.profiler.stop_trace()
        self._profiler_active = False
        path = self._profiler.output_path
        self._profiler = None
        log_dist(f"profiler: xplane trace written to {path}", ranks=[0])

    def _lr_at_fn(self):
        lr_schedule = self._lr_schedule
        cfg_lr = float(self.config.optimizer_params.get("lr", 1e-3))

        def lr_at(count):
            if lr_schedule is not None:
                return jnp.asarray(lr_schedule(count), jnp.float32)
            return jnp.asarray(cfg_lr, jnp.float32)
        return lr_at

    @staticmethod
    def _packed_metrics(mean_loss, grad_norm, scaler, finite, lr):
        """Metrics leave the device as ONE packed f32 vector: each
        np.asarray is a full host round-trip, so five separate fields would
        cost 5× the latency.  Order must match ``last_metrics``."""
        return jnp.stack([
            mean_loss.astype(jnp.float32),
            grad_norm.astype(jnp.float32),
            scaler.loss_scale.astype(jnp.float32),
            (~finite).astype(jnp.float32),
            lr,
        ])

    def _epilogue_scalars(self, scaler, global_steps, skipped_steps,
                          finite, mean_loss, grad_norm, lr_at,
                          scale_config):
        """Scalar core of the step tail — ONE definition of loss-scale
        update, skip/step counters, and the packed metrics contract, used
        by _step_epilogue (fused paths) AND the split-update tail program
        so they cannot drift."""
        new_scaler = precision.update_scale(scaler, finite, scale_config)
        new_skipped = skipped_steps + (1 - finite.astype(jnp.int32))
        new_global = global_steps + 1
        # lr is reported at the *applied*-step count so it matches what
        # the optimizer's schedule actually used (skipped steps don't
        # advance the schedule)
        applied = new_global - new_skipped
        packed = self._packed_metrics(mean_loss, grad_norm, scaler,
                                      finite, lr_at(applied))
        return new_scaler, new_global, new_skipped, packed

    def _step_epilogue(self, state, new_master, new_opt, finite,
                       mean_loss, grad_norm, lr_at, scale_config):
        """Shared step tail: loss-scale update, skip/step counters, the
        next TrainState, and the packed metrics vector.  One copy so skip
        semantics and the metrics contract can't drift across the step
        builders."""
        new_scaler, new_global, new_skipped, packed = \
            self._epilogue_scalars(state.scaler, state.global_steps,
                                   state.skipped_steps, finite, mean_loss,
                                   grad_norm, lr_at, scale_config)
        new_state = TrainState(
            master_params=new_master,
            opt_state=new_opt,
            scaler=new_scaler,
            global_steps=new_global,
            skipped_steps=new_skipped,
            rng=state.rng,
        )
        return new_state, packed

    def _build_train_step(self):
        optimizer = self.optimizer
        clip = self.gradient_clipping
        scale_config = self.loss_scale_config
        lr_at = self._lr_at_fn()

        def train_step(state: TrainState, batch):
            """batch leaves: [grad_acc, micro_global, ...]"""
            scaler = state.scaler
            step_rng = jax.random.fold_in(state.rng, state.global_steps)
            grads, scaled_losses = self._scan_scaled_grads(
                state.master_params, batch, scaler, step_rng)

            finite = precision.grads_finite(grads)
            grad_norm = global_norm(grads)
            if clip > 0:
                grads, _ = clip_by_global_norm(grads, clip, norm=grad_norm)

            def do_update(operand):
                master, opt_state = operand
                updates, new_opt = optimizer.update(grads, opt_state, master)
                new_master = optax.apply_updates(master, updates)
                return new_master, new_opt

            def skip_update(operand):
                return operand

            new_master, new_opt = jax.lax.cond(
                finite, do_update, skip_update,
                (state.master_params, state.opt_state))

            mean_loss = (jnp.mean(scaled_losses) / scaler.loss_scale)
            return self._step_epilogue(state, new_master, new_opt, finite,
                                       mean_loss, grad_norm, lr_at,
                                       scale_config)

        return jax.jit(train_step, donate_argnums=(0,))

    # ------------------------------------------------------------------
    # 1-bit Adam step: the whole step runs inside shard_map over ``data``
    # with LOCAL (pre-reduction) gradients, so the compressed momentum
    # exchange REPLACES the gradient psum — the wire saving the reference
    # gets by disabling the engine allreduce at freeze
    # (reference: onebit_adam.py:104-228, engine handoff :366-372).
    # ------------------------------------------------------------------
    def _build_onebit_step(self, phase: str):
        from ..compress.onebit import OnebitAdamState, onebit_adam
        clip = self.gradient_clipping
        scale_config = self.loss_scale_config
        lr_schedule = self._lr_schedule
        mesh = self.mesh
        oparams = dict(self.config.optimizer_params)
        cfg_lr = float(oparams.get("lr", 1e-3))
        tx = onebit_adam(
            lr_schedule if lr_schedule is not None else cfg_lr,
            betas=tuple(oparams.get("betas", (0.9, 0.999))),
            eps=float(oparams.get("eps", 1e-8)),
            weight_decay=float(oparams.get("weight_decay", 0.0)),
            freeze_step=int(oparams.get("freeze_step", 100000)),
            data_axis=DATA_AXIS, phase=phase)
        lr_at = self._lr_at_fn()

        squeeze0 = lambda t: jax.tree.map(lambda a: jnp.squeeze(a, 0), t)
        stack0 = lambda t: jax.tree.map(lambda a: a[None], t)

        def spmd(state: TrainState, batch):
            scaler = state.scaler
            widx = jax.lax.axis_index(DATA_AXIS)
            # decorrelate dropout across workers (the GSPMD path partitions
            # one random-bit tensor instead)
            step_rng = jax.random.fold_in(
                jax.random.fold_in(state.rng, state.global_steps), widx)
            opt = state.opt_state
            opt_local = opt._replace(
                worker_error=squeeze0(opt.worker_error),
                server_error=squeeze0(opt.server_error))

            # grads stay LOCAL (constrain=False): the compressed collective
            # below is the only cross-worker gradient-sized exchange
            grads, scaled_losses = self._scan_scaled_grads(
                state.master_params, batch, scaler, step_rng,
                constrain=False)

            # overflow anywhere -> every worker skips (scalar collective;
            # reference CheckOverflow allreduces a MAX the same way,
            # runtime/utils.py:41-137)
            finite_local = precision.grads_finite(grads)
            bad = jax.lax.psum(
                (~finite_local).astype(jnp.float32), DATA_AXIS)
            finite = bad == 0
            # reporting norm: sqrt of the worker-mean squared local norm (a
            # scalar collective; the true norm of the average gradient
            # would require the very allreduce compression avoids)
            norm2 = global_norm(grads) ** 2
            grad_norm = jnp.sqrt(jax.lax.pmean(norm2, DATA_AXIS))
            if clip > 0:
                grads, _ = clip_by_global_norm(grads, clip, norm=grad_norm)

            updates, new_opt_local = tx.update(
                grads, opt_local, state.master_params)
            master2 = optax.apply_updates(state.master_params, updates)

            # overflow-skip as elementwise select: no lax.cond around code
            # containing collectives (fragile in SPMD lowering)
            keep = lambda n, o: jax.tree.map(
                lambda a, b: jnp.where(finite, a, b), n, o)
            new_master = keep(master2, state.master_params)
            new_opt = OnebitAdamState(
                count=opt.count + finite.astype(jnp.int32),
                mu=keep(new_opt_local.mu, opt.mu),
                nu=keep(new_opt_local.nu, opt.nu),
                worker_error=stack0(
                    keep(new_opt_local.worker_error,
                         opt_local.worker_error)),
                server_error=stack0(
                    keep(new_opt_local.server_error,
                         opt_local.server_error)))

            mean_loss = jax.lax.pmean(
                jnp.mean(scaled_losses) / scaler.loss_scale, DATA_AXIS)
            return self._step_epilogue(state, new_master, new_opt, finite,
                                       mean_loss, grad_norm, lr_at,
                                       scale_config)

        err_spec = P(DATA_AXIS)
        rep = lambda t: jax.tree.map(lambda _: P(), t)
        state_specs = TrainState(
            master_params=rep(self.state.master_params),
            opt_state=self.state.opt_state.__class__(
                count=P(),
                mu=rep(self.state.opt_state.mu),
                nu=rep(self.state.opt_state.nu),
                worker_error=jax.tree.map(
                    lambda _: err_spec, self.state.opt_state.worker_error),
                server_error=jax.tree.map(
                    lambda _: err_spec, self.state.opt_state.server_error)),
            scaler=jax.tree.map(lambda _: P(), self.state.scaler),
            global_steps=P(), skipped_steps=P(), rng=P())
        batch_spec = P(None, DATA_AXIS)

        sm = jax.shard_map(
            spmd, mesh=mesh,
            in_specs=(state_specs, batch_spec),
            out_specs=(state_specs, P()),
            axis_names={DATA_AXIS},
            check_vma=False)
        return jax.jit(sm, donate_argnums=(0,))

    # ------------------------------------------------------------------
    # CSR sparse-gradient step: embedding-style grads cross the data axis
    # as (indices, values) allgathers instead of a dense [vocab, d] psum
    # (reference: sparse_gradients + nn.Embedding detection at
    # engine.py:177-183, CSR exchange at engine.py:1153-1209).
    # ------------------------------------------------------------------
    def _use_sparse_grads(self) -> bool:
        if not self.config.sparse_gradients_enabled:
            return False
        hook = getattr(type(self.module), "sparse_grad_tokens", None)
        if hook is None or hook is TrainModule.sparse_grad_tokens:
            log_dist(
                "sparse_gradients enabled but the module declares no "
                "sparse params (sparse_grad_tokens) — dense path",
                ranks=[0])
            return False
        if self.config.zero_optimization_stage >= 1:
            # reference parity: the ZeRO optimizers' reduction machinery is
            # dense-only; sparse_gradients only affects the stage-0
            # allreduce path there too (engine.py:1137-1140)
            log_dist(
                "sparse_gradients ignored under ZeRO stage >= 1 "
                "(reference parity: only the stage-0 allreduce path is "
                "sparse there)", ranks=[0])
            return False
        return self.dp_world_size > 1

    def _build_sparse_grad_step(self):
        from .csr_tensor import csr_allgather, sparse_embedding_grad
        module = self.module
        optimizer = self.optimizer
        clip = self.gradient_clipping
        scale_config = self.loss_scale_config
        mesh = self.mesh
        dp = self.dp_world_size
        lr_at = self._lr_at_fn()

        def spmd(state: TrainState, batch):
            scaler = state.scaler
            widx = jax.lax.axis_index(DATA_AXIS)
            step_rng = jax.random.fold_in(
                jax.random.fold_in(state.rng, state.global_steps), widx)
            # LOCAL grads; the combine below chooses dense pmean vs CSR
            # allgather per leaf
            grads, scaled_losses = self._scan_scaled_grads(
                state.master_params, batch, scaler, step_rng,
                constrain=False)

            sparse_map = module.sparse_grad_tokens(batch) or {}
            flat, treedef = jax.tree_util.tree_flatten_with_path(grads)
            known = {jax.tree_util.keystr(p) for p, _ in flat}
            unknown = set(sparse_map) - known
            if unknown:
                raise ValueError(
                    f"sparse_grad_tokens declares params {sorted(unknown)} "
                    f"that do not exist in the gradient tree; valid "
                    f"keystrs: {sorted(known)}")
            combined = []
            for path, g in flat:
                key = jax.tree_util.keystr(path)
                if key in sparse_map:
                    csr = sparse_embedding_grad(g, sparse_map[key])
                    gathered = csr_allgather(csr, DATA_AXIS)
                    combined.append(gathered.to_dense() / dp)
                else:
                    combined.append(jax.lax.pmean(g, DATA_AXIS))
            grads = jax.tree_util.tree_unflatten(treedef, combined)

            # combined grads are identical on every worker from here on —
            # standard step semantics apply
            finite = precision.grads_finite(grads)
            grad_norm = global_norm(grads)
            if clip > 0:
                grads, _ = clip_by_global_norm(grads, clip, norm=grad_norm)

            def do_update(operand):
                master, opt_state = operand
                updates, new_opt = optimizer.update(grads, opt_state, master)
                return optax.apply_updates(master, updates), new_opt

            new_master, new_opt = jax.lax.cond(
                finite, do_update, lambda o: o,
                (state.master_params, state.opt_state))

            mean_loss = jax.lax.pmean(
                jnp.mean(scaled_losses) / scaler.loss_scale, DATA_AXIS)
            return self._step_epilogue(state, new_master, new_opt, finite,
                                       mean_loss, grad_norm, lr_at,
                                       scale_config)

        state_specs = jax.tree.map(lambda _: P(), self.state)
        sm = jax.shard_map(
            spmd, mesh=mesh,
            in_specs=(state_specs, P(None, DATA_AXIS)),
            out_specs=(state_specs, P()),
            axis_names={DATA_AXIS},
            check_vma=False)
        return jax.jit(sm, donate_argnums=(0,))

    def _init_onebit_opt_state(self, master, master_shardings=None):
        """1-bit Adam multi-worker state: mu/nu are replicated (they hold
        the post-collective common value), worker/server error buffers are
        genuinely PER-WORKER — stored stacked [dp, n] and sharded over
        ``data`` so each worker owns its own feedback (reference: per-rank
        worker_error/server_error tensors, onebit_adam.py:287-309)."""
        from ..compress.onebit import init_onebit_state
        if master_shardings is None:
            master_shardings = self.zero_plan.master_shardings(master)
        dp = self.dp_world_size
        st = init_onebit_state(master, dp)
        stack = lambda t: jax.tree.map(
            lambda l: jnp.broadcast_to(l, (dp,) + l.shape), t)
        err_sharding = NamedSharding(self.mesh, P(DATA_AXIS))
        dev = NamedSharding(self.mesh, P())
        return st._replace(
            count=jax.device_put(st.count, dev),
            mu=_device_put_tree(st.mu, master_shardings),
            nu=_device_put_tree(st.nu, master_shardings),
            worker_error=jax.tree.map(
                lambda l: jax.device_put(l, err_sharding),
                stack(st.worker_error)),
            server_error=jax.tree.map(
                lambda l: jax.device_put(l, err_sharding),
                stack(st.server_error)))

    def _fresh_opt_state(self, master):
        """A brand-new optimizer state in the engine's INTERNAL form — used
        by module-only checkpoint restores.  Offload tiers go through
        _adopt_loaded(master, None); this covers the device paths."""
        if self._onebit_path and self.dp_world_size > 1:
            return self._init_onebit_opt_state(master)
        return self.optimizer.init(master)

    def _place_scalar(self, x):
        """Explicit replicated device placement for scalar state — without
        it, fresh jnp scalars change the compiled step's cache key and the
        next call silently recompiles the whole program."""
        return jax.device_put(jnp.asarray(x), NamedSharding(self.mesh, P()))

    def _select_onebit_step(self):
        """Host-side freeze transition (the reference flips
        enable_backward_allreduce at freeze, onebit_adam.py:366-372).
        Selected on the dispatch-time step counter: overflow-skipped steps
        count toward the freeze schedule here (under bf16 — the TPU-native
        dtype — no steps skip, so this matches the reference exactly)."""
        warm_fn, frozen_fn, freeze_step = self._onebit_steps
        return warm_fn if self.global_steps < freeze_step else frozen_fn

    def _build_eval_step(self):
        module = self.module
        compute_dtype = self.compute_dtype

        def eval_step(state: TrainState, batch, rng):
            params = precision.cast_to_compute(
                state.master_params, compute_dtype)
            return module.loss_fn(params, batch, rng, train=False)

        return jax.jit(eval_step)

    # ------------------------------------------------------------------
    # ZeRO-Offload steps (device grads → host Adam → device params)
    # ------------------------------------------------------------------
    def _build_offload_grad_step(self):
        module = self.module
        plan = self.zero_plan
        grad_acc = self._scan_grad_acc
        clip = self.gradient_clipping

        def grad_step(compute_params, batch, loss_scale, step_rng):
            def micro_loss(params, mb, rng):
                loss = module.loss_fn(params, mb, rng, train=True)
                return loss.astype(jnp.float32) * loss_scale

            grad_fn = jax.value_and_grad(micro_loss)

            def acc_body(carry, mb):
                gsum, i = carry
                rng = jax.random.fold_in(step_rng, i)
                scaled_loss, g = grad_fn(compute_params, mb, rng)
                gsum = jax.tree.map(
                    lambda a, b: a + b.astype(jnp.float32), gsum, g)
                return (gsum, i + 1), scaled_loss

            gsum0 = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), compute_params)
            (gsum, _), scaled_losses = jax.lax.scan(
                acc_body, (gsum0, jnp.asarray(0, jnp.int32)), batch)
            inv = (1.0 / (loss_scale * grad_acc)).astype(jnp.float32)
            grads = jax.tree.map(lambda g: g * inv, gsum)
            # ZeRO-2 placement: the host pulls reduce-scattered shards
            grads = constrain_grads(grads, plan)
            finite = precision.grads_finite(grads)
            grad_norm = global_norm(grads)
            if clip > 0:
                grads, _ = clip_by_global_norm(grads, clip, norm=grad_norm)
            mean_loss = jnp.mean(scaled_losses) / loss_scale
            return grads, mean_loss, finite, grad_norm

        return jax.jit(grad_step, donate_argnums=(1,))

    def _build_offload_eval_step(self):
        module = self.module

        def eval_step(compute_params, batch, rng):
            return module.loss_fn(compute_params, batch, rng, train=False)

        return jax.jit(eval_step)

    # ------------------------------------------------------------------
    # ZeRO-Offload, XLA tier: one compiled step; fp32 master + moments live
    # in pinned_host memory as flat padded vectors, cast + Adam run as XLA
    # host computations.
    # ------------------------------------------------------------------
    def _zero_host_pieces(self):
        """Zeroed (dp, w_i) host pieces — fresh Adam moments, shaped and
        placed exactly like the master pieces (one definition for both
        fresh init and checkpoint-load so they cannot drift).  Zeros are
        produced by a jit whose output IS pinned_host: the eager
        jnp.zeros + device_put form allocates each moment plane in HBM
        first and moves it over the slow client path."""
        zero_piece = getattr(self, "_zero_piece_jit", None)
        if zero_piece is None:
            # one jit for the engine's lifetime: a fresh wrapper per call
            # would retrace/compile every distinct width on every call
            # (init makes two calls for mu/nu, checkpoint load two more).
            # dp is captured ONCE here — it is fixed per engine.
            dp = self.dp_world_size
            zero_piece = jax.jit(
                lambda w: jnp.zeros((dp, w), jnp.float32),
                static_argnums=0,
                out_shardings=self._piece_host_sharding)
            self._zero_piece_jit = zero_piece
        return tuple(zero_piece(rec.w) for rec in self._flat_layout)

    def _offload_flatten(self, tree, dtype=jnp.float32):
        """Param-shaped tree -> tuple of partition-major (dp, w_i) pieces
        (traceable).  Each leaf's data-sharded dim is moved to the front
        and split into dp rows, so a leaf carrying its ZeRO reduce-scatter
        / stage-3 sharding packs into its P('data') piece with ZERO
        collectives — every reshape is sharding-natural (see
        ``_FlatLeaf``)."""
        dp = self.dp_world_size
        return tuple(
            _pack_leaf(leaf.astype(dtype), rec, dp, jnp)
            for leaf, rec in zip(jax.tree.leaves(tree), self._flat_layout))

    def _offload_unflatten(self, pieces):
        """Pieces -> param-shaped tree with compute shardings (traceable).
        Delegates per leaf to ``_unpack_device_piece`` — the ONE
        definition of the gather/unpack contract.  Piece-wise state also
        means NO slicing of one big vector here, removing the last SPMD
        hazard of the old layout."""
        shard_leaves = jax.tree.leaves(
            self._compute_shardings,
            is_leaf=lambda x: isinstance(x, NamedSharding))
        out = [
            self._unpack_device_piece(p, rec, sh)
            for p, rec, sh in zip(pieces, self._flat_layout, shard_leaves)]
        return jax.tree.unflatten(self._flat_treedef, out)

    def _unflatten_numpy(self, pieces):
        """Host-side unflatten for checkpointing (no device memory cost).
        Inverts the same partition-major layout as the traceable pair."""
        out = [
            _unpack_leaf(np.asarray(jax.device_get(p)), rec, np)
            for p, rec in zip(pieces, self._flat_layout)]
        return jax.tree.unflatten(self._flat_treedef, out)

    def _flatten_numpy(self, tree):
        dp = self.dp_world_size
        return tuple(
            _pack_leaf(np.asarray(jax.device_get(l)).astype(np.float32),
                       rec, dp, np)
            for l, rec in zip(jax.tree.leaves(tree), self._flat_layout))

    def _host_section(self):
        """compute_on('device_host') on real TPUs; a no-op scope on CPU test
        meshes (same memory space, and the host-compute partitioner rejects
        sharded host placements there).  DS_OFFLOAD_COMPUTE_ON=0 excises
        the host-compute sections while keeping pinned_host residency —
        XLA then runs the optimizer math on device with streamed transfers
        (diagnosis knob for the compute_on stall candidate; also a valid
        fallback configuration in its own right)."""
        if (self._offload_real_host
                and os.environ.get("DS_OFFLOAD_COMPUTE_ON", "1") == "1"):
            from jax.experimental import compute_on
            return compute_on.compute_on("device_host")
        import contextlib
        return contextlib.nullcontext()

    def _xla_offload_cast_up(self, master_pieces):
        """Host-side cast to compute dtype + PCIe upload (half the bytes of
        shipping fp32 and casting on device), then split into the tree.

        Stages ≤ 2: each piece is all-gathered whole before its unpack —
        the ZeRO param all-gather, one collective per parameter (NOT the
        hundreds of tiny reshard collectives that slicing a dp-sharded
        vector fragments into), and peak-memory-neutral there because
        stages ≤ 2 materialize replicated compute params anyway.
        Stage 3 skips the gather: compute params stay data-sharded.

        param_streaming: masked leaves are cast AND unpacked inside the
        host section and constrained to a pinned_host placement — their
        compute copies never claim HBM.  The model fetches one layer's
        slice per scan tick (streaming_param_spec contract), so device-
        resident parameter bytes ~ one layer + the non-streamed leaves
        (embeddings, final LN) — ZeRO-Infinity's param offload re-expressed
        as XLA memory placement.  Streaming leaves never need the stage<3
        gather: the mode requires dp == 1 below stage 3."""
        mask = getattr(self, "_stream_mask", None) or \
            [False] * len(self._flat_layout)
        with self._host_section():
            lowp = [p.astype(self.compute_dtype) for p in master_pieces]
            stream_leaves = {
                i: _unpack_leaf(lowp[i], rec, jnp)
                for i, rec in enumerate(self._flat_layout) if mask[i]}
        shard_leaves = jax.tree.leaves(
            self._compute_shardings,
            is_leaf=lambda x: isinstance(x, NamedSharding))
        out = []
        for i, rec in enumerate(self._flat_layout):
            if mask[i]:
                sh = shard_leaves[i]
                if self._offload_real_host:
                    sh = sh.with_memory_kind("pinned_host")
                out.append(jax.lax.with_sharding_constraint(
                    stream_leaves[i], sh))
            else:
                out.append(self._unpack_device_piece(
                    lowp[i], rec, shard_leaves[i]))
        return jax.tree.unflatten(self._flat_treedef, out)

    def _unpack_device_piece(self, piece, rec: _FlatLeaf, leaf_sharding):
        """ONE definition of piece -> device compute leaf, shared by the
        streamed and unstreamed cast-up paths so the partition-major
        unpack and the stage<3 gather cannot drift apart.

        Stages ≤ 2: the piece is all-gathered whole before its unpack —
        the fused ZeRO param all-gather (reference stage2.py:1438-1471),
        one collective per parameter, peak-memory-neutral because stages
        ≤ 2 materialize replicated compute params anyway.  Stage 3 skips
        the gather: pieces stay P('data')-sharded and, because the layout
        is partition-major, the reshape lands exactly on the leaf's
        data-sharded compute spec — no resharding collectives (ZeRO-3
        never materializes the replica)."""
        p = jax.device_put(piece, self._piece_dev_sharding)
        if self.zero_plan.stage < 3:
            p = jax.lax.with_sharding_constraint(
                p, NamedSharding(self.mesh, P()))
        return jax.lax.with_sharding_constraint(
            _unpack_leaf(p, rec, jnp), leaf_sharding)

    def _build_xla_offload_step(self):
        compute_dtype = self.compute_dtype
        clip = self.gradient_clipping
        scale_config = self.loss_scale_config
        oparams = dict(self.config.optimizer_params)
        b1, b2 = (float(b) for b in oparams.get("betas", (0.9, 0.999)))
        eps = float(oparams.get("eps", 1e-8))
        wd = float(oparams.get("weight_decay", 0.0))
        adam_w_mode = bool(oparams.get("adam_w_mode", True))
        bias_correction = bool(oparams.get("bias_correction", True))
        piece_dev = self._piece_dev_sharding
        piece_host = self._piece_host_sharding
        host_scalar = NamedSharding(self.mesh, P())
        if self._offload_real_host:
            host_scalar = host_scalar.with_memory_kind("pinned_host")
        lr_at = self._lr_at_fn()

        def train_step(state: TrainState, batch):
            scaler = state.scaler
            step_rng = jax.random.fold_in(state.rng, state.global_steps)
            params = self._xla_offload_cast_up(state.master_params)
            # params are already compute-dtype (the host cast above).
            # constrain=True is deliberate: the per-leaf ZeRO shardings keep
            # the fp32 grad accumulator at ~N/dp per device through the
            # scan — dropping them would fragment fewer collectives but
            # replicate ~N fp32 on every device (ZeRO-2's whole memory
            # point); on the dp=1 bench chip constraints are no-ops either
            # way.
            grads, scaled_losses = self._scan_scaled_grads(
                params, batch, scaler, step_rng, cast=False,
                keep_param_dtype=True)
            finite = precision.grads_finite(grads)
            grad_norm = global_norm(grads)
            if clip > 0:
                grads, _ = clip_by_global_norm(grads, clip, norm=grad_norm)

            # The host section must be ALL-FLOAT: an s32 (the Adam step
            # count) in pinned_host space trips XLA's host-compute alias
            # assigner.  Count, bias correction, and lr are computed here on
            # device and shipped over as f32 scalars.
            opt = state.opt_state
            count1 = opt.count + 1
            count_f = count1.astype(jnp.float32)
            if bias_correction:
                c1 = 1 - b1 ** count_f
                c2 = 1 - b2 ** count_f
            else:
                c1 = c2 = jnp.asarray(1.0, jnp.float32)
            step_lr = lr_at(count1)

            # PCIe down: per-parameter compute-dtype grad pieces (the
            # reference likewise stages fp16 gradients into pinned host
            # buffers, stage2.py:793-816); the P('data') constraint makes
            # each pack consume its rank's reduce-scattered slice only,
            # and XLA schedules/overlaps the piece transfers inside the
            # one compiled step.
            gpieces = tuple(
                jax.device_put(
                    jax.lax.with_sharding_constraint(p, piece_dev),
                    piece_host)
                for p in self._offload_flatten(grads, compute_dtype))
            finite_f = jax.device_put(
                finite.astype(jnp.float32), host_scalar)
            c1_h = jax.device_put(c1, host_scalar)
            c2_h = jax.device_put(c2, host_scalar)
            lr_h = jax.device_put(step_lr, host_scalar)

            masters = state.master_params  # tuple of pinned_host f32 pieces
            new_master, new_mu, new_nu = self._host_adam_pieces(
                gpieces, masters, opt, finite_f, c1_h, c2_h, lr_h,
                b1=b1, b2=b2, eps=eps, wd=wd, adam_w_mode=adam_w_mode)

            new_opt = FusedAdamState(
                count=opt.count + finite.astype(jnp.int32),
                mu=new_mu, nu=new_nu)
            mean_loss = jnp.mean(scaled_losses) / scaler.loss_scale
            return self._step_epilogue(state, new_master, new_opt, finite,
                                       mean_loss, grad_norm, lr_at,
                                       scale_config)

        # Outputs MUST be pinned to the state's canonical placement: without
        # explicit out_shardings the host-section outputs surface in default
        # device memory, the next call sees different avals, and every step
        # retraces + recompiles (~40s/step observed on a v5e).
        dev = NamedSharding(self.mesh, P())
        n_pieces = len(self._flat_layout)
        host_tuple = (piece_host,) * n_pieces
        state_shardings = jax.tree.map(lambda _: dev, self.state)._replace(
            master_params=host_tuple,
            opt_state=FusedAdamState(count=dev, mu=host_tuple,
                                     nu=host_tuple))
        return jax.jit(train_step, donate_argnums=(0,),
                       out_shardings=(state_shardings, dev))

    def _host_adam_pieces(self, gpieces, masters, opt, finite_f,
                          c1_h, c2_h, lr_h, *, b1, b2, eps, wd,
                          adam_w_mode, clip_scale_h=None):
        """The piece-wise Adam update in one host-compute section — the
        ONE definition of overflow-skip masking and weight-decay
        semantics for both the single-program and chunked offload steps.
        All operands are floats (an s32 in pinned_host space trips XLA's
        host-compute alias assigner; control flow stays outside — the
        write-back is an elementwise select on ``finite_f``)."""
        with self._host_section():
            new_master, new_mu, new_nu = [], [], []
            keep = finite_f > 0.5
            for gh, master, mu_p, nu_p in zip(
                    gpieces, masters, opt.mu, opt.nu):
                g32 = gh.astype(jnp.float32)
                if clip_scale_h is not None:
                    g32 = g32 * clip_scale_h
                if wd != 0.0 and not adam_w_mode:
                    g32 = g32 + wd * master
                mu2, nu2 = adam_moments(g32, mu_p, nu_p, b1, b2)
                upd = adam_direction(mu2, nu2, c1_h, c2_h, eps)
                if wd != 0.0 and adam_w_mode:
                    upd = upd + wd * master
                master2 = master - lr_h * upd
                new_master.append(jnp.where(keep, master2, master))
                new_mu.append(jnp.where(keep, mu2, mu_p))
                new_nu.append(jnp.where(keep, nu2, nu_p))
            return (tuple(new_master), tuple(new_mu), tuple(new_nu))

    def _build_split_update(self, *, b1, b2, eps, wd, adam_w_mode,
                            bias_correction, clip, scale_config, lr_at,
                            piece_host, host_scalar, donate: bool = True):
        """Optimizer update as ONE COMPILED PROGRAM PER MASTER PIECE
        (zero_optimization.offload_split_update).

        Why program-per-piece: XLA cannot extend buffer liveness across
        executable boundaries, so device-resident optimizer bytes are
        bounded by ONE piece's temps even where the compiler materializes
        host-placed buffers in HBM — the observed failure of the fused
        update program on the AOT compile path (round-5 hardware window:
        22.76 GB of fp32 piece-shaped HLO temps at 1.5B).  The reference
        gets the same bound from its pinned-buffer tile loop
        (csrc/adam/cpu_adam.cpp:64-113 there); here the boundary IS the
        mechanism.  Numerics are identical to the fused update — same
        _host_adam_pieces math per piece, same overflow-skip select.

        Cost: one dispatch per piece per step (tens of microseconds each)
        plus one scalar-stats program and one scalar-tail program; jit
        caches by piece shape, so a scan-stacked transformer compiles a
        handful of distinct piece programs, not one per layer.

        ``donate=False`` is the DPU composition: the deferred update for
        step t-1 runs while the already-dispatched grad program for step
        t still READS the same master pieces, so the old buffers must
        stay live (ping-pong; transient 2x fp32 host state, same price
        the fused DPU pays).  Without donation a mid-loop failure leaves
        the old state fully intact, so the poison guard applies only to
        the donating variant.
        """
        dev = NamedSharding(self.mesh, P())

        def stats_fn(count, finites, sumsqs):
            finite, grad_norm, c1, c2, step_lr, cscale = \
                _offload_update_scalars(
                    count, finites, sumsqs, b1=b1, b2=b2,
                    bias_correction=bias_correction, clip=clip,
                    lr_at=lr_at)
            return (finite, grad_norm, finite.astype(jnp.float32),
                    jnp.asarray(c1, jnp.float32),
                    jnp.asarray(c2, jnp.float32),
                    jnp.asarray(step_lr, jnp.float32), cscale)

        stats_jit = jax.jit(
            stats_fn,
            out_shardings=(dev, dev) + (host_scalar,) * 5)

        def piece_fn(master, mu, nu, g, finite_f, c1, c2, lr, cs):
            # delegate to _host_adam_pieces with one-piece tuples: it is
            # the ONE definition of overflow-skip and weight-decay
            # semantics (count is unused there; zero placeholder)
            opt1 = FusedAdamState(count=jnp.zeros((), jnp.int32),
                                  mu=(mu,), nu=(nu,))
            new_m, new_mu, new_nu = self._host_adam_pieces(
                (g,), (master,), opt1, finite_f, c1, c2, lr,
                b1=b1, b2=b2, eps=eps, wd=wd, adam_w_mode=adam_w_mode,
                clip_scale_h=cs)
            return new_m[0], new_mu[0], new_nu[0]

        # the grad piece (3) is donated in both variants: it is dead
        # after this program either way
        piece_jit = jax.jit(
            piece_fn,
            donate_argnums=((0, 1, 2, 3) if donate else (3,)),
            out_shardings=(piece_host,) * 3)

        def tail_fn(scaler, global_steps, skipped, count, finite,
                    mean_loss, grad_norm):
            new_scaler, new_global, new_skipped, packed = \
                self._epilogue_scalars(scaler, global_steps, skipped,
                                       finite, mean_loss, grad_norm,
                                       lr_at, scale_config)
            new_count = count + finite.astype(jnp.int32)
            return new_scaler, new_global, new_skipped, new_count, packed

        # scaler/counter/packed-metric outputs pinned replicated exactly
        # like the fused path's state_shardings — without this the split
        # tail's scalars ride default placement and their avals diverge
        # from the fused state on a multi-device mesh
        tail_jit = jax.jit(tail_fn, out_shardings=dev)

        def update_split(state: TrainState, gpieces, finites, sumsqs,
                         mean_loss):
            opt = state.opt_state
            (finite, grad_norm, finite_f, c1_h, c2_h, lr_h,
             cs_h) = stats_jit(opt.count, finites, sumsqs)
            new_m, new_mu, new_nu = [], [], []
            try:
                for m, mu, nu, g in zip(state.master_params, opt.mu,
                                        opt.nu, gpieces):
                    m2, mu2, nu2 = piece_jit(m, mu, nu, g, finite_f,
                                             c1_h, c2_h, lr_h, cs_h)
                    new_m.append(m2)
                    new_mu.append(mu2)
                    new_nu.append(nu2)
                # the tail must sit inside the guard too: by now every
                # old master/mu/nu buffer is donated, so a tail failure
                # leaves self.state just as unrecoverable as a mid-piece
                # one
                (new_scaler, new_global, new_skipped, new_count,
                 packed) = tail_jit(state.scaler, state.global_steps,
                                    state.skipped_steps, opt.count,
                                    finite, mean_loss, grad_norm)
            except BaseException as e:
                # BaseException, not Exception: a KeyboardInterrupt mid
                # piece-loop deletes donated buffers exactly like a crash
                # does, and must poison the state the same way
                if not donate:
                    # ping-pong variant: the old buffers are intact;
                    # discarding the partial update leaves state valid
                    raise
                # pieces updated so far were DONATED: self.state still
                # points at their deleted buffers, so this engine's
                # optimizer plane is unrecoverable.  Poison loudly rather
                # than letting a later save_checkpoint serialize a
                # half-donated state or die on 'Array has been deleted'.
                self._fatal_state_error = (
                    "offload_split_update failed after "
                    f"{len(new_m)}/{len(gpieces)} piece updates: the "
                    "applied pieces' previous buffers were donated, so "
                    "this engine's optimizer state is unusable. "
                    "load_checkpoint on this engine (or rebuild it) to "
                    "recover. Original error: "
                    f"{e!r}")
                if not isinstance(e, Exception):
                    # KeyboardInterrupt/SystemExit must keep their type —
                    # wrapping them in RuntimeError would stop Ctrl-C from
                    # actually interrupting the run
                    raise
                raise RuntimeError(self._fatal_state_error) from e
            new_state = TrainState(
                master_params=tuple(new_m),
                opt_state=FusedAdamState(count=new_count,
                                         mu=tuple(new_mu),
                                         nu=tuple(new_nu)),
                scaler=new_scaler,
                global_steps=new_global,
                skipped_steps=new_skipped,
                rng=state.rng,
            )
            return new_state, packed

        return update_split

    def _build_xla_offload_eval_step(self):
        module = self.module

        def eval_step(state: TrainState, batch, rng):
            params = self._xla_offload_cast_up(state.master_params)
            return module.loss_fn(params, batch, rng, train=False)

        return jax.jit(eval_step)

    # ------------------------------------------------------------------
    # Chunked-gradient capacity mode (zero_optimization.offload_grad_chunks
    # > 1): K compiled grad programs, each computing one balanced group of
    # parameter gradients and staging them to host, then one compiled
    # host-Adam update over all pieces.  The program boundaries GUARANTEE
    # device-resident gradient bytes <= the largest group (XLA cannot
    # extend liveness across programs) — the in-XLA analogue of the
    # reference streaming gradients into pinned host buffers during
    # backward (stage2.py:743-816), trading K forward recomputations for
    # capacity.
    # ------------------------------------------------------------------
    def _grad_group_indices(self, k: int):
        """Balanced greedy partition of leaf indices into k groups."""
        order = sorted(range(len(self._flat_sizes)),
                       key=lambda i: -self._flat_sizes[i])
        groups = [[] for _ in range(k)]
        loads = [0] * k
        for i in order:
            g = loads.index(min(loads))
            groups[g].append(i)
            loads[g] += self._flat_sizes[i]
        return [sorted(g) for g in groups if g]

    def _build_chunked_offload_steps(self, groups, delayed: bool = False,
                                     split_update: bool = False):
        compute_dtype = self.compute_dtype
        clip = self.gradient_clipping
        scale_config = self.loss_scale_config
        oparams = dict(self.config.optimizer_params)
        b1, b2 = (float(b) for b in oparams.get("betas", (0.9, 0.999)))
        eps = float(oparams.get("eps", 1e-8))
        wd = float(oparams.get("weight_decay", 0.0))
        adam_w_mode = bool(oparams.get("adam_w_mode", True))
        bias_correction = bool(oparams.get("bias_correction", True))
        piece_dev = self._piece_dev_sharding
        piece_host = self._piece_host_sharding
        host_scalar = NamedSharding(self.mesh, P())
        if self._offload_real_host:
            host_scalar = host_scalar.with_memory_kind("pinned_host")
        lr_at = self._lr_at_fn()
        module = self.module
        treedef = self._flat_treedef
        n_leaves = len(self._flat_sizes)
        dp = self.dp_world_size
        # full-tree grad placement, selected by leaf index (grad_specs on
        # a subset tree would misalign with the base specs; the dummy
        # tree must carry real shapes — int leaves' () shapes would make
        # every spec replicated and defeat the memory bound)
        shape_tree = jax.tree.unflatten(treedef, [
            jax.ShapeDtypeStruct(s, jnp.float32)
            for s in self._flat_shapes])
        gspecs = jax.tree.leaves(
            self.zero_plan.grad_specs(shape_tree),
            is_leaf=lambda x: isinstance(x, P))

        def make_grad_fn(gidx, first):
            gset = list(gidx)
            group_shardings = [NamedSharding(self.mesh, gspecs[i])
                               for i in gset]

            def con_subset(tree):
                # subset-aware ZeRO grad constraint: applied INSIDE the
                # accumulation scan too, so the fp32 carry stays sharded
                # over data (the single-program path's constrain=True)
                return [jax.lax.with_sharding_constraint(g, sh)
                        for g, sh in zip(tree, group_shardings)]

            def grad_fn(master_pieces, batch, scaler, rng, global_steps):
                step_rng = jax.random.fold_in(rng, global_steps)
                params = self._xla_offload_cast_up(master_pieces)
                leaves = jax.tree.leaves(params)
                active = [leaves[i] for i in gset]

                def subset_loss(act, mb, mrng, train=True):
                    merged = list(leaves)
                    for j, i in enumerate(gset):
                        merged[i] = act[j]
                    return module.loss_fn(
                        jax.tree.unflatten(treedef, merged), mb, mrng,
                        train=train)

                grads, scaled_losses = self._scan_scaled_grads(
                    active, batch, scaler, step_rng, cast=False,
                    constrain=False, keep_param_dtype=True,
                    loss_fn=subset_loss, constrain_fn=con_subset)
                finite = precision.grads_finite(grads)
                sumsq = sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in grads)
                pieces = []
                for j, i in enumerate(gset):
                    p = _pack_leaf(grads[j].astype(compute_dtype),
                                   self._flat_layout[i], dp, jnp)
                    p = jax.lax.with_sharding_constraint(p, piece_dev)
                    pieces.append(jax.device_put(p, piece_host))
                out = (tuple(pieces), finite, sumsq)
                if first:
                    mean_loss = (jnp.mean(scaled_losses)
                                 / scaler.loss_scale)
                    out = out + (mean_loss,)
                return out

            return jax.jit(grad_fn)

        grad_fns = [make_grad_fn(g, first=(k == 0))
                    for k, g in enumerate(groups)]

        def update_fn(state: TrainState, gpieces, finites, sumsqs,
                      mean_loss):
            # per-group stats combine INSIDE the one compiled program —
            # eager op-by-op combination would dispatch ~2K tiny programs
            # per step (the class of overhead prior rounds removed)
            opt = state.opt_state
            finite, grad_norm, c1, c2, step_lr, cscale = \
                _offload_update_scalars(
                    opt.count, finites, sumsqs, b1=b1, b2=b2,
                    bias_correction=bias_correction, clip=clip,
                    lr_at=lr_at)
            finite_f = jax.device_put(
                finite.astype(jnp.float32), host_scalar)
            c1_h = jax.device_put(c1, host_scalar)
            c2_h = jax.device_put(c2, host_scalar)
            lr_h = jax.device_put(step_lr, host_scalar)
            cs_h = jax.device_put(cscale, host_scalar)
            new_master, new_mu, new_nu = self._host_adam_pieces(
                gpieces, state.master_params, opt, finite_f, c1_h, c2_h,
                lr_h, b1=b1, b2=b2, eps=eps, wd=wd,
                adam_w_mode=adam_w_mode, clip_scale_h=cs_h)
            new_opt = FusedAdamState(
                count=opt.count + finite.astype(jnp.int32),
                mu=new_mu, nu=new_nu)
            return self._step_epilogue(state, new_master, new_opt, finite,
                                       mean_loss, grad_norm, lr_at,
                                       scale_config)

        dev = NamedSharding(self.mesh, P())
        host_tuple = (piece_host,) * n_leaves
        state_shardings = jax.tree.map(lambda _: dev, self.state)._replace(
            master_params=host_tuple,
            opt_state=FusedAdamState(count=dev, mu=host_tuple,
                                     nu=host_tuple))
        # DPU: no donation — the update for step t-1 runs while the
        # already-dispatched grad program for step t still READS the same
        # master pieces, so aliasing would be refused anyway (ping-pong
        # buffers; transient 2× host state is the price of the overlap)
        if split_update:
            update_jit = self._build_split_update(
                b1=b1, b2=b2, eps=eps, wd=wd, adam_w_mode=adam_w_mode,
                bias_correction=bias_correction, clip=clip,
                scale_config=scale_config, lr_at=lr_at,
                piece_host=piece_host, host_scalar=host_scalar,
                donate=not delayed)
        else:
            update_jit = jax.jit(
                update_fn, donate_argnums=(() if delayed else (0,)),
                out_shardings=(state_shardings, dev))
        self._xla_dpu_update = update_jit if delayed else None

        def run_grads(state, batch, step_seed):
            pieces_by_leaf = [None] * n_leaves
            finites, sumsqs, mean_loss = [], [], None
            for k, (gidx, fn) in enumerate(zip(groups, grad_fns)):
                out = fn(state.master_params, batch, state.scaler,
                         state.rng, step_seed)
                pieces, fin, sumsq = out[:3]
                if k == 0:
                    mean_loss = out[3]
                for j, i in enumerate(gidx):
                    pieces_by_leaf[i] = pieces[j]
                finites.append(fin)
                sumsqs.append(sumsq)
            return (tuple(pieces_by_leaf), tuple(finites), tuple(sumsqs),
                    mean_loss)

        if not delayed:
            def train_step(state: TrainState, batch):
                gp, fins, ssqs, mean_loss = run_grads(
                    state, batch, state.global_steps)
                return update_jit(state, gp, fins, ssqs, mean_loss)

            return train_step

        # ---- delayed parameter update (xla tier) ----
        # Dispatch step t's grad program(s) on the CURRENT (one-step-
        # stale) master FIRST, then apply step t-1's pending update: the
        # device crunches t's fwd/bwd while the update's host section
        # runs — the overlap the single-program step structurally cannot
        # have (its host Adam sits between the grads and the next cast-
        # up of the SAME step).  Returned packed metrics carry step t's
        # loss with step t-1's grad_norm/scale/lr (one tiny .at[].set
        # per step, DPU mode only).
        #
        # Loss-scale exactness: finite(t-1) is synced BEFORE dispatching
        # step t.  On the (rare) overflow, the pending update is applied
        # FIRST — forgoing one step's overlap — so step t's grads run at
        # the reacted scale and one overflow costs exactly one skip, not
        # two (the host-tier DPU has the same ordering guarantee).
        #
        # rng: a host-side dispatch counter seeds the per-step rng fold —
        # state.global_steps lags behind dispatches by one (and stalls
        # across flushes), which would hand consecutive steps identical
        # dropout masks.
        def train_step(state: TrainState, batch):
            prev = self._xla_dpu_pending
            if prev is not None:
                prev_finite = all(bool(f) for f in prev[1])
                if not prev_finite:
                    # react to the overflow before dispatching new grads
                    self._xla_dpu_pending = None
                    state, _ = update_jit(state, *prev)
                    prev = None
            seed = jnp.asarray(self._xla_dpu_dispatch, jnp.int32)
            self._xla_dpu_dispatch += 1
            gp, fins, ssqs, mean_loss = run_grads(state, batch, seed)
            self._xla_dpu_pending = (gp, fins, ssqs, mean_loss)
            if prev is not None:
                new_state, packed = update_jit(state, *prev)
            else:
                new_state = state
                applied = state.global_steps - state.skipped_steps
                packed = self._packed_metrics(
                    jnp.asarray(0.0, jnp.float32),
                    jnp.asarray(0.0, jnp.float32), state.scaler,
                    jnp.asarray(True), self._lr_at_fn()(applied))
            packed = packed.at[0].set(mean_loss.astype(jnp.float32))
            return new_state, packed

        return train_step

    def _start_small_leaf_d2h(self, grads):
        """Kick off async D2H for leaves the guarded pull will fetch in
        ONE native call (<= one chunk) — their later device_get just
        syncs the in-flight copy.  Leaves ABOVE the chunk size are pulled
        piece-wise by chunked_device_get; a full-leaf async copy for
        those would move the same bytes over the wire twice.  Sharded
        tier: no-op — the optimizer async-copies per addressable shard."""
        if getattr(self, "_offload_sharded", False):
            return
        from .offload import pull_chunk_bytes
        cb = pull_chunk_bytes()
        for g in jax.tree.leaves(grads):
            if cb <= 0 or getattr(g, "nbytes", 0) <= cb:
                g.copy_to_host_async()

    def _apply_host_update(self, grads):
        """C++ Adam over host grads + re-upload of compute params.

        Default (``offload_pipeline``): the three-stage streaming path —
        per-leaf H2D uploads are issued WHILE the Adam loop runs, so the
        transfer tail hides under host compute instead of serializing
        after it.  ``DS_OFFLOAD_PIPELINE=0`` / ``offload_pipeline:
        false`` falls back to this serial path: full CPU step, then one
        post-step upload.

        Sharded (multi-host) tier: grads are first pinned to the master's
        dp-sharding (a no-op when the ZeRO plan already placed them
        there), each host Adams only its shards, and the updated lowp
        shards all-gather to the compute sharding on device.  A DEGRADED
        ``offload_h2d`` stage pins this path serial (docs/stages.md)."""
        if getattr(self, "_offload_pipeline", False) \
                and not stage_degraded(self, "offload_h2d"):
            return self._apply_host_update_pipelined(grads)
        t0 = time.perf_counter()
        if getattr(self, "_offload_sharded", False):
            if isinstance(grads, _HostBlockStash):
                # DPU-stashed host blocks (pull_local's form) — tagged
                # explicitly rather than sniffed by container type, so a
                # model whose parameter tree is a top-level list cannot
                # be misrouted into step_local
                with self._tel_span("offload/host_adam", cat="offload"):
                    lowp = self._host_opt.step_local(grads.blocks)
            else:
                with self._tel_span("offload/host_adam", cat="offload"):
                    lowp = self._host_opt.step(
                        self._reshard_to_master(grads))
            t1 = time.perf_counter()
            with self._tel_span("offload/h2d_params", cat="offload"):
                self._compute_params = self._sharded_gather(lowp)
                # drain inside the span: gather/put only enqueue, and a
                # dispatch-only h2d_s (JL006 class) would make the bench
                # A/B's serial leg look free.  The next dispatch gates
                # on these params anyway — this moves the wait, not adds
                # one.
                jax.block_until_ready(self._compute_params)
            self._record_offload_overlap([], t0, t1,
                                         time.perf_counter())
            return
        # host_adam covers the grad D2H pulls too (the optimizer's
        # prefetch puller overlaps them with the C++ Adam); per-leaf
        # transfer spans come from offload.set_transfer_tracer
        with self._tel_span("offload/host_adam", cat="offload"):
            lowp = self._host_opt.step(grads)
        t1 = time.perf_counter()
        with self._tel_span("offload/h2d_params", cat="offload"):
            self._compute_params = _device_put_tree(
                lowp, self._compute_shardings)
            # honest h2d_s for the serial reference leg (see the
            # sharded branch above)
            jax.block_until_ready(self._compute_params)
        self._record_offload_overlap([], t0, t1, time.perf_counter())

    def _apply_host_update_pipelined(self, grads):
        """Streaming offload update (the ZeRO-Offload overlap completed
        for the H2D direction): while CPU-Adam updates leaf i, leaf
        i+1's gradient D2H is in flight (``_PrefetchPuller``) AND leaf
        i-1's updated low-precision copy is already uploading
        (``StreamingUploader``).  ``_compute_params`` is swapped only
        after EVERY upload resolves — a mid-pipeline failure poisons the
        optimizer and leaves the old compute tree fully intact (never
        half-swapped).  Composes with DPU: a flush during step t+1's
        dispatch window streams its uploads under the already-running
        device fwd/bwd as well."""
        from . import offload as offload_mod
        sharded = getattr(self, "_offload_sharded", False)
        if sharded:
            put = self._host_opt.upload_block
        else:
            shard_leaves = self._compute_shard_leaves
            put = lambda i, a: offload_mod.device_put_leaf(  # noqa: E731
                a, shard_leaves[i])
        # stashed on the engine mid-step so the stage graph's close()
        # entry can abort the in-flight uploads (cleared on every exit)
        up = self._active_uploader = offload_mod.StreamingUploader(
            put, stage=getattr(self, "_stage_records",
                               {}).get("offload_h2d"))
        t0 = time.perf_counter()
        try:
            try:
                with self._tel_span("offload/host_adam", cat="offload",
                                    pipelined=True):
                    if sharded:
                        if isinstance(grads, _HostBlockStash):
                            # DPU stash — tagged, never sniffed (see the
                            # serial path)
                            self._host_opt.step_local(grads.blocks,
                                                      on_leaf=up.submit)
                        else:
                            self._host_opt.step(
                                self._reshard_to_master(grads),
                                on_leaf=up.submit)
                    else:
                        self._host_opt.step(grads, on_leaf=up.submit)
            except BaseException:
                # Adam-side failure: the optimizer poisoned itself;
                # release the worker without waiting on queued transfers
                up.abort()
                raise
            t1 = time.perf_counter()
            try:
                # the exposed tail: whatever transfer time did NOT hide
                # under the Adam loop above
                with self._tel_span("offload/h2d_tail", cat="offload"):
                    results, timings = up.finish()
            except BaseException as e:
                # Adam done but an upload failed (or a concurrent close
                # aborted it — UploadAborted): master carries step t,
                # device would keep t-1 — poison so the mismatch can
                # neither train nor serialize.  _compute_params was
                # never touched (still the old tree).
                self._host_opt.poison(e)
                raise
        finally:
            self._active_uploader = None
        if sharded:
            n = len(self._host_opt._flat_groups)
            assert len(results) == n, (len(results), n)
            self._compute_params = self._sharded_gather(
                self._host_opt.assemble_uploaded(
                    [results[i] for i in range(n)]))
        else:
            n = len(self._compute_shard_leaves)
            assert len(results) == n, (len(results), n)
            self._compute_params = jax.tree.unflatten(
                self._compute_treedef, [results[i] for i in range(n)])
        self._record_offload_overlap(timings, t0, t1,
                                     time.perf_counter())

    def _record_offload_overlap(self, timings, adam_start, adam_end, end):
        """Per-step pipeline accounting from host timestamps: how much
        of the H2D transfer time hid under the Adam window.  Feeds
        ``last_offload_breakdown`` (bench A/B), the
        ``offload_overlap_ratio`` gauge, and the periodic sync scalars.
        Serial path passes no timings — its upload is all tail."""
        h2d = sum(t1 - t0 for _, t0, t1, _ in timings)
        hidden = sum(max(0.0, min(t1, adam_end) - max(t0, adam_start))
                     for _, t0, t1, _ in timings)
        ratio = (hidden / h2d) if h2d > 0 else 0.0
        self.last_offload_breakdown = {
            "pipelined": bool(timings) or bool(
                getattr(self, "_offload_pipeline", False)),
            "d2h_s": float(getattr(self._host_opt, "last_d2h_seconds",
                                   0.0) or 0.0),
            "cpu_adam_s": adam_end - adam_start,
            "h2d_s": h2d if timings else end - adam_end,
            "h2d_hidden_s": hidden,
            "h2d_tail_s": end - adam_end,
            "overlap_ratio": ratio,
        }
        disk = getattr(self._host_opt, "last_disk_breakdown", None)
        if disk is not None:
            # disk tier (runtime/disk_offload.py): fold the state-I/O
            # breakdown in next to the H2D numbers — one dict is the
            # bench A/B's whole story
            self.last_offload_breakdown.update(disk)
            dacc = getattr(self, "_disk_interval_acc", None)
            if dacc is None:
                dacc = self._disk_interval_acc = {
                    "read": 0.0, "write": 0.0, "hidden": 0.0, "steps": 0}
            dacc["read"] += disk["disk_read_s"]
            dacc["write"] += disk["disk_write_s"]
            dacc["hidden"] += disk["disk_hidden_s"]
            dacc["steps"] += 1
            if self.telemetry is not None:
                self.telemetry.registry.gauge(
                    "offload_disk_overlap_ratio",
                    "fraction of disk-tier state I/O time hidden under "
                    "the host Adam (three-tier pipeline; serial loop "
                    "= 0)").set(disk["disk_overlap_ratio"])
                self.telemetry.registry.counter(
                    "disk_bytes_read_total",
                    "optimizer/master state bytes read from the disk "
                    "tier").inc(disk["disk_bytes_read"])
                self.telemetry.registry.counter(
                    "disk_bytes_written_total",
                    "optimizer/master state bytes written back to the "
                    "disk tier").inc(disk["disk_bytes_written"])
        # interval accumulators: the sync scalar must aggregate EVERY
        # step in the steps_per_print window, not snapshot the last one
        # (a checkpoint-adjacent straggler step would misrepresent the
        # whole interval in summarize)
        acc = getattr(self, "_offload_interval_acc", None)
        if acc is None:
            acc = self._offload_interval_acc = {
                "h2d": 0.0, "hidden": 0.0, "cpu_adam": 0.0, "steps": 0}
        acc["h2d"] += self.last_offload_breakdown["h2d_s"]
        acc["hidden"] += hidden
        acc["cpu_adam"] += self.last_offload_breakdown["cpu_adam_s"]
        acc["steps"] += 1
        if self.telemetry is not None:
            self.telemetry.registry.gauge(
                "offload_overlap_ratio",
                "fraction of offload H2D param-upload time hidden under "
                "the host Adam (streaming pipeline; serial path = 0)",
            ).set(ratio)

    def _dpu_flush(self):
        """Apply a pending delayed update (checkpoint save, eval, and
        state sync must see the fully-applied master)."""
        pending = getattr(self, "_dpu_pending", None)
        if pending is not None:
            self._dpu_pending = None
            self._apply_host_update(pending)

    def _xla_dpu_flush(self):
        """xla-tier analogue: run the deferred update program so
        engine.state reflects every gradient computed so far."""
        pending = getattr(self, "_xla_dpu_pending", None)
        if pending is not None and self._xla_dpu_update is not None:
            self._xla_dpu_pending = None
            # scope: a flush can be the FIRST dispatch of the update
            # program (save right after a DPU step) — tracing needs the
            # ambient mesh like every other compiled step
            with self._pallas_scope():
                self.state, _ = self._xla_dpu_update(self.state, *pending)

    def _train_batch_offload(self, batch):
        scaler = self.state.scaler
        step_rng = jax.random.fold_in(self.state.rng,
                                      int(self.state.global_steps))
        with self._pallas_scope():
            grads, loss, finite, grad_norm = self._grad_step(
                self._compute_params, batch, scaler.loss_scale, step_rng)
        if self._dpu:
            # Delayed parameter update (ZeRO-Offload paper's DPU; the
            # reference repo gained it after v0.3.2): step t's device
            # fwd/bwd is ALREADY dispatched above on one-step-stale
            # params — running step t-1's C++ Adam now overlaps it for
            # real (device crunches in the background while this Python
            # thread drives the OpenMP kernel).  finite(t-1) was
            # resolved at the end of the previous call, so loss-scale
            # semantics are exact; only the weight application lags one
            # step.
            self._dpu_flush()
            finite_b = bool(finite)  # syncs: step t's compute done
            if finite_b:
                # stash HOST copies: keeping the jax arrays would pin a
                # full device gradient tree alive across the next step
                # (one extra grad tree of peak HBM — the opposite of
                # offload's point).  Small leaves' async D2H is in
                # flight, large leaves stream piece-wise — and every pull
                # is watchdogged (dtype-preserving, so the stash stays at
                # 1x the grads' bytes) so a link that degrades
                # mid-training fails cleanly.  Sharded tier: each process
                # stashes only its dedup'd dp-shard blocks.
                if getattr(self, "_offload_sharded", False):
                    with self._tel_span("offload/d2h_grads",
                                        cat="offload"):
                        self._dpu_pending = _HostBlockStash(
                            self._host_opt.pull_local(
                                self._reshard_to_master(grads)))
                else:
                    self._start_small_leaf_d2h(grads)
                    from .offload import guarded_tree_pull
                    with self._tel_span("offload/d2h_grads",
                                        cat="offload"):
                        self._dpu_pending = guarded_tree_pull(grads)
        else:
            finite_b = bool(finite)
            if finite_b:
                # Device → host staging overlapped with the host Adam:
                # start EVERY leaf's D2H transfer asynchronously, then
                # hand the jax arrays straight to the optimizer — its
                # per-leaf np.asarray blocks only for that leaf while
                # later leaves stream behind the C++ Adam of earlier ones
                # (the reference's pinned-tile double buffering,
                # csrc/adam/cpu_adam.cpp:64-113, done by the transfer
                # engine instead of hand-rolled buffers).
                # Single-controller: this host assembles the FULL gradient
                # and owns the full master (host RAM is the resource
                # offload spends; HBM is what it frees).
                self._start_small_leaf_d2h(grads)
                self._apply_host_update(grads)
        new_scaler = precision.update_scale(
            scaler, jnp.asarray(finite_b), self.loss_scale_config)
        self.state = TrainState(
            master_params=self._host_opt.master,
            opt_state=self._host_opt.state_tree(),
            scaler=new_scaler,
            global_steps=self.state.global_steps + 1,
            skipped_steps=self.state.skipped_steps
            + (0 if finite_b else 1),
            rng=self.state.rng,
        )
        applied = self._host_opt.opt.step_count
        lr = (self._lr_schedule(jnp.asarray(applied))
              if self._lr_schedule is not None
              else self.config.optimizer_params.get("lr", 1e-3))
        return StepMetrics(
            loss=np.asarray(loss), grad_norm=np.asarray(grad_norm),
            loss_scale=np.asarray(scaler.loss_scale),
            overflow=np.asarray(not finite_b),
            lr=np.asarray(lr, np.float32))

    # --- canonical (tree-form) state for checkpointing -----------------
    # The XLA offload tier stores master/moments as flat host vectors; the
    # checkpoint keeps the logical per-parameter tree so a checkpoint saved
    # with offload loads into a non-offload engine (and vice versa) — the
    # analogue of the reference's merge/re-partition elastic restore
    # (stage2.py:1712-1778).
    @property
    def _offload_xla(self) -> bool:
        return self._offload and not self._offload_host

    def _canonical_state(self):
        """(master, opt_state) in per-parameter tree form, for saving.
        The optimizer plane is ALWAYS a FusedAdamState(count, mu, nu)
        pytree regardless of tier — the one canonical shape is what lets
        a checkpoint saved by any tier (plain device, xla offload, host
        offload, sharded host offload) restore into any other."""
        if self._offload_xla:
            opt = self.state.opt_state
            return (self._unflatten_numpy(self.state.master_params),
                    FusedAdamState(count=opt.count,
                                   mu=self._unflatten_numpy(opt.mu),
                                   nu=self._unflatten_numpy(opt.nu)))
        if getattr(self, "_offload_sharded", False):
            # global (non-fully-addressable) fp32 arrays: the saver
            # writes per-process shard files and merges on load
            master, opt = self._host_opt.canonical_state()
            return master, FusedAdamState(
                count=np.asarray(opt["step"], np.int64),
                mu=opt["mu"], nu=opt["nu"])
        if self._offload_host:
            # Route through state_tree(), which refuses while poisoned:
            # self.state.opt_state's mu/nu are live views of the native
            # Adam buffers, so after a mid-step pull failure they hold
            # partially-updated values even though self.state itself was
            # never advanced.  Reading them directly would let
            # save_checkpoint serialize exactly the inconsistency the
            # poison guard exists to fence off.
            opt = self._host_opt.state_tree()
            return self.state.master_params, FusedAdamState(
                count=np.asarray(opt["step"], np.int64),
                mu=opt["mu"], nu=opt["nu"])
        return self.state.master_params, self.state.opt_state

    def _canonical_templates(self):
        """Shape/dtype templates matching the saved (tree) form; numpy
        broadcast views so no device or host memory is allocated."""
        if self._offload_xla:
            def tmpl():
                leaves = [np.broadcast_to(np.zeros((), np.float32), s)
                          for s in self._flat_shapes]
                return jax.tree.unflatten(self._flat_treedef, leaves)
            return tmpl(), FusedAdamState(
                count=self.state.opt_state.count, mu=tmpl(), nu=tmpl())
        if getattr(self, "_offload_sharded", False):
            master, opt = self._host_opt.canonical_templates()
            return master, FusedAdamState(
                count=np.asarray(opt["step"], np.int64),
                mu=opt["mu"], nu=opt["nu"])
        if self._offload_host:
            opt = self.state.opt_state
            return self.state.master_params, FusedAdamState(
                count=np.asarray(opt["step"], np.int64),
                mu=opt["mu"], nu=opt["nu"])
        return self.state.master_params, self.state.opt_state

    def _adopt_loaded(self, master_tree, opt_tree):
        """Convert loaded canonical trees to the engine's internal form."""
        if not self._offload_xla:
            return master_tree, opt_tree
        self._xla_dpu_pending = None  # loaded state supersedes pending
        # NOTE: the DPU dispatch counter is NOT seeded here — opt.count
        # counts only applied (finite) steps, and seeding from it would
        # replay the dropout seeds consumed by overflow-skipped steps
        # before the save.  load_checkpoint seeds it from global_steps
        # (total dispatches after a flush, including skips).
        dev = NamedSharding(self.mesh, P())

        def put_pieces(tree):
            return tuple(jax.device_put(p, self._piece_host_sharding)
                         for p in self._flatten_numpy(tree))

        flat_master = put_pieces(master_tree)
        if opt_tree is None:
            opt = FusedAdamState(
                count=jax.device_put(jnp.zeros([], jnp.int32), dev),
                mu=self._zero_host_pieces(), nu=self._zero_host_pieces())
        else:
            opt = FusedAdamState(
                count=jax.device_put(
                    jnp.asarray(opt_tree.count, jnp.int32), dev),
                mu=put_pieces(opt_tree.mu),
                nu=put_pieces(opt_tree.nu))
        return flat_master, opt

    def _sync_offload_from_state(self):
        """After a checkpoint load replaced engine.state with device/loaded
        arrays: copy them back into the host buffers (identity-preserving)
        and refresh the device compute params."""
        self._dpu_pending = None  # loaded state supersedes any pending
        opt_tree = self.state.opt_state
        if isinstance(opt_tree, FusedAdamState):
            # canonical (cross-tier) form — a checkpoint saved by any
            # tier, incl. plain device engines, restores here
            opt_tree = {"step": opt_tree.count,
                        "mu": opt_tree.mu, "nu": opt_tree.nu}
        elif not (isinstance(opt_tree, dict) and "mu" in opt_tree):
            # module-only restore path: fresh moments (the loader built a
            # device optimizer state that doesn't apply to the host tier)
            opt_tree = None
        if getattr(self, "_offload_sharded", False):
            # each process scatters only its addressable shards back into
            # its host blocks; compute params re-gather on device
            self._host_opt.load_state_tree(self.state.master_params,
                                           opt_tree)
            self._compute_params = self._sharded_gather(
                self._host_opt.compute_params())
            self.state = self.state._replace(
                master_params=self._host_opt.master,
                opt_state=self._host_opt.state_tree())
            return
        if getattr(self, "_offload_disk", False):
            # disk tier: rewrite every leaf file from the loaded trees
            # (opt_tree None = fresh moments + step 0, the module-only
            # restore) — also what heals a torn write-back
            self._host_opt.load_state_tree(self.state.master_params,
                                           opt_tree)
            self._compute_params = _device_put_tree(
                self._host_opt.compute_params(), self._compute_shardings)
            self.state = self.state._replace(
                master_params=self._host_opt.master,
                opt_state=self._host_opt.state_tree())
            return
        if opt_tree is None:
            def copy_into(dst, src):
                arr = np.asarray(jax.device_get(src))
                dst[...] = arr.astype(dst.dtype) if arr.dtype != dst.dtype \
                    else arr
            jax.tree.map(copy_into, self._host_opt.master,
                         self.state.master_params)
            for m, v in self._host_opt.opt._state.values():
                m[...] = 0.0
                v[...] = 0.0
            # Adam restarts at t=1: stale step_count with zeroed moments
            # would mis-apply bias correction (c1≈1 against m≈0) and resume
            # lr schedules mid-curve
            self._host_opt.opt.step_count = 0
        else:
            self._host_opt.load_state_tree(self.state.master_params,
                                           opt_tree)
        self._compute_params = _device_put_tree(
            self._host_opt.compute_params(), self._compute_shardings)
        self.state = self.state._replace(
            master_params=self._host_opt.master,
            opt_state=self._host_opt.state_tree())

    # ------------------------------------------------------------------
    # data plumbing
    # ------------------------------------------------------------------
    def deepspeed_io(self, dataset, batch_size=None, collate_fn=None):
        from .dataloader import DeepSpeedDataLoader
        return DeepSpeedDataLoader(
            dataset,
            batch_size=batch_size or self.train_batch_size,
            collate_fn=collate_fn,
            mesh=self.mesh)

    def _batch_leading_reshape(self, x: np.ndarray) -> np.ndarray:
        """[train_batch/nproc, ...] → [grad_acc, micro_rows, ...] (the
        engine's accumulation-scan layout).  Multi-host: each process feeds
        its OWN slice of the global batch (the reference's
        DistributedSampler contract, dataloader.py:48-58 there), so the
        expected leading dim divides by process_count.  The pipeline
        engine overrides this — it's the only part of batch placement that
        differs there."""
        ga, mb = self.gradient_accumulation_steps, self.micro_batch_size
        nproc = jax.process_count()
        micro_global = mb * self.dp_world_size
        expect = ga * micro_global // nproc
        if x.shape[0] != expect:
            raise ValueError(
                f"batch dim {x.shape[0]} != train_batch_size"
                f"{'/process_count' if nproc > 1 else ''} {expect} "
                f"(grad_acc {ga} × micro {mb} × dp {self.dp_world_size}"
                f"{f' ÷ {nproc} processes' if nproc > 1 else ''})")
        return x.reshape((ga, micro_global // nproc) + x.shape[1:])

    def _shard_batch(self, batch):
        """Place a global batch as [leading, samples, ...] sharded over the
        data axis on dim 1.  Multi-host: every process contributes its
        local rows via ``make_array_from_process_local_data`` — no process
        ever materializes the global batch (reference: per-rank
        DistributedSampler slices, dataloader.py:48-58)."""
        # leaves already on device stay there: np.asarray on a jax.Array
        # is a D2H pull (a full tunnel round trip on remote platforms) and
        # the reshape/device_put below are device ops / no-ops for a
        # correctly-placed array.  Callers can device_put a repeating
        # batch ONCE and pay zero per-step transfer.
        batch = jax.tree.map(
            lambda x: self._batch_leading_reshape(
                x if isinstance(x, jax.Array) else np.asarray(x)), batch)
        nproc = jax.process_count()

        def sharding_of(x):
            spec = [None] * x.ndim
            spec[1] = DATA_AXIS
            return NamedSharding(self.mesh, P(*spec))

        if nproc > 1:
            def shard(x):
                sharding = sharding_of(x)
                if isinstance(x, jax.Array):
                    if x.sharding == sharding:
                        return x  # already assembled for this mesh
                    raise ValueError(
                        "multi-process _shard_batch needs process-local "
                        "numpy leaves (each process contributes its own "
                        f"rows); got a jax.Array with sharding {x.sharding}"
                        " — pass the local slice instead")
                return jax.make_array_from_process_local_data(
                    sharding, np.asarray(x))

            return jax.tree.map(shard, batch)

        # single-process: ONE batched list-form jax.device_put for all
        # numpy leaves (mirrors offload._batched_device_put_pairs) —
        # a multi-leaf batch must not pay one client round trip per
        # leaf on a remote platform.  jax.Array leaves pass through a
        # per-leaf put (a no-op for a correctly-placed array).
        leaves, treedef = jax.tree.flatten(batch)
        out = [None] * len(leaves)
        np_idx, np_arrs, np_shs = [], [], []
        for i, x in enumerate(leaves):
            sharding = sharding_of(x)
            if isinstance(x, jax.Array):
                out[i] = jax.device_put(x, sharding)
            else:
                np_idx.append(i)
                np_arrs.append(x)
                np_shs.append(sharding)
        if np_arrs:
            # shardings are valid device_put destinations, so the
            # offload tier's one-batched-call-with-fallback helper is
            # the single implementation here too
            from .offload import _batched_device_put_pairs
            for i, p in zip(np_idx,
                            _batched_device_put_pairs(np_arrs, np_shs)):
                out[i] = p
        return jax.tree.unflatten(treedef, out)

    # ------------------------------------------------------------------
    # public training API
    # ------------------------------------------------------------------
    def train_batch(self, batch=None, data_iter=None):
        """Run one full training step (grad-accum included) on a global
        batch of ``train_batch_size`` samples."""
        # _in_step fences the SIGTERM preemption hook: a signal landing
        # while the update is mid-flight (host-offload CPU-Adam loop,
        # streaming uploads) must not snapshot a torn half-applied state
        # — the handler defers to this step's boundary instead (the
        # finally below runs the deferred save)
        self._in_step = True
        try:
            return self._train_batch_inner(batch, data_iter)
        except BaseException as e:
            # a failing step (a poisoned stage re-raising its original
            # exception, a donation fault, ...) dumps the fault plane's
            # recent history ONCE for post-mortem; StopIteration is
            # ordinary epoch-end control flow, never a failure
            if not isinstance(e, StopIteration) \
                    and not self._flightrec_poison_dumped:
                self._flightrec_poison_dumped = True
                self.dump_flight_record(reason="train_batch failure",
                                        error=e)
            raise
        finally:
            self._in_step = False
            h = self._deferred_preempt
            if h is not None:
                self._deferred_preempt = None
                h.complete_deferred()

    def _train_batch_inner(self, batch=None, data_iter=None):
        if self._fatal_state_error is not None:
            raise RuntimeError(self._fatal_state_error)
        self._ckpt_writer_tick()
        if batch is None:
            it = data_iter or self._training_iter()
            if it is None:
                raise ValueError("train_batch needs a batch or a data_iter")
            if isinstance(it, DevicePrefetcher) \
                    and self._train_prefetcher is not it:
                # transparently adopt a caller-built prefetcher: its
                # stats feed the periodic telemetry sync and engine
                # close() shuts its worker down
                self._bind_train_prefetcher(it)
            # a DevicePrefetcher stamps its data/prefetch_wait span here
            batch = next(it)
        t0 = time.time()
        placed = batch if isinstance(batch, DevicePlacedBatch) else None
        if placed is not None and placed.kind != "train":
            raise ValueError(
                f"train_batch received a {placed.kind!r}-placed batch "
                "(flat micro-batch layout); it needs the train placement "
                "— build the prefetcher with engine.prefetch(it) (not "
                "for_eval=True)")
        if self.progressive_layer_drop is not None:
            if placed is not None:
                # prefetched batches carry a PLACEHOLDER theta leaf (they
                # were placed ahead of time, before global_steps advanced
                # to this step) — overwrite it at consumption so the
                # schedule reads the CURRENT step
                placed = self._pld_theta_overwrite(placed)
            elif isinstance(batch, dict):
                # inject PLD state as batch leaves (the reference injects
                # model kwargs, engine.py:787-788); the theta array
                # updates per step without retracing
                self.progressive_layer_drop.update_state(self.global_steps)
                batch = dict(batch)
                batch["pld_theta"] = np.full(
                    (len(next(iter(batch.values()))),),
                    self.progressive_layer_drop.get_theta(), np.float32)
        if self.timers is not None:
            self.timers("train_batch_data").start()
        self._profiler_window_tick()
        # telemetry spans are HOST-side stamps (time.perf_counter + a
        # list append): a dispatch span measures enqueue latency, and the
        # periodic on_sync below emits the synced ground truth — zero
        # device syncs are added per step (the acceptance contract
        # tests/test_telemetry.py::test_train_batch_adds_zero_device_syncs)
        with self._tel_span("train/shard_batch", cat="data",
                            prefetched=placed is not None):
            sharded = (placed.tree if placed is not None
                       else self._shard_batch(batch))
        if self._pg_check_pending:
            # first-step sweep, before any update mutates the state
            self._pg_check_pending = False
            self._run_pg_correctness(sharded)
        if self.timers is not None:
            self.timers("train_batch_data").stop()
            self.timers("train_batch_step").start()
        # step arg uses the POST-increment number so the span correlates
        # with record_step / on_sync / the report line for the same batch
        with self._tel_span("train/dispatch", cat="train",
                            step=self.global_steps + 1):
            # causal arrow: terminate the prefetched batch's flow INSIDE
            # the consuming step's span — trace.json then links the
            # worker's data/prefetch_place span to this train/step (a
            # host-side append; the zero-added-device-syncs contract
            # holds, test_train_batch_adds_zero_device_syncs)
            if placed is not None and placed.ctx is not None \
                    and self.telemetry is not None \
                    and self.telemetry.tracer is not None:
                self.telemetry.tracer.flow_end(
                    "data/batch", placed.ctx, cat="data",
                    step=self.global_steps + 1)
            if self._offload_host:
                metrics = self._train_batch_offload(sharded)
                self._last_metrics = metrics
                loss_out = metrics.loss
            else:
                step_fn = self._train_step if self._onebit_steps is None \
                    else self._select_onebit_step()
                with self._pallas_scope():
                    self.state, packed = step_fn(self.state, sharded)
                # NO host sync here: every np.asarray is a full round-trip
                # (expensive through the axon tunnel) and a serialization
                # point.  The packed metrics vector stays on device; steps
                # queue back-to-back and the transfer latency overlaps with
                # compute.  ``last_metrics`` materializes on demand, and the
                # steps_per_print report is the periodic sync (the reference
                # likewise returns the live loss tensor, engine.py:818).
                self._last_packed = packed
                self._last_metrics = None
                loss_out = packed[0]
        if self.timers is not None:
            # materializing the metrics is the device sync
            _ = self.last_metrics
            self.timers("train_batch_step").stop()
        self.global_steps += 1
        self.micro_steps += self.gradient_accumulation_steps
        # dispatch-only delta by design: _step_times records enqueue
        # latency (syncing here would serialize the async-dispatch
        # overlap); the synced ground truth comes from _report's
        # report-interval wall time and telemetry's on_sync step-time
        # histogram — see docs/observability.md
        # jaxlint: disable=JL006
        dispatch_s = time.time() - t0
        self._step_times.append(dispatch_s)
        if self._heartbeat is not None:
            # per-host liveness beat (atomic small-file write; step_s is
            # the wall delta between beats — the fleet-relative number
            # the straggler monitor medians, so dispatch-only timing is
            # fine here: every host's beats bracket the same queue)
            self._heartbeat.beat(self.global_steps)
            if self.telemetry is not None:
                self.telemetry.registry.gauge(
                    "heartbeat_step",
                    "last step this process heartbeat for (elastic "
                    "liveness)").set(self.global_steps)
        if self.telemetry is not None:
            self.telemetry.record_step(self.global_steps, dispatch_s,
                                       samples=int(self.train_batch_size))
        if self.summary_writer is not None:
            # buffer the (device) packed metrics; materializing per step
            # would force a full device sync every step and negate the
            # async-dispatch overlap (advisor finding, round 1) — the
            # flush below rides the steps_per_print sync instead
            self._tb_pending.append(
                (self.global_steps,
                 self._last_packed if self._last_metrics is None
                 else self._last_metrics))
            if len(self._tb_pending) >= 1000:
                # bound the buffer for huge steps_per_print settings
                self._flush_tensorboard()
        if self.global_steps % self.config.steps_per_print == 0:
            if self.timers is not None:
                self.timers.log(["train_batch_data", "train_batch_step"])
            # interval bookkeeping BEFORE _report (which resets it): the
            # telemetry sync reuses the same synced wall-clock window
            prev_t = getattr(self, "_last_report", None)
            prev_step = getattr(self, "_last_report_step", 0)
            self._report(self.last_metrics)
            self._flush_tensorboard()
            if self.telemetry is not None:
                self._telemetry_sync(prev_t, prev_step)
        return loss_out

    def _telemetry_sync(self, prev_t, prev_step):
        """Telemetry's periodic drain, riding the steps_per_print sync
        that ``_report``'s metrics materialization already paid for:
        synced step-time histogram, memory gauges, compile samples,
        exporter flushes.  The first interval has no synced baseline
        (prev_t is None) and records no step-time sample — dispatch
        times would inflate samples/sec by orders of magnitude
        (engine._report's rule)."""
        m = self.last_metrics
        steps = self.global_steps - prev_step
        interval = (self._last_report - prev_t) if prev_t is not None \
            else None
        # anomaly check FIRST: it also closes a previous trigger's
        # bounded capture, and it must run BEFORE the straggler block
        # below — a straggler-arm _fire_anomaly later in THIS sync would
        # otherwise open a capture this same sync immediately stops,
        # recording an empty window (and the one-shot is then spent)
        self._anomaly_check(interval / steps
                            if interval is not None and steps else None)
        scalars = {}
        if m is not None:
            scalars = {"loss": float(m.loss),
                       "grad_norm": float(m.grad_norm),
                       "loss_scale": float(m.loss_scale),
                       "lr": float(m.lr)}
        acc = getattr(self, "_offload_interval_acc", None)
        if acc is not None and acc["steps"]:
            # the pipeline's headline number, aggregated over the WHOLE
            # interval (hidden/h2d sums, per-step means) — summarize's
            # step-count weighting is then exact
            scalars["offload_overlap_ratio"] = (
                acc["hidden"] / acc["h2d"] if acc["h2d"] > 0 else 0.0)
            scalars["offload_h2d_s"] = acc["h2d"] / acc["steps"]
            scalars["offload_cpu_adam_s"] = acc["cpu_adam"] / acc["steps"]
            acc.update(h2d=0.0, hidden=0.0, cpu_adam=0.0, steps=0)
        dacc = getattr(self, "_disk_interval_acc", None)
        if dacc is not None and dacc["steps"]:
            # disk tier: interval-aggregated state-I/O overlap (the
            # summarize "disk tier" row) + per-step read/write seconds
            io = dacc["read"] + dacc["write"]
            scalars["offload_disk_overlap_ratio"] = (
                dacc["hidden"] / io if io > 0 else 0.0)
            scalars["disk_read_s"] = dacc["read"] / dacc["steps"]
            scalars["disk_write_s"] = dacc["write"] / dacc["steps"]
            dacc.update(read=0.0, write=0.0, hidden=0.0, steps=0)
        ca = getattr(self, "_ckpt_interval_acc", None)
        if ca is not None and ca["saves"]:
            # exposed per-save stall (sync: the whole serialize; async:
            # just the snapshot D2H) and the background write time the
            # async path hid — the pair summarize reports as the
            # checkpoint row (docs/checkpointing.md).  Read-and-reset
            # under the acc lock: the writer thread adds overlap_s as
            # its saves land
            with self._ckpt_acc_lock:
                scalars["ckpt_save_s"] = ca["save_s"] / ca["saves"]
                if ca["overlap_s"] > 0:
                    # per WRITTEN save (coalesced submissions never
                    # wrote) — the same denominator bench.py uses
                    scalars["ckpt_async_overlap_s"] = (
                        ca["overlap_s"] / max(ca.get("writes", 0), 1))
                ca.update(save_s=0.0, overlap_s=0.0, saves=0, writes=0)
        pf = getattr(self, "_train_prefetcher", None)
        if pf is not None:
            # interval delta over the prefetcher's cumulative stats: the
            # hit ratio (batch already resident when the step asked) and
            # the mean blocked wait per consumed batch — the input
            # pipeline's hidden-vs-exposed numbers (docs/observability.md)
            s = pf.stats()
            prev = self._prefetch_prev_stats or {
                "hits": 0, "misses": 0, "wait_s": 0.0}
            self._prefetch_prev_stats = s
            n = (s["hits"] - prev["hits"]) + (s["misses"] - prev["misses"])
            if n > 0:
                hit_ratio = (s["hits"] - prev["hits"]) / n
                scalars["prefetch_hit_ratio"] = hit_ratio
                scalars["prefetch_wait_s"] = (
                    (s["wait_s"] - prev["wait_s"]) / n)
                self.telemetry.registry.gauge(
                    "data_prefetch_hit_ratio",
                    "fraction of consumed batches already device-"
                    "resident when requested (async input pipeline)",
                ).set(hit_ratio)
            self.telemetry.registry.gauge(
                "data_prefetch_queue_depth",
                "batches staged ahead in the input-prefetch queue",
            ).set(pf.qsize())
        if self._straggler_monitor is not None \
                and self._heartbeat is not None:
            # fleet health from the shared heartbeat dir: flag hosts
            # whose step time exceeds straggler_ratio × the fleet
            # median; detections count ONCE per flagged episode
            from ..telemetry.heartbeat import beat_ages, read_heartbeats
            beats = read_heartbeats(self._heartbeat.directory)
            # supervisor-visible staleness, made operator-visible: one
            # heartbeat_age_s gauge per host (the summarize liveness row
            # reads these from the metrics snapshots)
            age_gauge = self.telemetry.registry.gauge(
                "heartbeat_age_s",
                "seconds since each host's last heartbeat (elastic "
                "liveness; stale = hung host)")
            for key, age in beat_ages(beats).items():
                age_gauge.set(age, host=key)
            rep = self._straggler_monitor.update(beats)
            if rep["new_stragglers"]:
                self.telemetry.registry.counter(
                    "straggler_detected_total",
                    "hosts flagged slower than straggler_ratio x the "
                    "fleet median step time").inc(
                    len(rep["new_stragglers"]))
                logger.warning(
                    "straggler(s) detected: %s (fleet median %.3fs/step, "
                    "ratio %.1fx)", ", ".join(rep["new_stragglers"]),
                    rep["median_step_s"] or 0.0,
                    self._straggler_monitor.ratio)
                self_key = (f"{self._heartbeat.host}/"
                            f"{self._heartbeat.process_index}")
                if self_key in rep["new_stragglers"]:
                    # the anomaly trigger's straggler arm: THIS host is
                    # the slow one — capture it while it is still slow
                    self._fire_anomaly(
                        f"this host flagged as straggler ({self_key})")
            scalars["straggler_detected_total"] = float(
                self._straggler_monitor.flagged_total)
        self.telemetry.on_sync(
            self.global_steps,
            interval_s=interval,
            steps=steps if interval is not None else None,
            samples_per_step=int(self.train_batch_size),
            scalars=scalars)

    def _flush_tensorboard(self):
        if self.summary_writer is None or not self._tb_pending:
            return
        # in-place drain: the GC finalizer holds this SAME list object,
        # so rebinding here would desynchronize the two paths
        _drain_tb_pending(self._tb_pending, self.summary_writer)

    def _training_iter(self):
        """Persistent iterator over the training dataloader (a fresh
        ``iter()`` per call would replay batch 0 forever).  When the
        ``data_prefetch`` block is enabled (the default) the iterator is
        wrapped in a :class:`DevicePrefetcher`, so collate + batch
        sharding run on a daemon worker ahead of consumption and
        ``train_batch`` receives already-device-resident pytrees."""
        if self.training_dataloader is None:
            return None
        if getattr(self, "_train_data_iter", None) is None:
            loader = self.training_dataloader
            if self._prefetch_enabled:
                # wrap the LOADER OBJECT, not a pre-made iterator: the
                # prefetcher iterates it itself and keeps access to its
                # state_dict for sample-exact resume (docs/elastic.md)
                it = self.prefetch(loader)
                self._bind_train_prefetcher(it)
            else:
                it = (loader if hasattr(loader, "__next__")
                      else iter(loader))
            self._train_data_iter = it
        return self._train_data_iter

    # ------------------------------------------------------------------
    # data-iterator checkpoint plane (sample-exact resume; docs/elastic.md)
    # ------------------------------------------------------------------
    def data_iterator_state(self):
        """JSON-able state of the training data iterator at the current
        CONSUMPTION point, or None when no checkpointable iterator is
        bound.  The prefetcher path accounts batches staged ahead in its
        queue as not-yet-consumed (they re-produce on resume), so the
        state always names the exact next sample ``train_batch`` would
        see.  ``save_checkpoint`` persists this as the checkpoint's
        data-iterator plane."""
        from .dataloader import supports_iter_state
        pf = getattr(self, "_train_prefetcher", None)
        if pf is not None and not pf.closed:
            try:
                return pf.state_dict()
            except TypeError:
                # caller wrapped a raw iterator: the loader's own state
                # would reflect PRODUCTION (in-flight prefetched batches
                # counted as consumed) — refusing beats silently skipping
                # up to `depth` batches on resume
                return None
        for cand in (getattr(self, "_train_data_iter", None),
                     self.training_dataloader):
            if cand is not None and supports_iter_state(cand) \
                    and not isinstance(cand, DevicePrefetcher):
                try:
                    return cand.state_dict()
                except TypeError:
                    # RepeatingLoader over a raw iterable: quacks the
                    # protocol but can't honor it — save no data plane
                    # (the checkpoint stays loadable, resume replays)
                    return None
        return None

    def load_data_iterator_state(self, state) -> bool:
        """Apply a checkpointed iterator state to this engine's training
        dataloader and drop the live iterator chain so the next
        ``train_batch`` rebuilds it from the restored position.  The
        raw state is always stashed as ``last_loaded_data_iter_state``
        so callers driving their own ``data_iter`` chain can apply it to
        their loader manually.  Returns True when auto-applied."""
        from .dataloader import supports_iter_state
        self.last_loaded_data_iter_state = state
        loader = self.training_dataloader
        if loader is None or not supports_iter_state(loader):
            logger.warning(
                "checkpoint has a data-iterator plane but this engine "
                "has no checkpointable training dataloader to apply it "
                "to (training_data not passed / custom iterator): the "
                "state is stashed as engine.last_loaded_data_iter_state "
                "— apply it to your loader with load_state_dict() or "
                "the resumed run will replay/skip data")
            return False
        loader.load_state_dict(state)
        pf = getattr(self, "_train_prefetcher", None)
        if pf is not None:
            pf.close()  # its queued batches predate the restored position
        self._train_prefetcher = None
        self._prefetch_prev_stats = None
        self._train_data_iter = None
        return True

    def _bind_train_prefetcher(self, pf: DevicePrefetcher):
        """Make ``pf`` the training prefetcher whose stats feed the
        periodic telemetry sync.  A previously bound one (e.g. an
        adopted caller-built iterator replaced by the engine's own) is
        kept in ``_prefetchers`` so close()/the finalizer still drain
        it, and the stats baseline resets — interval deltas must never
        mix two prefetchers' cumulative counters."""
        if pf not in self._prefetchers:
            self._prefetchers.append(pf)
        self._train_prefetcher = pf
        self._prefetch_prev_stats = None

    def prefetch(self, data_iter, depth: Optional[int] = None,
                 for_eval: bool = False) -> DevicePrefetcher:
        """Wrap ``data_iter`` in a :class:`DevicePrefetcher` bound to
        this engine's batch placement: the worker collates and
        device-places batches ahead of consumption, and
        ``train_batch(data_iter=...)`` / ``eval_batch(data_iter=...)``
        transparently adopt the placed pytrees.  ``for_eval`` batches
        skip the train reshape/sharding (eval consumes flat
        micro-batches) — only the host collate/conversion moves off the
        hot path there."""
        # the worker thread is a GC root: bound methods here would pin
        # the engine (full param/optimizer state) for process lifetime
        # when it is dropped without close(), and its flush finalizer
        # would never fire.  Weak closures keep the engine collectable;
        # the _close_prefetchers finalizer then drains the worker.
        eng_ref = weakref.ref(self)

        def place(batch, _eval=for_eval):
            eng = eng_ref()
            if eng is None:
                raise RuntimeError(
                    "engine was dropped; prefetcher is orphaned")
            return (eng._place_eval_batch(batch) if _eval
                    else eng._place_train_batch(batch))

        def span(name, cat="runtime", **args):
            eng = eng_ref()
            if eng is None:
                return contextlib.nullcontext()
            return eng._tel_span(name, cat=cat, **args)

        pf = DevicePrefetcher(
            data_iter, place_fn=place,
            depth=depth if depth is not None else self._prefetch_depth,
            span_fn=span,
            name="eval" if for_eval else "train",
            stage=self._stage_records["prefetch"],
            tracer=(self.telemetry.tracer
                    if self.telemetry is not None else None))
        # prune already-closed entries IN PLACE (the GC finalizer holds
        # this same list object): a per-eval prefetcher pattern must not
        # grow the list — and retain every source iterator — forever
        self._prefetchers[:] = [p for p in self._prefetchers
                                if not p.closed]
        self._prefetchers.append(pf)
        return pf

    def _place_train_batch(self, batch) -> DevicePlacedBatch:
        """Worker-side half of the prefetch pipeline: the exact
        placement ``train_batch`` would do inline.  PLD runs get a
        PLACEHOLDER theta leaf so the batch's structure (and therefore
        the compiled step's signature) matches the inline path — the
        real theta is overwritten at consumption time
        (``_pld_theta_overwrite``), keeping prefetched batches valid
        across ``global_steps`` changes."""
        rows = None
        if self.progressive_layer_drop is not None \
                and isinstance(batch, dict):
            batch = dict(batch)
            rows = len(next(iter(batch.values())))
            batch["pld_theta"] = np.zeros((rows,), np.float32)
        return DevicePlacedBatch(self._shard_batch(batch), rows=rows,
                                 kind="train")

    def _place_eval_batch(self, batch) -> DevicePlacedBatch:
        """Eval placement: the same host conversion ``eval_batch`` does
        inline (flat micro-batch, no train reshape)."""
        return DevicePlacedBatch(jax.tree.map(np.asarray, batch),
                                 kind="eval")

    def _pld_theta_overwrite(self, placed: DevicePlacedBatch):
        """Consumption-time PLD theta: rebuild the theta leaf for the
        CURRENT ``global_steps`` with the same placement the prefetched
        placeholder got — one tiny per-step put, instead of invalidating
        every queued batch whenever the schedule advances."""
        if not (isinstance(placed.tree, dict)
                and "pld_theta" in placed.tree):
            return placed
        self.progressive_layer_drop.update_state(self.global_steps)
        theta = self._shard_batch({"pld_theta": np.full(
            (placed.rows,), self.progressive_layer_drop.get_theta(),
            np.float32)})["pld_theta"]
        tree = dict(placed.tree)
        tree["pld_theta"] = theta
        return DevicePlacedBatch(tree, rows=placed.rows, kind=placed.kind,
                                 ctx=placed.ctx)

    def eval_batch(self, batch=None, data_iter=None):
        """Forward-only loss on one batch; like ``train_batch`` it also
        accepts a ``data_iter`` (the reference's eval_batch signature,
        pipe/engine.py:305 there).  Unlike ``train_batch``, a no-arg call
        raises instead of falling back to the training iterator — silently
        consuming training batches during evaluation would skew the
        training stream (the reference requires an explicit data_iter)."""
        if self._fatal_state_error is not None:
            # donation-poisoned state: surface the recovery message, not a
            # raw 'Array has been deleted' from the deleted master pieces
            raise RuntimeError(self._fatal_state_error)
        if batch is None:
            if data_iter is None:
                raise ValueError(
                    "eval_batch needs a batch or a data_iter; it does not "
                    "fall back to the training iterator (that would consume "
                    "and advance the training data stream)")
            batch = next(data_iter)
        if isinstance(batch, DevicePlacedBatch):
            if batch.kind != "eval":
                raise ValueError(
                    f"eval_batch received a {batch.kind!r}-placed batch "
                    "(the train accumulation layout); it needs the flat "
                    "eval placement — build the prefetcher with "
                    "engine.prefetch(it, for_eval=True)")
            micro = batch.tree
            if batch.ctx is not None and self.telemetry is not None \
                    and self.telemetry.tracer is not None:
                # terminate the prefetched batch's flow here too —
                # eval-placed batches must not leak open flows (the
                # recorder would grow one entry per eval batch and
                # flush them all as synthetic terminators at export)
                with self._tel_span("eval/dispatch", cat="eval"):
                    self.telemetry.tracer.flow_end(
                        "data/batch", batch.ctx, cat="data")
        else:
            micro = jax.tree.map(np.asarray, batch)
        rng = jax.random.fold_in(self._data_rng, self.micro_steps)
        with self._pallas_scope():
            if self._offload_host:
                self._dpu_flush()  # eval on fully-applied params
                return self._offload_eval_step(self._compute_params,
                                               micro, rng)
            if self._offload_xla:
                self._xla_dpu_flush()
            return self._eval_step(self.state, micro, rng)

    # --- reference-style imperative facade -----------------------------
    def forward(self, batch):
        """Compat shim for the reference trio (engine.py:779): computes the
        micro-batch loss and queues the batch for the fused step."""
        if self._fatal_state_error is not None:
            # same guard as eval_batch: this reads self.state below
            raise RuntimeError(self._fatal_state_error)
        if not getattr(self, "_facade_warned", False):
            self._facade_warned = True
            log_dist(
                "forward/backward/step facade in use: each micro-batch "
                "pays one EXTRA forward (the loss returned here is an "
                "eval pass; gradients run inside the fused step). Port "
                "the loop to engine.train_batch(batch) for full "
                "throughput.", ranks=[0])
        rng = jax.random.fold_in(self._data_rng, self.micro_steps)
        micro = jax.tree.map(np.asarray, batch)
        with self._pallas_scope():
            if self._offload_host:
                self._dpu_flush()  # same view as eval_batch
                loss = self._offload_eval_step(self._compute_params,
                                               micro, rng)
            else:
                if self._offload_xla:
                    self._xla_dpu_flush()
                loss = self._eval_step(self.state, micro, rng)
        self._pending_micros.append(batch)
        return loss

    __call__ = forward

    def backward(self, loss):
        """No-op gradient marker (gradients happen inside the fused step)."""
        self.micro_steps += 1
        return loss

    def is_gradient_accumulation_boundary(self) -> bool:
        return len(self._pending_micros) >= self.gradient_accumulation_steps

    def step(self):
        if not self.is_gradient_accumulation_boundary():
            return
        micros = self._pending_micros[:self.gradient_accumulation_steps]
        self._pending_micros = self._pending_micros[
            self.gradient_accumulation_steps:]
        batch = jax.tree.map(
            lambda *xs: np.concatenate([np.asarray(x) for x in xs], axis=0),
            *micros)
        self.micro_steps -= self.gradient_accumulation_steps  # train_batch re-adds
        return self.train_batch(batch)

    # ------------------------------------------------------------------
    # checkpointing (reference engine.py:1211-1478)
    # ------------------------------------------------------------------
    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True, async_write=None):
        """``async_write=True`` snapshots device state to host (the DPU
        flush below runs FIRST, so the snapshot sees fully-applied
        params on every offload tier) and hands serialization to the
        daemon writer — the step loop pays only the D2H drain.  ``None``
        defaults to the ``checkpoint.async_save`` config."""
        if self._fatal_state_error is not None:
            raise RuntimeError(self._fatal_state_error)
        if async_write is None:
            async_write = bool(self.config.checkpoint_config.async_save)
        if async_write:
            # a degraded writer saves synchronously (docs/stages.md)
            async_write = not stage_degraded(self, "ckpt_writer")
        if async_write and getattr(self, "_offload_disk", False):
            # disk tier: the async snapshot COPIES every plane to host
            # first (_host_snapshot), which would materialize the full
            # master+moments the tier exists to keep off-RAM — on a
            # model sized past host RAM that is an OOM, not a
            # checkpoint.  The sync path streams leaf-by-leaf straight
            # from the per-leaf files, so it is the only shape that
            # honors the bounded-residency contract.
            logger.warning(
                "offload.tier='disk': async checkpoint save downgraded "
                "to synchronous (the async snapshot would materialize "
                "the full disk-resident master+moments in host RAM)")
            async_write = False
        if self._offload_host:
            self._dpu_flush()  # the saved master must be fully applied
        elif self._offload_xla:
            self._xla_dpu_flush()
        from .checkpointing import save_checkpoint
        t0 = time.perf_counter()
        with self._tel_span("checkpoint/save", cat="checkpoint",
                            step=self.global_steps,
                            **{"async": bool(async_write)}):
            out = save_checkpoint(self, save_dir, tag=tag,
                                  client_state=client_state,
                                  save_latest=save_latest,
                                  async_write=bool(async_write))
        self._ckpt_last_save_dir = save_dir
        # exposed stall only: an async save returns after the snapshot,
        # so this is the number the ckpt_save_s telemetry scalar reports
        # (the background write lands in overlap_s via the writer job)
        with self._ckpt_acc_lock:
            acc = self._ckpt_interval_acc
            acc["save_s"] += time.perf_counter() - t0
            acc["saves"] += 1
        return out

    # ------------------------------------------------------------------
    # flight recorder + anomaly trigger (docs/observability.md)
    # ------------------------------------------------------------------
    def dump_flight_record(self, reason: str = "manual", error=None,
                           directory: Optional[str] = None
                           ) -> Optional[str]:
        """Dump every stage's bounded event ring (call outcomes, queue
        depths, failures, degradations) as ``flightrec_<step>.json`` for
        post-mortem (``python -m deepspeed_tpu.telemetry diagnose``).
        Fired automatically on a train_batch failure, a stage
        degradation, the SIGTERM preemption hook, and the anomaly
        trigger; callable on demand.  Never raises — it runs inside
        failure paths and worker threads; returns the path, or None when
        no telemetry output directory exists to hold it."""
        try:
            if directory is None:
                if self.telemetry is None:
                    logger.warning(
                        "flight record NOT dumped (%s): telemetry is "
                        "disabled and no directory was given", reason)
                    return None
                directory = self.telemetry.output_path
            from ..telemetry.hub import write_flight_record
            extra = {}
            if self.last_ckpt_error is not None:
                extra["last_ckpt_error"] = repr(self.last_ckpt_error)
            if getattr(self, "last_stage_error", None) is not None:
                extra["last_stage_error"] = repr(self.last_stage_error)
            path = write_flight_record(
                directory, getattr(self, "_stage_records", {}),
                self.global_steps, reason, error=error,
                extra=extra or None)
            logger.warning("flight record dumped to %s (%s)", path,
                           reason)
            return path
        except Exception:
            logger.exception("flight-record dump failed (reason=%r)",
                             reason)
            return None

    def _anomaly_stop(self):
        """Close a trigger-opened profiler capture (bounded: the window
        is one sync interval — or engine.close, whichever first)."""
        if not self._anomaly_profiling:
            return
        self._anomaly_profiling = False
        try:
            jax.profiler.stop_trace()
            log_dist("anomaly profiler capture closed", ranks=[0])
        except Exception as e:
            logger.warning("anomaly profiler capture stop failed: %s", e)

    def _fire_anomaly(self, reason: str):
        """One-shot (per run) anomaly response: flight-record dump + a
        bounded ``jax.profiler`` capture.  Opt-in — inert unless
        ``telemetry.anomaly_ratio`` is set."""
        if self._anomaly_ratio <= 0 or self._anomaly_fired:
            return
        self._anomaly_fired = True
        logger.warning(
            "telemetry anomaly trigger: %s — dumping a flight record "
            "and starting ONE bounded profiler capture", reason)
        self.dump_flight_record(reason=f"anomaly: {reason}")
        if self.telemetry is None or self._profiler_active \
                or self._profiler is not None:
            # never stack on a user-configured capture window — open OR
            # still pending (a window opening at start_step while the
            # anomaly capture runs would raise 'Profile has already
            # been started' and kill train_batch)
            return
        try:
            out = os.path.join(self.telemetry.output_path,
                               "anomaly_profile")
            jax.profiler.start_trace(out)
            self._anomaly_profiling = True
        except Exception as e:
            logger.warning("anomaly profiler capture failed to "
                           "start: %s", e)

    def _anomaly_check(self, avg: Optional[float]):
        """Step-time arm of the anomaly trigger, at the periodic sync:
        fire when this interval's per-step time exceeds
        ``telemetry.anomaly_ratio`` × the trailing median.  Also where a
        previous trigger's capture closes (bounded to one interval)."""
        self._anomaly_stop()
        if avg is None:
            return
        if (self._anomaly_ratio > 0 and not self._anomaly_fired
                and len(self._anomaly_trail) >= 4):
            med = statistics.median(self._anomaly_trail)
            if med > 0 and avg > self._anomaly_ratio * med:
                self._fire_anomaly(
                    f"interval step time {avg:.4f}s/step > "
                    f"{self._anomaly_ratio:g}x trailing median "
                    f"{med:.4f}s/step")
        # appended AFTER the check: the anomalous interval must not
        # dilute its own baseline
        self._anomaly_trail.append(avg)

    def _ckpt_writer_tick(self):
        """Pre-step surfacing of a completed async save's failure: the
        failure poisoned only that save (the writer already logged it
        loudly); here it lands in ``last_ckpt_error`` + the failure
        counter so the training thread and dashboards see it promptly,
        and training continues — the next save retries from a fresh
        snapshot."""
        w = getattr(self, "_ckpt_writer", None)
        err = w.pop_error() if w is not None else None
        if err is not None:
            self.last_ckpt_error = err
            if self.telemetry is not None:
                self.telemetry.registry.counter(
                    "ckpt_save_failures_total",
                    "checkpoint saves that failed (async writer or sync)",
                ).inc()
        # post-close/post-abort stage failures land in last_stage_error
        pop_stage_errors(self)

    def load_checkpoint(self, load_dir, tag=None,
                        load_optimizer_states=True,
                        load_lr_scheduler_states=True,
                        load_module_only=False):
        from .checkpointing import load_checkpoint
        # offload host-state sync happens inside load_checkpoint itself so
        # the public runtime.checkpointing API is consistent when called
        # directly (advisor finding, round 1)
        with self._tel_span("checkpoint/load", cat="checkpoint"):
            out = load_checkpoint(
                self, load_dir, tag=tag,
                load_optimizer_states=load_optimizer_states,
                load_lr_scheduler_states=load_lr_scheduler_states,
                load_module_only=load_module_only)
        # a successful load rebuilt self.state wholesale (module-only
        # loads get a fresh optimizer plane), so a donation-poisoned
        # engine is healthy again — the poison message's own recovery
        # instruction must actually work on this engine instance
        self._fatal_state_error = None
        return out

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------
    def drain_stages(self):
        """Wait out in-flight async work in THE drain order without
        tearing the stages down (the built-in sync save drains the
        ckpt entry via the same graph).  Never raises."""
        return self._stage_graph.drain_all()

    def close(self):
        """Drain + stop every async stage in THE documented order
        (prefetch -> offload uploads -> ckpt writer -> telemetry flush;
        docs/stages.md), then release the preemption hook and the GC
        finalizer (which covers engines dropped without a close, so
        buffered ``_tb_pending`` scalars are never lost).  Idempotent.
        A close-time failure never aborts the drain mid-order: every
        stage still closes, the errors land in ``stage_errors``/
        ``last_stage_error``, and the FIRST one re-raises so an explicit
        caller sees the shutdown was not clean (the GC finalizer path
        swallows it like any finalizer exception)."""
        try:
            self.stop_profiler()  # no-op unless a window is open
        except Exception:
            pass
        try:
            self._anomaly_stop()  # a trigger-opened capture must land
        except Exception:
            pass
        finish_close(self)

    # ------------------------------------------------------------------
    # introspection / logging
    # ------------------------------------------------------------------
    @property
    def last_metrics(self) -> Optional[StepMetrics]:
        if self._last_metrics is None and \
                getattr(self, "_last_packed", None) is not None:
            vec = np.asarray(self._last_packed)
            self._last_metrics = StepMetrics(
                loss=vec[0], grad_norm=vec[1], loss_scale=vec[2],
                overflow=bool(vec[3] > 0.5), lr=vec[4])
        return self._last_metrics

    @property
    def lr_scheduler(self):
        """The resolved step→lr callable (config- or client-provided)."""
        return self._lr_schedule

    # ---- reference accessor surface (engine.py:241-392 there: config
    # facts exposed as zero-arg methods) ----
    def pld_enabled(self):
        return self.config.pld_config.enabled

    def pld_params(self):
        if not self.config.pld_config.enabled:
            return False
        return {"theta": self.config.pld_config.theta,
                "gamma": self.config.pld_config.gamma}

    def tensorboard_enabled(self):
        return self.config.tensorboard_config.enabled

    def tensorboard_output_path(self):
        return self.config.tensorboard_config.output_path

    def tensorboard_job_name(self):
        return self.config.tensorboard_config.job_name

    def train_micro_batch_size_per_gpu(self):
        return int(self.micro_batch_size)

    def optimizer_name(self):
        return self.config.optimizer_name

    def optimizer_params(self):
        return self.config.optimizer_params

    def scheduler_name(self):
        return self.config.scheduler_name

    def scheduler_params(self):
        return self.config.scheduler_params

    def zero_optimization(self):
        return self.config.zero_optimization_stage > 0

    def zero_optimization_stage(self):
        return self.config.zero_optimization_stage

    def zero_cpu_offload(self):
        return bool(self.config.zero_config.cpu_offload)

    def loss_scale(self):
        return self.get_loss_scale()

    def amp_enabled(self):
        return self.config.amp_enabled

    def amp_params(self):
        return self.config.amp_params

    def zero_allow_untested_optimizer(self):
        return self.config.zero_allow_untested_optimizer

    def postscale_gradients(self):
        return not self.config.prescale_gradients

    def gradient_predivide_factor(self):
        return self.config.gradient_predivide_factor

    def dump_state(self):
        return self.config.dump_state

    def dynamic_loss_scale(self):
        return self.loss_scale_config.dynamic

    def steps_per_print(self):
        return self.config.steps_per_print

    def wall_clock_breakdown(self):
        return self.config.wall_clock_breakdown

    def memory_breakdown(self):
        return self.config.memory_breakdown

    def sparse_gradients_enabled(self):
        return bool(self.config.sparse_gradients_enabled)

    def train(self, mode: bool = True):
        """Mode record for API parity (reference engine.py:745-758 —
        nn.Module train()/eval() there).  Train-vs-eval behavior (dropout,
        PLD) is decided per compiled program here — train_batch always
        trains, eval_batch/forward never do — so the flag is bookkeeping,
        not a behavior switch."""
        self._train_mode = bool(mode)
        return self

    def eval(self):
        return self.train(False)

    def get_lr(self):
        if self._lr_schedule is not None:
            applied = self.global_steps - self.get_skipped_steps()
            return float(self._lr_schedule(jnp.asarray(applied)))
        return float(self.config.optimizer_params.get("lr", 1e-3))

    def get_loss_scale(self):
        return float(self.state.scaler.loss_scale)

    def get_skipped_steps(self):
        return int(self.state.skipped_steps)

    @property
    def skipped_steps(self) -> int:
        """Live overflow-skip count (reads the traced state — a plain
        python counter here would stay 0 forever on the compiled-step
        paths, silently under-reporting fp16 warmdown skips)."""
        state = getattr(self, "state", None)
        if state is None:
            return 0
        return int(np.asarray(jax.device_get(state.skipped_steps)))

    @skipped_steps.setter
    def skipped_steps(self, v):
        state = getattr(self, "state", None)
        if state is not None:
            self.state = state._replace(
                skipped_steps=self._place_scalar(
                    jnp.asarray(int(v), jnp.int32)))

    def _report(self, metrics: StepMetrics):
        # throughput from report-interval wall time, measured AFTER the
        # metrics materialization above drained the device: with async
        # dispatch, per-call _step_times record only enqueue latency and
        # would inflate samples/sec by orders of magnitude
        now = time.time()
        last = getattr(self, "_last_report", None)
        steps = self.global_steps - getattr(self, "_last_report_step", 0)
        self._last_report = now
        self._last_report_step = self.global_steps
        if last is not None and steps > 0:
            avg = (now - last) / steps
        else:
            times = list(self._step_times)  # first report: dispatch-biased
            avg = sum(times) / max(len(times), 1)
        tput = self.train_batch_size / avg if avg > 0 else 0.0
        log_dist(
            f"step={self.global_steps} loss={float(metrics.loss):.4f} "
            f"lr={float(metrics.lr):.3e} "
            f"loss_scale={float(metrics.loss_scale):.1f} "
            f"skipped={self.get_skipped_steps()} "
            f"samples/sec={tput:.1f}", ranks=[0])


def _drain_tb_pending(pending, writer):
    """Flush buffered (step, packed-metrics) records into the summary
    writer.  Mutates ``pending`` IN PLACE (clear, not rebind) so the GC
    finalizer — which holds the same list object — always sees the live
    buffer.  One definition shared by engine._flush_tensorboard and the
    finalizer path."""
    for step, rec in pending:
        if isinstance(rec, StepMetrics):
            loss, lr, scale = rec.loss, rec.lr, rec.loss_scale
        else:
            vec = np.asarray(rec)
            loss, lr, scale = vec[0], vec[4], vec[2]
        writer.add_scalar("Train/loss", float(loss), step)
        writer.add_scalar("Train/lr", float(lr), step)
        writer.add_scalar("Train/loss_scale", float(scale), step)
    pending.clear()


def _close_quietly(objs, tb_pending=None, writer=None, tracer=None):
    """GC-finalizer body: drain buffered scalars, clear the process-wide
    transfer-tracer hook if it is ours, close observability outputs.
    Never raises (runs during interpreter shutdown, where half the world
    may be gone)."""
    try:
        if tb_pending and writer is not None:
            _drain_tb_pending(tb_pending, writer)
    except Exception:
        pass
    try:
        if tracer is not None:
            from . import offload
            if offload._TRANSFER_TRACER is tracer:
                offload.set_transfer_tracer(None)
    except Exception:
        pass
    for obj in objs:
        try:
            obj.close()
        except Exception:
            pass


def _close_prefetchers(prefetchers):
    """GC-finalizer body for a dropped engine's input pipeline: release
    each parked prefetch worker (and the device-resident batches it
    staged).  Holds only the list object — the prefetchers reference the
    engine weakly, so this finalizer can actually fire.  Never raises."""
    for pf in list(prefetchers):
        try:
            pf.close()
        except Exception:
            pass


class _CallableInt(int):
    """int that also answers the reference's method-call accessor style
    (engine.train_batch_size() — engine.py:296 there — vs this codebase's
    engine.train_batch_size attribute)."""

    def __call__(self):
        return int(self)


class _CallableFloat(float):
    def __call__(self):
        return float(self)


def _device_put_tree(tree, shardings):
    leaves, treedef = jax.tree.flatten(tree)
    shard_leaves = jax.tree.leaves(
        shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
    out = [jax.device_put(l, s) for l, s in zip(leaves, shard_leaves)]
    return jax.tree.unflatten(treedef, out)
