"""ZeRO as GSPMD sharding policy.

The reference implements ZeRO imperatively: ~1000 lines of sub-partitioning +
reduce-scatter for stage 1 (reference: deepspeed/runtime/zero/stage1.py) and
~1850 lines of autograd-hook bucketing, dedicated CUDA streams, and sharded
all-gathers for stage 2 (reference: deepspeed/runtime/zero/stage2.py).  On
TPU the identical memory/communication semantics are *placement decisions on
a compiled graph*:

  stage 0 — master params, grads, optimizer state replicated over ``data``.
  stage 1 — optimizer state (incl. fp32 master copy) sharded over ``data``;
            grads still fully reduced (psum); params all-gathered by XLA
            where consumed.  ≡ reference stage1.py sub-partitioning.
  stage 2 — + gradients sharded over ``data``: the sharding constraint on
            the grad tree turns XLA's grad all-reduce into reduce-scatter
            (≡ the IPG bucket + reduce-to-owner machinery, stage2.py:613-738)
            and the latency-hiding scheduler overlaps it with the backward
            (≡ ``overlap_comm``'s reduction stream, stage2.py:283-287).
  stage 3 — + parameters themselves stored sharded; XLA all-gathers each
            layer's params just before use and discards after (the reference
            *defines* stage 3 but raises NotImplementedError, engine.py:692;
            here it falls out of the same mechanism).
  offload — optimizer state placed in host memory (``pinned_host`` memory
            kind); see runtime/offload.py.

Leaves whose dims don't divide the data-axis size stay replicated — the
analogue of the reference's alignment padding (stage2.py:218-278), chosen
instead of padding because XLA requires static per-shard shapes.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS


def _leaf_shape(leaf) -> tuple:
    return tuple(getattr(leaf, "shape", ()))


def shard_spec_for_leaf(shape: tuple,
                        axis_size: int,
                        axis_name: str = DATA_AXIS,
                        base_spec: Optional[P] = None) -> P:
    """Extend ``base_spec`` (e.g. a tensor-parallel spec) by sharding the
    first unassigned dim divisible by ``axis_size`` over ``axis_name``."""
    base = list(base_spec) if base_spec is not None else []
    base += [None] * (len(shape) - len(base))
    if axis_size <= 1:
        return P(*base)
    # A base spec may already consume the axis (expert-parallel weights
    # shard their expert dim over ``data``); a mesh axis can appear at most
    # once in a PartitionSpec, so ZeRO then has nothing to add.
    def _uses_axis(entry) -> bool:
        return (axis_name in entry if isinstance(entry, tuple)
                else entry == axis_name)
    if any(_uses_axis(e) for e in base if e is not None):
        return P(*base)
    for i, d in enumerate(shape):
        if base[i] is None and d % axis_size == 0 and d > 0:
            base[i] = axis_name
            return P(*base)
    return P(*base)  # too small / indivisible: replicate (no padding on TPU)


def sanitize_base_spec(spec: Optional[P], shape: tuple, mesh: Mesh) -> \
        Optional[P]:
    """Drop base-spec axis assignments whose leaf dim is not divisible by
    the mesh-axis size (product, for tuple entries) — the leaf falls back
    to replication on that dim, the same no-padding rule ZeRO applies to
    its own ``data``-axis sharding above.  Concretely: a model declaring
    expert-parallel ``P('data', ...)`` on a 4-expert weight keeps training
    on a dp=8 mesh instead of failing NamedSharding validation."""
    if spec is None:
        return None
    if len(spec) > len(shape):
        raise ValueError(
            f"partition spec {spec} has more entries than array rank "
            f"{len(shape)} (shape {shape}) — model param_partition_specs "
            "and param tree disagree")
    entries = list(spec) + [None] * (len(shape) - len(spec))
    out = []
    for i, e in enumerate(entries):
        if e is None:
            out.append(None)
            continue
        names = e if isinstance(e, tuple) else (e,)
        # Greedy major-to-minor retention: keep each sub-axis while the
        # running product still divides the dim, so a tuple entry like
        # ('data', 'model') on a dim divisible by dp but not dp*tp keeps
        # the 'data' sharding instead of replicating wholesale.
        kept, prod = [], 1
        for n in names:
            s = int(mesh.shape.get(n, 1))
            if shape[i] % (prod * s) == 0:
                kept.append(n)
                prod *= s
        if not kept:
            out.append(None)
        elif not isinstance(e, tuple):
            out.append(kept[0])
        else:
            out.append(tuple(kept))
    return P(*out)


class ZeroShardingPlan:
    """Per-stage placement rules for the train-state pytree."""

    def __init__(self, stage: int, mesh: Mesh,
                 base_param_specs: Optional[Any] = None,
                 offload: bool = False,
                 params: Optional[Any] = None):
        if not 0 <= stage <= 3:
            raise ValueError(f"ZeRO stage must be 0..3, got {stage}")
        self.stage = stage
        self.mesh = mesh
        self.offload = offload
        self.dp = mesh.shape.get(DATA_AXIS, 1)
        # base specs carry tensor/expert-parallel placement decided by the
        # model; ZeRO composes the 'data' axis on top.  Sanitized ONCE here
        # (indivisible dims → replicated); ``params`` supplies leaf shapes.
        if base_param_specs is not None and params is not None:
            spec_def = jax.tree.structure(
                base_param_specs, is_leaf=lambda x: isinstance(x, P))
            param_def = jax.tree.structure(params)
            if spec_def != param_def:
                raise ValueError(
                    "param_partition_specs tree structure does not match "
                    "the param tree — every param leaf needs exactly one "
                    "PartitionSpec at the same position (a silent "
                    "mismatch would drop ALL tensor-parallel placement "
                    "and replicate every leaf).\n"
                    f"  specs tree:  {spec_def}\n"
                    f"  params tree: {param_def}")
            base_param_specs = jax.tree.map(
                lambda s, l: sanitize_base_spec(
                    s, _leaf_shape(l), mesh),
                base_param_specs, params,
                is_leaf=lambda x: isinstance(x, P))
        self.base_param_specs = base_param_specs

    # -- helpers --------------------------------------------------------
    def _specs(self, tree, sharded: bool):
        leaves, treedef = jax.tree.flatten(tree)
        base_leaves = (None if self.base_param_specs is None
                       else jax.tree.leaves(self.base_param_specs))
        if base_leaves is not None and len(base_leaves) != len(leaves):
            raise ValueError(
                "param_partition_specs leaf count does not match the "
                f"tree being placed: {len(base_leaves)} specs vs "
                f"{len(leaves)} leaves — positional matching would "
                "mis-assign tensor-parallel placement.\n"
                f"  specs tree: "
                f"{jax.tree.structure(self.base_param_specs)}\n"
                f"  placed tree: {treedef}")
        specs = []
        for i, leaf in enumerate(leaves):
            base = None if base_leaves is None else base_leaves[i]
            if sharded:
                specs.append(shard_spec_for_leaf(
                    _leaf_shape(leaf), self.dp, DATA_AXIS, base))
            else:
                specs.append(base if base is not None else P())
        return jax.tree.unflatten(treedef, specs)

    def _sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    # -- public placement queries --------------------------------------
    def master_param_specs(self, params):
        """fp32 master copy: sharded from stage >= 1."""
        return self._specs(params, sharded=self.stage >= 1)

    def compute_param_specs(self, params):
        """Params as consumed by the forward pass: sharded only at stage 3."""
        return self._specs(params, sharded=self.stage >= 3)

    def grad_specs(self, params):
        """Gradients: sharded (reduce-scattered) from stage >= 2."""
        return self._specs(params, sharded=self.stage >= 2)

    def opt_state_specs(self, opt_state, params):
        """Optimizer moments mirror the master-param placement; scalar
        counters stay replicated."""
        master = self.master_param_specs(params)
        master_leaves = jax.tree.leaves(master)
        # Build spec tree by structural matching: any sub-tree of opt_state
        # with the same structure as params gets master specs; scalars get P().
        params_def = jax.tree.structure(params)

        param_shapes = [_leaf_shape(l) for l in jax.tree.leaves(params)]

        def match(subtree):
            """Same structure AND same leaf shapes as params.  The shape
            check matters: optimizer states may carry param-structured trees
            whose leaves are NOT param-shaped (e.g. 1-bit Adam's flat error
            buffers), and assigning them master specs would be wrong."""
            try:
                if (jax.tree.structure(subtree) == params_def
                        and [_leaf_shape(l) for l in
                             jax.tree.leaves(subtree)] == param_shapes):
                    return jax.tree.unflatten(params_def, master_leaves)
            except Exception:
                pass
            return None

        sharded = self.stage >= 1

        def recurse(node):
            m = match(node)
            if m is not None:
                return m
            if isinstance(node, (list, tuple)):
                out = [recurse(c) for c in node]
                return type(node)(out) if not hasattr(node, "_fields") else type(node)(*out)
            if isinstance(node, dict):
                return {k: recurse(v) for k, v in node.items()}
            # non-param-shaped state (e.g. 1-bit Adam's flat error buffers):
            # shard over data when divisible — replicating a full-param-size
            # fp32 buffer per device would undo the ZeRO memory win.  Scalar
            # counters have no divisible dim and stay replicated.
            shape = _leaf_shape(node)
            if sharded and shape:
                return shard_spec_for_leaf(shape, self.dp, DATA_AXIS)
            return P()

        return recurse(opt_state)

    def master_shardings(self, params):
        """Master params stay in device HBM even when offloading: they feed
        the forward cast every micro-step.  Offload targets the optimizer
        moments only (the reference's host-resident state is the fp32
        partitions consumed *only* at step time, stage2.py:743-900; our
        equivalent of that working set is the moments — see
        runtime/offload.py for the full host-Adam tier)."""
        return jax.tree.map(self._sharding, self.master_param_specs(params),
                            is_leaf=lambda x: isinstance(x, P))

    def opt_state_shardings(self, opt_state, params):
        # Only the non-offload engine path consumes this (both offload
        # tiers build their own flat host staging; see runtime/engine.py),
        # so placement is plain device memory.
        return jax.tree.map(self._sharding,
                            self.opt_state_specs(opt_state, params),
                            is_leaf=lambda x: isinstance(x, P))


def constrain_grads(grads, plan: ZeroShardingPlan):
    """Apply the stage>=2 reduce-scatter constraint inside the jitted step."""
    if plan.stage < 2 or plan.dp <= 1:
        return grads
    specs = plan.grad_specs(grads)
    spec_leaves = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    grad_leaves, treedef = jax.tree.flatten(grads)
    out = [jax.lax.with_sharding_constraint(g, NamedSharding(plan.mesh, s))
           for g, s in zip(grad_leaves, spec_leaves)]
    return jax.tree.unflatten(treedef, out)
