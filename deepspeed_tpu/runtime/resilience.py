"""Fault-tolerant checkpointing primitives: the background writer, the
retry/fault-injection plane, retention GC, and the preemption hook.

The reference writes checkpoints synchronously and trusts the filesystem
(reference deepspeed/runtime/engine.py:1211-1290): a save stalls the step
loop for the full serialize+fsync duration, a truncated file loads as
garbage, and old tags accumulate forever.  This module supplies the
production pieces (Megatron-LM distributed-checkpointing / Orbax-style
async checkpointing recipe — PAPERS.md, large-scale training infra):

  ``AsyncCheckpointWriter``   one daemon thread per engine; a submitted
                              save job is serialized + fsync'd + renamed
                              off the hot path.  A second submit while one
                              is in flight COALESCES (latest wins).  A
                              writer failure poisons only the pending
                              save — training continues, the next save
                              retries from a fresh snapshot.
  ``RetryPolicy``/``io_retry``  exponential backoff + jitter around every
                              checkpoint read/write; ``DS_CKPT_FAULT``
                              injects per-call failures for tests (the
                              PR 3/4 ``DS_OFFLOAD_H2D_DELAY_S`` /
                              ``DS_PREFETCH_DELAY_S`` fault-injection
                              style).
  ``sweep_tmp``/``retention_gc``  orphaned ``*.tmp`` cleanup and a
                              ``keep_last_n`` policy that reclaims old
                              tags only AFTER a new save verifies.
  ``install_preemption_handler``  opt-in SIGTERM hook: one final
                              synchronous save + clean ``engine.close()``
                              so a preempted pod resumes at the last
                              step instead of the last interval boundary.

Typed errors (``CheckpointCorruptError`` et al.) live here so both
``runtime.checkpointing`` and user code can catch them without import
cycles.
"""
from __future__ import annotations

import os
import random
import shutil
import signal
import threading
import time
import weakref
from typing import Callable, Iterable, NamedTuple, Optional

from ..utils.logging import log_dist, logger

CKPT_FORMAT_VERSION = 1

#: load_checkpoint status values (the three-way answer the reference
#: collapses into "got None back").
CKPT_OK = "OK"
CKPT_CORRUPT = "CORRUPT"
CKPT_MISSING = "MISSING"


class CheckpointError(Exception):
    """Base class for checkpoint integrity/availability failures."""


class CheckpointMissingError(CheckpointError):
    """An explicitly requested checkpoint does not exist on disk."""


class CheckpointCorruptError(CheckpointError):
    """A checkpoint artifact failed integrity verification (CRC/length/
    digest mismatch, unparseable manifest or meta, missing leaf file).
    The message names the offending leaf/file."""


# ---------------------------------------------------------------------------
# fault injection (tests + CPU overlaps proofs) — the unified stage plane
# ---------------------------------------------------------------------------
# The checkpoint write/read points (leaf, shard_index, manifest, meta,
# rename, latest, read) are stage ``ckpt`` in the unified chaos spec
# (runtime/stages.py, docs/stages.md): arm them with
# ``DS_STAGE_FAULT=ckpt:<point>:<n>[+]`` — or the legacy alias
# ``DS_CKPT_FAULT=<point>:<n>[+]``, kept and tested.  The thin wrappers
# below preserve this module's historical API.
from .stages import (fault_point as _stage_fault_point,
                     reset_fault_injection, spawn)  # noqa: F401 (re-export)


def fault_point(point: str, path: str = "") -> None:
    """Raise an injected transient OSError when the unified spec (or the
    ``DS_CKPT_FAULT`` alias) arms this checkpoint point's current hit
    number.  No-op (one cached dict lookup) when nothing is armed."""
    _stage_fault_point("ckpt", point, path)


# ---------------------------------------------------------------------------
# transient-I/O retry
# ---------------------------------------------------------------------------
class RetryPolicy(NamedTuple):
    """Exponential backoff + full jitter for checkpoint I/O.  ``attempts``
    is the TOTAL number of tries (1 = no retry)."""
    attempts: int = 3
    base_s: float = 0.05
    max_s: float = 2.0


DEFAULT_RETRY = RetryPolicy()


def io_retry(fn: Callable, what: str,
             policy: RetryPolicy = DEFAULT_RETRY,
             on_retry: Optional[Callable[[int, BaseException], None]] = None):
    """Run ``fn`` with up to ``policy.attempts`` tries on OSError (the
    transient class: NFS blips, GCS-fuse hiccups, injected faults).
    Non-OS errors propagate immediately — corruption is not transient."""
    attempts = max(int(policy.attempts), 1)
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except FileNotFoundError:
            # ENOENT never heals on retry (a missing leaf of a corrupt
            # checkpoint, a vanished dir): retrying only slows corruption
            # detection and pollutes logs
            raise
        except OSError as e:
            if attempt >= attempts:
                raise
            delay = min(policy.base_s * (2 ** (attempt - 1)), policy.max_s)
            delay *= 0.5 + random.random()  # full jitter
            logger.warning(
                "checkpoint I/O retry %d/%d for %s after %s: %s "
                "(backoff %.3fs)", attempt, attempts - 1, what,
                type(e).__name__, e, delay)
            if on_retry is not None:
                on_retry(attempt, e)
            if delay > 0:
                time.sleep(delay)


# ---------------------------------------------------------------------------
# async writer
# ---------------------------------------------------------------------------
class CheckpointJob(NamedTuple):
    """One fully host-resident save: ``run()`` needs no device access, no
    engine state, and no locks — everything was snapshotted (COPIED) at
    submit time, so the step loop may donate/mutate freely while the
    writer streams bytes.  ``ctx`` (optional) is the save's causal
    TraceContext: the flow opened inside the ``checkpoint/save`` span is
    terminated inside the writer's ``checkpoint/async_write`` span, so
    trace.json links the submitting step to its background write."""
    tag: str
    tmp_dir: str
    final_dir: str
    run: Callable[[], str]
    ctx: Optional[object] = None


class AsyncCheckpointWriter:
    """Single daemon writer thread with a one-slot, latest-wins queue.

    Semantics (ISSUE 5 tentpole):
      - ``submit`` while a job is pending REPLACES the pending job (the
        newer snapshot supersedes it — checkpoints are snapshots of a
        monotonically advancing run, so only the latest matters);
      - a job failure is recorded (``pop_error``) and logged loudly but
        poisons ONLY that save — the writer stays alive and the next
        submit retries from a fresh snapshot;
      - ``drain`` blocks until the queue is empty and the writer idle,
        returning the last un-surfaced error (if any);
      - ``close`` drains and stops the thread (idempotent).

    ``stage`` (optional) is the engine's persistent ``ckpt_writer``
    :class:`~.stages.Stage` record: each job passes the ``job``
    injection point (``DS_STAGE_FAULT=ckpt_writer:job:n[+]``), and a
    FAILED save — after the ``ckpt`` write points' own io_retry plane
    has given up — counts against the stage's failure budget.
    Exhausting the budget degrades the stage; the ENGINE reads
    ``stage.degraded`` at save time and falls back to synchronous saves
    (async == sync bitwise, so degradation costs latency, never bytes).
    """

    def __init__(self, name: str = "ds-ckpt-writer", stage=None):
        self._name = name
        self._stage = stage
        if stage is not None:
            # flight recorder: the writer's "queue depth" is its
            # in-flight job count (racy sample read is fine — it rides
            # event records, not control flow)
            stage.depth_fn = lambda: (int(self._pending is not None)
                                      + int(self._busy is not None))
        self._cv = threading.Condition()
        self._pending: Optional[CheckpointJob] = None
        self._busy: Optional[CheckpointJob] = None
        self._last_error: Optional[BaseException] = None
        self._closed = False
        self._thread = None
        # stats (read under _cv)
        self.completed = 0
        self.failed = 0
        self.coalesced = 0
        self.last_write_s = 0.0

    # -- submission -----------------------------------------------------
    def submit(self, job: CheckpointJob) -> None:
        with self._cv:
            if self._closed:
                raise RuntimeError(f"{self._name} is closed")
            if self._pending is not None:
                self.coalesced += 1
                log_dist(
                    f"async checkpoint: save {self._pending.tag!r} "
                    f"superseded by {job.tag!r} before it started "
                    "(latest wins)", ranks=[0])
            self._pending = job
            if self._thread is None:
                self._thread = spawn(self._run, name=self._name,
                                     restarts=0)
            self._cv.notify_all()

    # -- introspection --------------------------------------------------
    def active_tmp(self) -> set:
        """tmp dirs owned by in-flight/pending jobs — including the
        ``.replaced.tmp`` park dir a publishing job may hold — the
        orphan sweep must never reclaim these."""
        with self._cv:
            live = [j for j in (self._pending, self._busy)
                    if j is not None]
            return ({j.tmp_dir for j in live}
                    | {j.final_dir + ".replaced.tmp" for j in live})

    def in_flight(self) -> bool:
        with self._cv:
            return self._pending is not None or self._busy is not None

    def pop_error(self) -> Optional[BaseException]:
        """Return-and-clear the last writer failure (the engine's
        pre-step tick surfaces it exactly once)."""
        with self._cv:
            err, self._last_error = self._last_error, None
            return err

    # -- lifecycle ------------------------------------------------------
    def drain(self, timeout: Optional[float] = None
              ) -> Optional[BaseException]:
        """Block until no job is pending or running; returns (and clears)
        the last failure so callers can decide loud-vs-fatal."""
        with self._cv:
            self._cv.wait_for(
                lambda: self._pending is None and self._busy is None,
                timeout=timeout)
            err, self._last_error = self._last_error, None
            return err

    def close(self, timeout: Optional[float] = 60.0) -> None:
        with self._cv:
            if self._closed:
                return
        err = self.drain(timeout=timeout)
        with self._cv:
            if err is not None:
                logger.error("async checkpoint writer: pending save "
                             "failed at close: %s", err)
                # re-stash so the caller's pop_error (the engine's close
                # tick) still records the lost save instead of seeing a
                # clean shutdown
                self._last_error = err
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # -- worker ---------------------------------------------------------
    def _run(self):
        while True:
            with self._cv:
                self._cv.wait_for(
                    lambda: self._pending is not None or self._closed)
                if self._pending is None and self._closed:
                    return
                self._busy, self._pending = self._pending, None
                job = self._busy
            t0 = time.perf_counter()
            try:
                if self._stage is not None:
                    # the writer's own stage boundary (the ckpt write
                    # points inside job.run() are stage "ckpt")
                    self._stage.check("job", job.tag)
                job.run()
                with self._cv:
                    self.completed += 1
                    self.last_write_s = time.perf_counter() - t0
                if self._stage is not None:
                    self._stage.note_ok()
                    # writer drives the stage record manually (no
                    # Stage.call), so it records its own outcomes
                    self._stage.record_event(
                        "job_ok", tag=job.tag,
                        dur_s=round(time.perf_counter() - t0, 6))
            except BaseException as e:  # poison THIS save only
                logger.error(
                    "async checkpoint save %r FAILED (training continues; "
                    "the next save retries from a fresh snapshot): %s",
                    job.tag, e)
                with self._cv:
                    self.failed += 1
                    self._last_error = e
                if self._stage is not None \
                        and not self._stage.is_transient(e):
                    self._stage.record_event("job_failed", tag=job.tag,
                                             error=repr(e))
                if self._stage is not None and self._stage.is_transient(e):
                    # a failed SAVE (io_retry already exhausted inside)
                    # counts against the budget; exhausting it degrades
                    # the stage and the engine saves synchronously from
                    # then on
                    self._stage.note_failure(e)
            finally:
                with self._cv:
                    self._busy = None
                    self._cv.notify_all()


# ---------------------------------------------------------------------------
# retention GC + orphan sweep
# ---------------------------------------------------------------------------
def sweep_tmp(save_dir: str, keep: Iterable[str] = (),
              retry: RetryPolicy = DEFAULT_RETRY) -> int:
    """Remove orphaned ``*.tmp`` checkpoint dirs under ``save_dir`` — the
    debris of a crash mid-save (the old code only reclaimed a tag's tmp
    when the SAME tag was re-saved).  ``keep`` lists tmp/park dirs owned
    by a live async writer.  A ``<tag>.replaced.tmp`` park dir whose tag
    directory is MISSING is the old good copy stranded by a crash
    between the park and publish renames — it is RESTORED (renamed
    back), never deleted, so a same-tag re-save can lose the only copy
    to neither the crash nor this sweep.  Returns the number removed.
    Multi-host contract: call from process 0 only, behind the save
    barrier."""
    if not os.path.isdir(save_dir):
        return 0
    keep = {os.path.abspath(k) for k in keep}
    removed = 0
    for name in os.listdir(save_dir):
        if not name.endswith(".tmp"):
            continue
        path = os.path.join(save_dir, name)
        if not os.path.isdir(path) or os.path.abspath(path) in keep:
            continue
        if name.endswith(".replaced.tmp"):
            tag_dir = path[: -len(".replaced.tmp")]
            if not os.path.isdir(tag_dir):
                try:
                    io_retry(lambda: os.rename(path, tag_dir),
                             f"restore of parked {path}", retry)
                    logger.error(
                        "checkpoint hygiene: a crashed re-save left the "
                        "old copy parked at %s with no published "
                        "replacement — RESTORED it to %s", path, tag_dir)
                except OSError as e:
                    logger.warning("could not restore parked %s: %s",
                                   path, e)
                continue
        try:
            io_retry(lambda p=path: shutil.rmtree(p),
                     f"sweep of orphaned {path}", retry)
            removed += 1
            log_dist(f"checkpoint hygiene: removed orphaned {path} "
                     "(crashed save)", ranks=[0])
        except OSError as e:
            logger.warning("could not remove orphaned %s: %s", path, e)
    return removed


def list_tags(save_dir: str) -> list:
    """Tag directories under ``save_dir``, newest first (mtime order —
    tags are caller-chosen strings, so lexical order means nothing)."""
    if not os.path.isdir(save_dir):
        return []
    tags = []
    for name in os.listdir(save_dir):
        if name.endswith(".tmp"):
            continue
        path = os.path.join(save_dir, name)
        if os.path.isdir(path):
            try:
                tags.append((os.path.getmtime(path), name))
            except OSError:
                continue
    tags.sort(reverse=True)
    return [name for _, name in tags]


def retention_gc(save_dir: str, keep_last_n: int,
                 protect: Iterable[str] = (),
                 retry: RetryPolicy = DEFAULT_RETRY) -> int:
    """Reclaim old checkpoint tags beyond the newest ``keep_last_n``.
    ``protect`` names tags never removed regardless of age (the tag just
    written and the one ``latest`` points to).  keep_last_n <= 0 means
    unlimited (the default — retention is opt-in).  Callers run this
    only AFTER a new save verifies, never before: the fallback chain
    must always have a verified checkpoint to land on."""
    if keep_last_n <= 0:
        return 0
    tags = list_tags(save_dir)
    keep = set(tags[:keep_last_n]) | {str(p) for p in protect}
    removed = 0
    for tag in tags[keep_last_n:]:
        if tag in keep:
            continue
        path = os.path.join(save_dir, tag)
        try:
            io_retry(lambda p=path: shutil.rmtree(p),
                     f"retention GC of {path}", retry)
            removed += 1
            log_dist(f"checkpoint retention: removed {path} "
                     f"(keep_last_n={keep_last_n})", ranks=[0])
        except OSError as e:
            logger.warning("retention GC could not remove %s: %s", path, e)
    return removed


# ---------------------------------------------------------------------------
# preemption (SIGTERM) hook
# ---------------------------------------------------------------------------
class PreemptionHandler:
    """Opt-in SIGTERM hook: one final SYNCHRONOUS save + clean
    ``engine.close()`` so a preempted pod resumes at the last step, not
    the last interval boundary.  Holds the engine weakly (a dropped
    engine must stay collectable).  After the save, the previous handler
    is chained; with ``exit_after`` (the default) the default disposition
    is restored and the signal re-raised so the process still terminates
    with the expected status."""

    def __init__(self, engine, save_dir: Optional[str] = None,
                 tag: Optional[str] = None, exit_after: bool = True,
                 signals=(signal.SIGTERM,)):
        self._engine_ref = weakref.ref(engine)
        self.save_dir = save_dir
        self.tag = tag
        self.exit_after = exit_after
        self._signals = tuple(signals)
        self._prev = {}
        self._fired = False
        self._installed = False
        try:
            for sig in self._signals:
                self._prev[sig] = signal.signal(sig, self._handle)
            self._installed = True
        except ValueError:
            # signal handlers can only be installed from the main thread
            logger.warning(
                "preemption handler NOT installed (engine constructed off "
                "the main thread); call install_preemption_handler from "
                "the main thread instead")

    @property
    def installed(self) -> bool:
        return self._installed

    @property
    def fired(self) -> bool:
        return self._fired

    def _handle(self, signum, frame):
        if self._fired:
            # the preemption save already ran; later signals must not be
            # silently swallowed (an orchestrator escalating SIGTERMs
            # would otherwise need SIGKILL): behave as if uninstalled —
            # chain a callable prev, else restore the old disposition
            # and re-deliver so the default action applies
            prev = self._prev.get(signum)
            if callable(prev):
                prev(signum, frame)
            else:
                self.uninstall()
                os.kill(os.getpid(), signum)
            return
        if not self._installed:
            # uninstalled while sandwiched in a handler chain (a later
            # handler holds us as ITS previous): stay inert, keep the
            # chain intact
            prev = self._prev.get(signum)
            if callable(prev):
                prev(signum, frame)
            return
        eng0 = self._engine_ref()
        if eng0 is not None and getattr(eng0, "_in_step", False):
            # the signal interrupted train_batch mid-update (Python
            # handlers run on the main thread at an arbitrary bytecode):
            # saving NOW could checkpoint a torn, half-applied optimizer
            # state with valid CRCs.  Park on the engine; train_batch's
            # finally block calls complete_deferred() at the step
            # boundary, where the state is consistent.
            self._deferred_signum = signum
            eng0._deferred_preempt = self
            log_dist(
                "SIGTERM mid-step: deferring the preemption save to "
                "this step's boundary", ranks=[0])
            return
        self._fired = True
        eng = self._engine_ref()
        if eng is not None:
            # post-mortem first: the preemption save below can itself
            # fail, and the flight record explains the run's last
            # moments either way (dump_flight_record never raises)
            dump = getattr(eng, "dump_flight_record", None)
            if dump is not None:
                dump(reason=f"SIGTERM preemption (signal {signum})")
            save_dir = self.save_dir or getattr(
                eng, "_ckpt_last_save_dir", None)
            if save_dir:
                log_dist(
                    f"SIGTERM: preemption save to {save_dir} at step "
                    f"{getattr(eng, 'global_steps', '?')}", ranks=[0])
                try:
                    eng.save_checkpoint(save_dir, tag=self.tag,
                                        async_write=False)
                except Exception as e:
                    logger.error("preemption save FAILED: %s", e)
            else:
                logger.warning(
                    "SIGTERM: no checkpoint save_dir known (no prior "
                    "save_checkpoint and no checkpoint.save_dir config); "
                    "closing without a final save")
            try:
                eng.close()
            except Exception as e:
                logger.error("engine.close() during preemption: %s", e)
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif self.exit_after:
            self.uninstall()
            os.kill(os.getpid(), signum)

    def complete_deferred(self):
        """Run the parked preemption save at the step boundary (called by
        ``train_batch``'s finally block once ``_in_step`` clears)."""
        self._handle(getattr(self, "_deferred_signum", signal.SIGTERM),
                     None)

    def uninstall(self):
        if not self._installed:
            return
        self._installed = False
        try:
            for sig, prev in self._prev.items():
                # restore ONLY if we are still the active handler: blindly
                # writing our stored prev would clobber any handler
                # installed on top of us (e.g. a second engine's hook,
                # which would silently revert SIGTERM to the default
                # kill).  When sandwiched, we go inert instead — _handle
                # passes through to prev.
                if signal.getsignal(sig) == self._handle:
                    signal.signal(sig, prev)
        except ValueError:
            pass


def install_preemption_handler(engine, save_dir: Optional[str] = None,
                               tag: Optional[str] = None,
                               exit_after: bool = True) -> PreemptionHandler:
    """Install the SIGTERM preemption hook for ``engine``; returns the
    handler (``.uninstall()`` removes it — ``engine.close()`` does this
    automatically for the config-installed one)."""
    return PreemptionHandler(engine, save_dir=save_dir, tag=tag,
                             exit_after=exit_after)
