"""Model protocol consumed by the engine.

The reference wraps an ``nn.Module`` whose forward returns the loss
(reference: tests/unit/simple_model.py:9-25 and engine.py:779).  The JAX
equivalent is a pair (init, loss_fn) over an immutable param pytree:

    class MyModel(TrainModule):
        def init(self, rng) -> params
        def loss_fn(self, params, batch, rng, train=True) -> scalar loss

Adapters are provided for Flax linen modules and bare (init_fn, loss_fn)
pairs.  ``param_partition_specs`` optionally returns a pytree of
PartitionSpecs carrying the model's own tensor-parallel placement (the
analogue of the user-supplied Megatron ``mpu`` object, reference
deepspeed/__init__.py:76-77) which ZeRO composes with the data axis.
"""
from __future__ import annotations

from typing import Any, Callable, Optional


class TrainModule:
    """Duck-typed protocol; subclass or just match the surface."""

    def init(self, rng) -> Any:
        raise NotImplementedError

    def loss_fn(self, params, batch, rng, train: bool = True):
        raise NotImplementedError

    def param_partition_specs(self, params) -> Optional[Any]:
        return None

    def streaming_param_spec(self, params) -> Optional[Any]:
        """Optional: a pytree of bools aligned with ``params`` marking
        stacked-over-layers leaves the model consumes one layer per scan
        tick (True = streamable).  With
        ``zero_optimization.param_streaming`` the engine keeps those
        leaves' compute copies in HOST memory, so device-resident
        parameter bytes ~ one layer — ZeRO-Infinity-style parameter
        offload (the capacity feature the reference implements as CPU/
        NVMe param partitions, deepspeed/runtime/zero/stage2.py's fp16
        partition machinery generalized by the ZeRO-Infinity paper).
        Return None when nothing is streamable (streaming becomes a
        config error rather than a silent no-op)."""
        return None

    def sparse_grad_tokens(self, batch) -> dict:
        """Optional: declare embedding-style params whose gradient rows are
        only the batch's token rows.  Returns {param keystr: token-id
        array}, where keystr is ``jax.tree_util.keystr`` of the param's
        path and the tokens come from ``batch`` (called inside the traced
        step with the per-worker batch ``[grad_acc, local_micro, ...]``).
        With ``sparse_gradients`` enabled the engine exchanges these
        params' grads as (indices, values) instead of dense — the
        reference's nn.Embedding CSR allreduce (engine.py:177-183,
        1153-1209)."""
        return {}


class FunctionalModule(TrainModule):
    """Wrap bare (init_fn, loss_fn) callables."""

    def __init__(self, init_fn: Callable, loss_fn: Callable,
                 partition_spec_fn: Optional[Callable] = None):
        self._init = init_fn
        self._loss = loss_fn
        self._specs = partition_spec_fn

    def init(self, rng):
        return self._init(rng)

    def loss_fn(self, params, batch, rng, train: bool = True):
        return self._loss(params, batch, rng, train)

    def param_partition_specs(self, params):
        return self._specs(params) if self._specs else None


class FlaxModule(TrainModule):
    """Adapter for a Flax linen module + a loss callable.

    ``loss_fn(apply_fn, variables, batch, rng, train) -> loss``.
    ``example_batch`` supplies shapes for lazy init.
    """

    def __init__(self, module, loss_fn: Callable, example_batch,
                 partition_spec_fn: Optional[Callable] = None):
        self.module = module
        self._loss = loss_fn
        self._example_batch = example_batch
        self._specs = partition_spec_fn

    def init(self, rng):
        return self.module.init(rng, self._example_batch)

    def loss_fn(self, params, batch, rng, train: bool = True):
        return self._loss(self.module.apply, params, batch, rng, train)

    def param_partition_specs(self, params):
        return self._specs(params) if self._specs else None
