#!/bin/bash
# One-shot hardware evidence run for a healthy-tunnel window.
# Order: north-star bench (bench.py itself chains the remaining suite,
# banking artifacts as it goes) -> offload stall diagnosis matrix ->
# commit everything.  Never SIGTERM TPU jobs (BENCH_NOTES.md).
cd /root/repo
log=recovery_run.log
echo "=== recovery run start $(date -u +%H:%M:%S) ===" >> "$log"
python bench.py > BENCH_r03_raw.json 2>> "$log"
echo "=== bench.py rc=$? $(date -u +%H:%M:%S) ===" >> "$log"
python bench_cpu_adam.py > BENCH_cpu_adam.txt 2>> "$log"
echo "=== cpu_adam rc=$? $(date -u +%H:%M:%S) ===" >> "$log"
python diag_hostperf.py > DIAG_hostperf_run.log 2>&1
echo "=== hostperf rc=$? $(date -u +%H:%M:%S) ===" >> "$log"
python diag_offload.py --full > DIAG_offload_run.log 2>&1
echo "=== diag rc=$? $(date -u +%H:%M:%S) ===" >> "$log"
# add the whole tree: a pathspec list aborts (staging NOTHING) if any
# one artifact is missing, which is exactly the degraded case
git add -A >> "$log" 2>&1
git commit -q -m "Hardware bench artifacts: north star + suite + offload diagnosis" >> "$log" 2>&1
echo "=== recovery run done $(date -u +%H:%M:%S) ===" >> "$log"
