#!/bin/bash
# One-shot hardware evidence run for a healthy-tunnel window.
# Order: north-star bench (bench.py itself chains the remaining suite,
# banking artifacts as it goes) -> offload stall diagnosis matrix ->
# commit everything.  Never SIGTERM TPU jobs (BENCH_NOTES.md).
cd /root/repo
log=recovery_run.log
echo "=== recovery run start $(date -u +%H:%M:%S) ===" >> "$log"
python bench.py > BENCH_r05_raw.json 2>> "$log"
echo "=== bench.py rc=$? $(date -u +%H:%M:%S) ===" >> "$log"
python bench_cpu_adam.py > BENCH_cpu_adam.txt 2>> "$log"
echo "=== cpu_adam rc=$? $(date -u +%H:%M:%S) ===" >> "$log"
python diag_hostperf.py > DIAG_hostperf_run.log 2>&1
echo "=== hostperf rc=$? $(date -u +%H:%M:%S) ===" >> "$log"
python diag_offload.py --full > DIAG_offload_run.log 2>&1
echo "=== diag rc=$? $(date -u +%H:%M:%S) ===" >> "$log"
# Stage only bench/diag artifacts (tolerating missing ones) so a failed
# bench never sweeps unrelated working-tree changes into the commit.
# Globs cover every artifact the chain can write: BENCH_north_star.json,
# BENCH_r05_raw.json, the suite's BENCH_*{,_raw}.json, BENCH_cpu_adam.txt,
# DIAG_*.json and run logs.
for f in BENCH_*.json BENCH_*.txt DIAG_*.json DIAG_*.log \
         DIAG_hostperf_run.log DIAG_offload_run.log MULTICHIP_*.json \
         bench_suite.log recovery_run.log; do
  [ -e "$f" ] && git add "$f" >> "$log" 2>&1
done
git commit -q -m "Hardware bench artifacts: north star + suite + offload diagnosis" >> "$log" 2>&1
echo "=== recovery run done $(date -u +%H:%M:%S) ===" >> "$log"
