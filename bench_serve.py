#!/usr/bin/env python
"""Serving bench entry point — a thin shim over the workload plane.

The five A/B legs (serve / paged / spec / quant / fleet) now live as
scenario configs over the ONE open-loop replay harness in
``tools/loadgen/`` (docs/serving.md "workload plane"); this file keeps
the historical CLI and the ``run_*_ab`` import surface stable.  Each
leg still writes its committed ``BENCH_*.json`` headline:

    BENCH_serve.json        serve_continuous_batching_speedup
    BENCH_serve_paged.json  serve_paged_admitted_ratio
    BENCH_serve_spec.json   serve_spec_wall_per_token_ratio
    BENCH_serve_quant.json  serve_quant_admitted_ratio
    BENCH_fleet.json        fleet_scaling_tokens_ratio

The workload plane's own goodput headline
(``BENCH_loadgen_goodput.json``) runs via
``python -m tools.loadgen goodput``.
"""
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# this file is loaded both as a script and via spec_from_file_location
# (the bench tests) — anchor the repo root so ``tools.loadgen``
# resolves regardless of the caller's cwd
_HERE = os.path.dirname(os.path.abspath(__file__))
if _HERE not in sys.path:
    sys.path.insert(0, _HERE)

from tools.loadgen.scenarios import (  # noqa: E402  (path anchor above)
    run_ab, run_fleet_ab, run_goodput, run_paged_ab, run_quant_ab,
    run_spec_ab)

__all__ = ["run_ab", "run_paged_ab", "run_spec_ab", "run_quant_ab",
           "run_fleet_ab", "run_goodput"]


def _mode_kwargs(args, **attr_to_kw):
    """Per-mode default sentinels: every mode flag defaults to None at
    the parser, and ONLY explicitly-given values are forwarded, so
    each ``run_*_ab`` keeps its own mode defaults (the paged/spec/
    quant A/Bs want different slot counts, delays and budgets than the
    plain one).  One copy of the forwarding — the third mode no longer
    clones the other two's kwargs blocks."""
    kw = {}
    for attr, name in attr_to_kw.items():
        v = getattr(args, attr)
        if v is not None:
            kw[name] = v
    return kw


def main():
    import argparse
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--slots", type=int, default=None,
                        help="slot pool size (default 8); with --paged "
                             "this is the KV-byte budget in legacy-slot "
                             "strides (default 4 there)")
    parser.add_argument("--requests", type=int, default=None,
                        help="workload size (default 16; 24 with "
                             "--paged)")
    parser.add_argument("--prompt", type=int, default=None,
                        help="prompt length (unpaged and --spec A/Bs, "
                             "default 8 — the paged/quant legs drive a "
                             "fixed short/long mix)")
    parser.add_argument("--gen", type=int, default=None,
                        help="tokens per request (default 16; with "
                             "--spec, 4*(k+1)+1 — block-aligned for "
                             "the given --k)")
    parser.add_argument("--delay", type=float, default=None,
                        help="injected device time (s): per TICK for "
                             "the unpaged A/B (default 0.02), per "
                             "prefill PAGE for the --paged prefix leg "
                             "(default 0.03)")
    parser.add_argument("--paged", choices=("on", "off", "ab"),
                        default=None,
                        help="run the paged-KV A/B instead "
                             "(BENCH_serve_paged.json); 'ab' = both "
                             "arms (on/off are accepted for symmetry "
                             "with the other benches and also run the "
                             "full A/B — both arms are needed for the "
                             "ratio)")
    parser.add_argument("--spec", choices=("on", "off", "ab"),
                        default=None,
                        help="run the speculative-decoding A/B instead "
                             "(BENCH_serve_spec.json); both arms always "
                             "run — the headline is the spec/non-spec "
                             "wall-per-token ratio")
    parser.add_argument("--quant", choices=("on", "off", "ab"),
                        default=None,
                        help="run the quantized-serving A/B instead "
                             "(BENCH_serve_quant.json): admitted "
                             "concurrency at a fixed KV-byte budget, "
                             "int8 vs fp pages, plus the int8-weights "
                             "params-HBM leg; both arms always run — "
                             "the headline is a ratio")
    parser.add_argument("--k", type=int, default=4,
                        help="draft tokens per tick for --spec "
                             "(default 4)")
    parser.add_argument("--fleet", choices=("on", "off", "ab"),
                        default=None,
                        help="run the serving-fleet A/B instead "
                             "(BENCH_fleet.json): aggregate tokens/s "
                             "at 1 vs 2 replicas under identical "
                             "injected per-tick device time, plus the "
                             "replica-kill + autoscale-up trace; both "
                             "arms always run — the headline is the "
                             "2/1 tokens-per-second ratio")
    args = parser.parse_args()
    # one shared dispatch harness: every mode forwards ONLY the flags
    # the user gave (None sentinels), so each run_*_ab keeps its own
    # per-mode defaults — no more per-mode kwargs blocks to clone
    if args.fleet is not None:
        rec = run_fleet_ab(**_mode_kwargs(
            args, requests="n_requests", gen="gen_tokens",
            delay="tick_delay_s"))
    elif args.spec is not None:
        rec = run_spec_ab(**{"k": args.k}, **_mode_kwargs(
            args, delay="pass_delay_s", slots="slots",
            requests="n_requests", gen="gen_tokens",
            prompt="prompt_len"))
    elif args.quant is not None:
        rec = run_quant_ab(**_mode_kwargs(
            args, slots="kv_budget_slots", requests="n_requests"))
    elif args.paged is not None:
        rec = run_paged_ab(**_mode_kwargs(
            args, delay="tick_delay_s", slots="kv_budget_slots",
            requests="n_requests"))
    else:
        rec = run_ab(**_mode_kwargs(
            args, slots="slots", requests="n_requests",
            prompt="prompt_len", gen="gen_tokens",
            delay="tick_delay_s"))
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
