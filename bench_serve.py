#!/usr/bin/env python
"""Serving bench: continuous batching vs sequential per-request decode.

The claim under test is a SCHEDULING claim, so it is CPU-provable with
the repo's established fault-injection idiom: ``DS_STAGE_DELAY_S=
serve:<s>`` charges every serving tick (admission prefill + masked
decode step) a synthetic device time, the way the prefetch/offload
benches inject collate/H2D latency.  A slot pool of size S then retires
up to S tokens per paid tick while the sequential leg (slots=1 — one
request decoded start-to-finish at a time) pays one tick per token:
wall-clock speedup ≈ S at saturation, which is exactly the
continuous-batching win Orca measured on real GPUs (PAPERS.md).

Both legs drive a synthetic open-loop load (arrivals on a fixed
schedule, independent of completions) through the telemetry hub;
tokens/s and p50/p99 per-token latency come from the same
``events.jsonl`` scalars the ``telemetry summarize`` serving row reads.

Emits BENCH_serve.json:
    {"metric": "serve_continuous_batching_speedup", "value": ...,
     "batched": {...}, "sequential": {...}}
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _build_model():
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    cfg = GPT2Config(vocab_size=256, n_positions=64, d_model=64,
                     n_layer=2, n_head=4, remat=None, attn_impl="dense")
    return GPT2Model(cfg)


def run_leg(model, params, *, slots, n_requests, prompt_len, gen_tokens,
            tick_delay_s, arrival_s, tag):
    """One leg: serve ``n_requests`` arriving open-loop every
    ``arrival_s`` seconds, every tick charged ``tick_delay_s`` of
    synthetic device time through the serve stage's delay knob."""
    import numpy as np
    from deepspeed_tpu.inference import ServeEngine
    from deepspeed_tpu.telemetry.cli import summarize

    import shutil
    import tempfile
    tel_dir = tempfile.mkdtemp(prefix=f"bench_serve_tel_{tag}_")
    prev = os.environ.get("DS_STAGE_DELAY_S")
    try:
        eng = ServeEngine(model, {
            "serving": {"slots": slots, "max_seq_len": 64,
                        "prefill_len": max(prompt_len, 1),
                        "flush_interval_ticks": 10},
            "telemetry": {"enabled": True, "output_path": tel_dir,
                          "memory": False},
        }, params=params)
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 256, (prompt_len,)).astype(np.int32)
                   for _ in range(n_requests)]
        # warm up (compile prefill + decode) BEFORE arming the delay and
        # the clock: the A/B measures scheduling, not XLA compile time
        eng.submit(prompts[0], max_new_tokens=2)
        eng.run_until_idle()
        os.environ["DS_STAGE_DELAY_S"] = f"serve:{tick_delay_s}"
        t0 = time.perf_counter()
        arrivals = [t0 + i * arrival_s for i in range(n_requests)]
        reqs = []
        nxt = 0
        while nxt < n_requests or eng.scheduler.active or eng.queue.qsize():
            now = time.perf_counter()
            while nxt < n_requests and arrivals[nxt] <= now:
                reqs.append(eng.submit(prompts[nxt],
                                       max_new_tokens=gen_tokens))
                nxt += 1
            if not eng.scheduler.active and eng.queue.qsize() == 0:
                time.sleep(min(0.002, arrival_s))
                continue
            eng.step()
        wall = time.perf_counter() - t0
        assert all(r.error is None for r in reqs)
        tokens = sum(len(r.tokens) for r in reqs)
        eng.close()
    finally:
        if prev is None:
            os.environ.pop("DS_STAGE_DELAY_S", None)
        else:
            os.environ["DS_STAGE_DELAY_S"] = prev
    with open(os.devnull, "w") as devnull:
        report = summarize(os.path.join(tel_dir, "events.jsonl"),
                           out=devnull)
    shutil.rmtree(tel_dir, ignore_errors=True)
    return {
        "slots": slots,
        "requests": n_requests,
        "tokens": tokens,
        "wall_s": wall,
        "tokens_per_s": tokens / wall,
        "token_p50_s": report.get("serve_token_p50_s"),
        "token_p99_s": report.get("serve_token_p99_s"),
    }


def run_ab(slots=8, n_requests=16, prompt_len=8, gen_tokens=16,
           tick_delay_s=0.02, arrival_s=0.0, out_dir="."):
    """Batched (slot pool) vs sequential (slots=1) under the same load
    and the same injected per-tick device time."""
    import jax
    model = _build_model()
    params = model.init(jax.random.PRNGKey(0))
    common = dict(n_requests=n_requests, prompt_len=prompt_len,
                  gen_tokens=gen_tokens, tick_delay_s=tick_delay_s,
                  arrival_s=arrival_s)
    batched = run_leg(model, params, slots=slots, tag="batched", **common)
    sequential = run_leg(model, params, slots=1, tag="sequential",
                         **common)
    rec = {
        "metric": "serve_continuous_batching_speedup",
        "value": batched["tokens_per_s"] / sequential["tokens_per_s"],
        "tick_delay_s": tick_delay_s,
        "batched": batched,
        "sequential": sequential,
    }
    with open(os.path.join(out_dir, "BENCH_serve.json"), "w") as f:
        json.dump(rec, f, indent=1)
    return rec


def main():
    import argparse
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--slots", type=int, default=8)
    parser.add_argument("--requests", type=int, default=16)
    parser.add_argument("--prompt", type=int, default=8)
    parser.add_argument("--gen", type=int, default=16)
    parser.add_argument("--delay", type=float, default=0.02,
                        help="injected per-tick device time (s)")
    args = parser.parse_args()
    rec = run_ab(slots=args.slots, n_requests=args.requests,
                 prompt_len=args.prompt, gen_tokens=args.gen,
                 tick_delay_s=args.delay)
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
