#!/usr/bin/env python
"""Serving bench: continuous batching vs sequential per-request decode,
and (``--paged``) the paged-KV concurrency/prefix-reuse A/B.

The claims under test are SCHEDULING claims, so they are CPU-provable
with the repo's established fault-injection idiom: ``DS_STAGE_DELAY_S=
serve:<s>`` charges every serving tick (admission prefill + masked
decode step) a synthetic device time, the way the prefetch/offload
benches inject collate/H2D latency.  A slot pool of size S then retires
up to S tokens per paid tick while the sequential leg (slots=1 — one
request decoded start-to-finish at a time) pays one tick per token:
wall-clock speedup ≈ S at saturation, which is exactly the
continuous-batching win Orca measured on real GPUs (PAPERS.md).

Both legs drive a synthetic open-loop load (arrivals on a fixed
schedule, independent of completions) through the telemetry hub;
tokens/s and p50/p99 per-token latency come from the same
``events.jsonl`` scalars the ``telemetry summarize`` serving row reads.

Emits BENCH_serve.json:
    {"metric": "serve_continuous_batching_speedup", "value": ...,
     "batched": {...}, "sequential": {...}}

``--paged ab`` runs the PAGED A/B (docs/serving.md) instead:

* **Admitted-slots-at-fixed-KV-bytes** (the headline): the same mixed
  short/long open-loop workload against (a) the pre-page slot cache
  whose ``slots × max_seq_len`` stride fills a fixed KV-byte budget and
  (b) a page pool of the SAME bytes — max concurrently admitted
  requests is a pure scheduling fact (no injected time needed); the
  paged pool admits ≥2× because short requests hold pages, not strides.
* **Prefix-reuse compute proof**: K requests sharing a prompt template
  with unique suffixes, prefix cache on vs off, under injected
  per-page prefill device time (the serve stage's delay unit in paged
  mode) — total prefill time collapses from ``K × template`` to
  ``1 template + K deltas``, read from the same tracer-timestamp
  windows the ``serve/prefill`` spans cover.

Emits BENCH_serve_paged.json:
    {"metric": "serve_paged_admitted_ratio", "value": ...,
     "paged": {...}, "legacy": {...}, "prefix": {...}}

``--spec ab`` runs the SPECULATIVE-DECODING A/B (docs/serving.md)
instead: the same workload served with ``speculate_k=k`` (draft params
= target params — the distilled-draft stand-in, so acceptance runs
near k) vs ``speculate_k=0``, under ``DS_STAGE_DELAY_S=serve:`` now
charging one unit per TARGET PASS (spec mode verifies k+1 positions
per pass; the non-spec leg pays one pass per token).  The headline is
the wall-clock-per-token ratio spec/non-spec, LOWER better, expected
to track ``1 / mean-accepted-length``; per-token time is proven from
the per-request token timestamps in events.jsonl (the same stamps the
``serve/verify_step``/``serve/decode_step`` tracer spans cover), and
the two legs' token streams are asserted identical (greedy parity).

Emits BENCH_serve_spec.json:
    {"metric": "serve_spec_wall_per_token_ratio", "value": ...,
     "spec": {...}, "baseline": {...}}

``--fleet`` runs the SERVING-FLEET A/B (docs/serving.md "serving
fleet") instead: the same open-loop workload against a 1-replica and a
2-replica fleet (real ``inference.replica`` subprocesses behind the
``inference/fleet.py`` router) under identical injected per-tick
device time — aggregate tokens/s should scale with the replica count
(the headline, expected >= 1.8x at 2 replicas) because each replica is
a full slot pool paying its own ticks.  A second leg drives the
replica-kill + autoscale-up trace: under sustained load one of two
replicas is SIGKILLed mid-stream; the router fails over every
queued-but-unstarted request (zero lost, asserted from the per-request
completion records), the queue-wait p99 breaches ``fleet.slo_p99_s``,
the autoscaler spawns a replacement, and the tail-phase p99 returns
under the SLO.

Emits BENCH_fleet.json:
    {"metric": "fleet_scaling_tokens_ratio", "value": ...,
     "one_replica": {...}, "two_replicas": {...}, "killtrace": {...}}
"""
import contextlib
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _build_model():
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    cfg = GPT2Config(vocab_size=256, n_positions=64, d_model=64,
                     n_layer=2, n_head=4, remat=None, attn_impl="dense")
    return GPT2Model(cfg)


# ---------------------------------------------------------------------------
# the shared leg harness (one copy, not one per mode)
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def _injected_delay(delay_s):
    """Arm ``DS_STAGE_DELAY_S=serve:<s>`` for one leg and restore the
    previous spec (re-parsing the cached spec both ways) — the
    save/arm/restore dance every A/B leg used to hand-copy."""
    from deepspeed_tpu.runtime.stages import reset_fault_injection
    prev = os.environ.get("DS_STAGE_DELAY_S")
    try:
        if delay_s is not None:
            os.environ["DS_STAGE_DELAY_S"] = f"serve:{delay_s}"
            reset_fault_injection()
        yield
    finally:
        if prev is None:
            os.environ.pop("DS_STAGE_DELAY_S", None)
        else:
            os.environ["DS_STAGE_DELAY_S"] = prev
        reset_fault_injection()


def _mode_kwargs(args, **attr_to_kw):
    """Per-mode default sentinels: every mode flag defaults to None at
    the parser, and ONLY explicitly-given values are forwarded, so
    each ``run_*_ab`` keeps its own mode defaults (the paged/spec/
    quant A/Bs want different slot counts, delays and budgets than the
    plain one).  One copy of the forwarding — the third mode no longer
    clones the other two's kwargs blocks."""
    kw = {}
    for attr, name in attr_to_kw.items():
        v = getattr(args, attr)
        if v is not None:
            kw[name] = v
    return kw


def _kv_budget_bytes(model, slots, max_seq_len):
    """The fixed KV-byte budget: what ``slots`` legacy fp strides cost,
    read from the cache spec (dtype itemsize included — fp16 and int8
    legs report TRUE bytes, not a hardcoded 4 bytes/elem)."""
    from deepspeed_tpu.inference.kv_cache import KVCacheSpec
    import jax.numpy as jnp
    cfg = model.config
    return KVCacheSpec(layers=cfg.n_layer, slots=slots,
                       heads=cfg.n_head, max_len=max_seq_len,
                       head_dim=cfg.d_head, dtype=jnp.float32).bytes


def _pages_for_budget(model, budget_bytes, page_len, quant=False):
    """(pages, page_bytes): allocatable pages a byte budget buys (+1
    for the scratch page, which spends no budget — it is masked-write
    storage, not request capacity), from the paged spec's
    ``page_bytes`` — the quant arm's sidecar-inclusive quantum, so the
    int8 leg's extra pages are real bytes, never a 4-bytes/elem
    assumption."""
    from deepspeed_tpu.inference.kv_cache import PagedKVCacheSpec
    import jax.numpy as jnp
    cfg = model.config
    spec = PagedKVCacheSpec(
        layers=cfg.n_layer, slots=1, heads=cfg.n_head, pages=1,
        page_len=page_len, head_dim=cfg.d_head, max_pages=1,
        dtype=(jnp.int8 if quant else jnp.float32), quant=quant)
    return budget_bytes // spec.page_bytes + 1, spec.page_bytes


def run_leg(model, params, *, slots, n_requests, prompt_len, gen_tokens,
            tick_delay_s, arrival_s, tag):
    """One leg: serve ``n_requests`` arriving open-loop every
    ``arrival_s`` seconds, every tick charged ``tick_delay_s`` of
    synthetic device time through the serve stage's delay knob."""
    import numpy as np
    from deepspeed_tpu.inference import ServeEngine
    from deepspeed_tpu.telemetry.cli import summarize

    import shutil
    import tempfile
    tel_dir = tempfile.mkdtemp(prefix=f"bench_serve_tel_{tag}_")
    eng = ServeEngine(model, {
        "serving": {"slots": slots, "max_seq_len": 64,
                    "prefill_len": max(prompt_len, 1),
                    "flush_interval_ticks": 10},
        "telemetry": {"enabled": True, "output_path": tel_dir,
                      "memory": False},
    }, params=params)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 256, (prompt_len,)).astype(np.int32)
               for _ in range(n_requests)]
    # warm up (compile prefill + decode) BEFORE arming the delay and
    # the clock: the A/B measures scheduling, not XLA compile time
    eng.submit(prompts[0], max_new_tokens=2)
    eng.run_until_idle()
    with _injected_delay(tick_delay_s):
        t0 = time.perf_counter()
        arrivals = [t0 + i * arrival_s for i in range(n_requests)]
        reqs = []
        nxt = 0
        while nxt < n_requests or eng.scheduler.active or eng.queue.qsize():
            now = time.perf_counter()
            while nxt < n_requests and arrivals[nxt] <= now:
                reqs.append(eng.submit(prompts[nxt],
                                       max_new_tokens=gen_tokens))
                nxt += 1
            if not eng.scheduler.active and eng.queue.qsize() == 0:
                time.sleep(min(0.002, arrival_s))
                continue
            eng.step()
        wall = time.perf_counter() - t0
    assert all(r.error is None for r in reqs)
    tokens = sum(len(r.tokens) for r in reqs)
    eng.close()
    with open(os.devnull, "w") as devnull:
        report = summarize(os.path.join(tel_dir, "events.jsonl"),
                           out=devnull)
    shutil.rmtree(tel_dir, ignore_errors=True)
    return {
        "slots": slots,
        "requests": n_requests,
        "tokens": tokens,
        "wall_s": wall,
        "tokens_per_s": tokens / wall,
        "token_p50_s": report.get("serve_token_p50_s"),
        "token_p99_s": report.get("serve_token_p99_s"),
    }


def run_ab(slots=8, n_requests=16, prompt_len=8, gen_tokens=16,
           tick_delay_s=0.02, arrival_s=0.0, out_dir="."):
    """Batched (slot pool) vs sequential (slots=1) under the same load
    and the same injected per-tick device time."""
    import jax
    model = _build_model()
    params = model.init(jax.random.PRNGKey(0))
    common = dict(n_requests=n_requests, prompt_len=prompt_len,
                  gen_tokens=gen_tokens, tick_delay_s=tick_delay_s,
                  arrival_s=arrival_s)
    batched = run_leg(model, params, slots=slots, tag="batched", **common)
    sequential = run_leg(model, params, slots=1, tag="sequential",
                         **common)
    rec = {
        "metric": "serve_continuous_batching_speedup",
        "value": batched["tokens_per_s"] / sequential["tokens_per_s"],
        "tick_delay_s": tick_delay_s,
        "batched": batched,
        "sequential": sequential,
    }
    with open(os.path.join(out_dir, "BENCH_serve.json"), "w") as f:
        json.dump(rec, f, indent=1)
    return rec


# ---------------------------------------------------------------------------
# --paged: page-table indirection + prefix reuse A/B (docs/serving.md)
# ---------------------------------------------------------------------------


def _run_mixed_leg(model, params, serving, requests, tag):
    """Serve a mixed short/long workload (all submitted up front — the
    saturation snapshot) and record the max concurrently ADMITTED
    requests: the number the KV layout, not the wall clock, decides."""
    from deepspeed_tpu.inference import ServeEngine
    eng = ServeEngine(model, {"serving": serving}, params=params)
    reqs = [eng.submit(p, max_new_tokens=g) for p, g in requests]
    max_concurrent = 0
    ticks = 0
    while eng.scheduler.active or eng._pending or eng.queue.qsize():
        eng.step()
        ticks += 1
        max_concurrent = max(max_concurrent, len(eng.scheduler.active))
        assert ticks < 100_000
    assert all(r.error is None for r in reqs), \
        [r.error for r in reqs if r.error]
    tokens = [r.tokens for r in reqs]
    # TRUE device bytes from the engine's memory plane (spec itemsize +
    # quant sidecars + param tree) — never recomputed by hand here, and
    # cross-checked against the REAL array bytes so a spec-accounting
    # bug (e.g. a sidecar miscount) cannot silently skew a fixed-byte
    # headline
    kv_bytes = eng.kv_bytes
    data_bytes = sum(int(eng.cache[key].nbytes) for key in eng.cache
                     if key != "lengths")
    assert data_bytes == eng.cache_spec.bytes, \
        (data_bytes, eng.cache_spec.bytes)
    param_bytes = eng.param_bytes
    truncated = sum(r.finish_reason == "kv_capacity" for r in reqs)
    eng.close()
    return {"tag": tag, "kv_bytes": kv_bytes,
            "param_bytes": param_bytes,
            "max_concurrent": max_concurrent, "ticks": ticks,
            "requests": len(reqs),
            "kv_capacity_finishes": truncated,
            "tokens_total": sum(len(t) for t in tokens)}, tokens


def _run_prefix_leg(model, params, serving, prompts, gen_tokens,
                    tick_delay_s):
    """Serve template-sharing prompts under injected per-page prefill
    device time; total prefill seconds comes from the same windows the
    ``serve/prefill`` tracer spans cover (req.prefill_s)."""
    from deepspeed_tpu.inference import ServeEngine
    eng = ServeEngine(model, {"serving": serving}, params=params)
    # compile prefill/decode BEFORE arming the delay: the A/B
    # measures scheduling, not XLA compile time
    eng.submit(prompts[0][:1], max_new_tokens=1)
    eng.run_until_idle()
    with _injected_delay(tick_delay_s):
        reqs = [eng.submit(p, max_new_tokens=gen_tokens) for p in prompts]
        eng.run_until_idle()
    assert all(r.error is None for r in reqs)
    out = {
        "prefill_total_s": sum(r.prefill_s for r in reqs),
        "computed_tokens": [r.computed_len for r in reqs],
        "shared_tokens": [r.shared_len for r in reqs],
        "prefix_hits": eng.prefix.hits if eng.prefix else 0,
    }
    tokens = [r.tokens for r in reqs]
    eng.close()
    return out, tokens


def run_paged_ab(kv_budget_slots=4, max_seq_len=64, page_len=8,
                 n_requests=24, long_every=4, template_len=24,
                 prefix_k=6, tick_delay_s=0.03, out_dir="."):
    """The paged A/B: (1) admitted concurrency at a fixed KV-byte
    budget under a short/long mix, (2) prefix-reuse prefill compute.
    ``kv_budget_slots`` sets the budget: the slot count whose fixed
    strides exactly spend it on the legacy arm."""
    import jax
    import numpy as np
    model = _build_model()
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)

    # -- leg 1: admitted slots at fixed KV bytes ------------------------
    # budget = kv_budget_slots full strides; the page pool spends the
    # same BYTES as pages (+1 scratch page) — both sides read their
    # dtype itemsize from the cache specs, never a 4-bytes/elem
    # assumption (the fp16/int8 legs of --quant ride the same helper)
    budget_bytes = _kv_budget_bytes(model, kv_budget_slots, max_seq_len)
    pages, _ = _pages_for_budget(model, budget_bytes, page_len)
    short = dict(prompt=4, gen=4)       # 8 live tokens -> 1 page
    long = dict(prompt=template_len, gen=16)
    requests = []
    for i in range(n_requests):
        spec = long if (i % long_every == long_every - 1) else short
        requests.append((list(rng.integers(0, 256, (spec["prompt"],))),
                         spec["gen"]))
    legacy, tok_l = _run_mixed_leg(
        model, params,
        {"slots": kv_budget_slots, "max_seq_len": max_seq_len,
         "prefill_len": template_len + page_len, "queue_capacity": 256},
        requests, "legacy")
    paged, tok_p = _run_mixed_leg(
        model, params,
        {"slots": 4 * kv_budget_slots, "max_seq_len": max_seq_len,
         "prefill_len": template_len + page_len, "queue_capacity": 256,
         "page_len": page_len, "pages": pages},
        requests, "paged")
    # over-subscribing the pool may TRUNCATE a long request at pool
    # exhaustion (the pool-aware kv_capacity finish — the documented
    # backpressure, docs/serving.md); it must never DIVERGE: every
    # paged stream matches the legacy arm token for token up to its
    # length
    truncated = 0
    for tl, tp in zip(tok_l, tok_p):
        assert tp == tl[:len(tp)], "paged arm diverged from legacy"
        truncated += tp != tl
    paged["truncated"] = truncated

    # -- leg 2: prefix reuse — compute ∝ 1 template + K deltas ----------
    template = list(rng.integers(0, 256, (template_len,)))
    prompts = [template + list(rng.integers(0, 256, (4,)))
               for _ in range(prefix_k)]
    serving = {"slots": 4, "max_seq_len": max_seq_len,
               "prefill_len": template_len + page_len,
               "page_len": page_len, "queue_capacity": 256}
    on, tok_on = _run_prefix_leg(
        model, params, {**serving, "prefix_cache": True}, prompts, 2,
        tick_delay_s)
    off, tok_off = _run_prefix_leg(
        model, params, {**serving, "prefix_cache": False}, prompts, 2,
        tick_delay_s)
    assert tok_on == tok_off, "prefix cache changed the token streams"

    rec = {
        "metric": "serve_paged_admitted_ratio",
        "value": paged["max_concurrent"] / legacy["max_concurrent"],
        "page_len": page_len,
        "paged": paged,
        "legacy": legacy,
        "prefix": {
            "k": prefix_k,
            "template_len": template_len,
            "tick_delay_s": tick_delay_s,
            "on": on,
            "off": off,
            "prefill_ratio": (on["prefill_total_s"]
                              / max(off["prefill_total_s"], 1e-9)),
        },
    }
    with open(os.path.join(out_dir, "BENCH_serve_paged.json"), "w") as f:
        json.dump(rec, f, indent=1)
    return rec


# ---------------------------------------------------------------------------
# --quant: int8 weights + int8 KV pages A/B (docs/serving.md)
# ---------------------------------------------------------------------------


def _token_agreement(a, b):
    """Positionwise greedy-stream agreement over two request lists —
    REPORTED, never asserted equal: quantization is a tolerance tier,
    not a bitwise one (docs/serving.md)."""
    total = same = 0
    for ta, tb in zip(a, b):
        for x, y in zip(ta, tb):
            total += 1
            same += x == y
    return same / max(total, 1)


def run_quant_ab(kv_budget_slots=4, max_seq_len=64, page_len=8,
                 slots=64, n_requests=96, long_every=4, out_dir="."):
    """The quantized-serving A/B (docs/serving.md "quantized serving").

    **KV leg (the headline)**: the same mixed short/long workload
    against fp pages and int8 pages whose pools spend the SAME byte
    budget (``kv_budget_slots`` legacy fp strides, bytes via the cache
    specs — sidecars included).  Request geometry is page-exact
    (prompt+gen fills whole pages), so nothing ever appends past its
    admission allocation: 0 truncations by construction, and the max
    concurrently admitted count is a pure bytes-per-page fact.

    **Weights leg**: the same workload with weights='int8' (fp pages)
    — params HBM from the ``serve_param_bytes`` plane (the param-tree
    bytes ``collect_memory_stats()`` would show on a device with
    allocator stats; the raw snapshot rides along), expected >= 1.8x
    smaller.  Greedy token agreement vs the fp leg is REPORTED for
    every arm, never asserted equal."""
    import jax
    import numpy as np
    from deepspeed_tpu.runtime.utils import collect_memory_stats
    model = _build_model()
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)

    budget_bytes = _kv_budget_bytes(model, kv_budget_slots, max_seq_len)
    pages_fp, _ = _pages_for_budget(model, budget_bytes, page_len)
    pages_q, _ = _pages_for_budget(model, budget_bytes, page_len,
                                   quant=True)
    # page-exact geometry: short = 1 page live, long = 3 pages live —
    # decode never crosses a page boundary, so the pool can never dry
    # mid-request (0 kv_capacity finishes, asserted below); gen=4
    # keeps every request alive across several ticks so the sampled
    # max-concurrency sees the full admitted wave
    short = dict(prompt=page_len - 4, gen=4)
    long = dict(prompt=3 * page_len - 4, gen=4)
    requests = []
    for i in range(n_requests):
        spec = long if (i % long_every == long_every - 1) else short
        requests.append((list(rng.integers(0, 256, (spec["prompt"],))),
                         spec["gen"]))
    base = {"slots": slots, "max_seq_len": max_seq_len,
            "prefill_len": long["prompt"], "queue_capacity": 256,
            "page_len": page_len, "prefix_cache": False}
    fp, tok_fp = _run_mixed_leg(
        model, params, {**base, "pages": pages_fp}, requests, "fp")
    q, tok_q = _run_mixed_leg(
        model, params,
        {**base, "pages": pages_q,
         "quantization": {"kv": "int8"}}, requests, "int8")
    # allocatable pages spend <= the budget by construction of
    # _pages_for_budget; the REAL accounting guard is the per-leg
    # array-bytes == spec-bytes assert in _run_mixed_leg, plus: the
    # int8 pool (sidecar included) must not cost more device bytes
    # than the fp pool it beats
    assert q["kv_bytes"] <= fp["kv_bytes"], (q["kv_bytes"],
                                             fp["kv_bytes"])
    truncations = fp["kv_capacity_finishes"] + q["kv_capacity_finishes"]
    assert truncations == 0, "page-exact workload truncated"

    # weights leg: same workload, int8 weights over fp pages
    w8, tok_w8 = _run_mixed_leg(
        model, params,
        {**base, "pages": pages_fp,
         "quantization": {"weights": "int8"}}, requests, "weights_int8")
    params_ratio = fp["param_bytes"] / w8["param_bytes"]

    rec = {
        "metric": "serve_quant_admitted_ratio",
        "value": q["max_concurrent"] / fp["max_concurrent"],
        "kv_budget_bytes": budget_bytes,
        "page_len": page_len,
        "truncations": truncations,
        "int8": q,
        "fp": fp,
        "weights": {
            "leg": w8,
            "param_bytes_fp": fp["param_bytes"],
            "param_bytes_int8": w8["param_bytes"],
            "params_hbm_ratio": params_ratio,
            # allocator-stats snapshot (empty device list on the CPU
            # oracle; real HBM on TPU) — the same plane
            # collect_memory_stats() feeds the telemetry gauges
            "collect_memory_stats": collect_memory_stats(),
        },
        "token_agreement_vs_fp": {
            "kv_int8": _token_agreement(tok_fp, tok_q),
            "weights_int8": _token_agreement(tok_fp, tok_w8),
        },
    }
    with open(os.path.join(out_dir, "BENCH_serve_quant.json"), "w") as f:
        json.dump(rec, f, indent=1)
    return rec


# ---------------------------------------------------------------------------
# --spec: draft-verify speculative decoding A/B (docs/serving.md)
# ---------------------------------------------------------------------------


def _run_spec_leg(model, params, serving, draft_params, prompts,
                  gen_tokens, pass_delay_s, tag):
    """Serve the workload under injected per-PASS device time; wall
    per token comes from the per-request token timestamps the
    events.jsonl serve_request records carry (the tracer-span window),
    mean accepted length from the engine's speculation scalars."""
    from deepspeed_tpu.inference import ServeEngine

    import shutil
    import tempfile
    tel_dir = tempfile.mkdtemp(prefix=f"bench_serve_spec_{tag}_")
    eng = ServeEngine(model, {
        "serving": serving,
        "telemetry": {"enabled": True, "output_path": tel_dir,
                      "memory": False},
    }, params=params, draft_params=draft_params)
    # compile every program BEFORE arming the delay: the A/B
    # measures scheduling, not XLA compile time
    warm = eng.submit(prompts[0][:4], max_new_tokens=2)
    eng.run_until_idle()
    # the warmup's truncated pass must not contaminate the
    # measured statistics: reset the speculation counters and
    # remember its rid so the events.jsonl scan below skips it
    warm_rid = warm.rid
    eng._spec_passes = 0
    eng._spec_accepted_n = 0
    eng._spec_proposed_n = 0
    with _injected_delay(pass_delay_s):
        t0 = time.perf_counter()
        reqs = [eng.submit(p, max_new_tokens=gen_tokens)
                for p in prompts]
        eng.run_until_idle()
        wall = time.perf_counter() - t0
    assert all(r.error is None for r in reqs)
    tokens = [r.tokens for r in reqs]
    n_tokens = sum(len(t) for t in tokens)
    passes = eng._spec_passes
    mal = ((eng._spec_accepted_n + passes) / passes
           if passes else 1.0)
    eng.close()
    # per-token decode time from the completion records' timestamps —
    # the same windows the decode/verify spans cover (PR 9
    # attribution).  STEADY-STATE only: a request's first decode
    # interval absorbs the co-admitted requests' prefill delay (every
    # admission charges one unit in BOTH legs), so counting starts at
    # the second nonzero interval — a spec block is one nonzero
    # interval followed by its burst of zero-stamped tokens, so this
    # drops exactly the first (polluted) block on either leg
    dec_s = dec_n = 0.0
    with open(os.path.join(tel_dir, "events.jsonl")) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("kind") == "serve_request" and rec.get("tokens") \
                    and rec.get("rid") != warm_rid:
                nonzero = 0
                for t in rec.get("token_times_s") or []:
                    if t > 0:
                        nonzero += 1
                    if nonzero >= 2:
                        dec_s += float(t)
                        dec_n += 1
    shutil.rmtree(tel_dir, ignore_errors=True)
    return {
        "tag": tag,
        "requests": len(tokens),
        "tokens": n_tokens,
        "wall_s": wall,
        "wall_per_token_s": wall / max(n_tokens, 1),
        "decode_s_per_token": dec_s / max(dec_n, 1),
        "mean_accepted_len": mal,
    }, tokens


def run_spec_ab(k=4, slots=6, n_requests=6, prompt_len=8,
                gen_tokens=None, pass_delay_s=0.25, out_dir="."):
    """Speculative vs plain decode under the same injected per-pass
    device time.  The draft shares the target's params (acceptance
    ~= k), so wall/token should collapse toward 1/(k+1); the headline
    ratio is expected ∝ 1/mean-accepted-length.

    Geometry keeps the proof clean: slots cover the whole workload
    (every admission — whose prefill delay is identical in both legs —
    lands before the first decode tick, so the decode-phase intervals
    are pure per-pass time) and the DEFAULT generation budget is
    derived block-aligned from the given k (``gen_tokens - 1``
    divisible by ``k + 1``: no half-used final pass skewing the mean
    accepted length)."""
    if gen_tokens is None:
        gen_tokens = 4 * (k + 1) + 1
    import jax
    import numpy as np
    model = _build_model()
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 256, (prompt_len,)).astype(np.int32)
               for _ in range(n_requests)]
    base_serving = {"slots": slots, "max_seq_len": 64,
                    "prefill_len": max(prompt_len, 4),
                    "queue_capacity": 256,
                    "flush_interval_ticks": 10}
    spec_serving = dict(base_serving)
    spec_serving.update({
        "speculate_k": k,
        # the draft IS the target config here: with shared params the
        # proposals match and acceptance runs near k — the CPU stand-in
        # for a distilled draft
        "draft": {"d_model": 64, "n_layer": 2, "n_head": 4},
    })
    spec, tok_s = _run_spec_leg(model, params, spec_serving, params,
                                prompts, gen_tokens, pass_delay_s,
                                "spec")
    base, tok_b = _run_spec_leg(model, params, base_serving, None,
                                prompts, gen_tokens, pass_delay_s,
                                "baseline")
    # greedy parity: speculation must never change what is emitted
    assert tok_s == tok_b, "speculative stream diverged from baseline"
    rec = {
        # headline: decode-phase wall per token from the per-request
        # token timestamps (prefill admission pays the same one unit
        # per request in both legs and is excluded by construction —
        # it is reported inside each leg's wall_s)
        "metric": "serve_spec_wall_per_token_ratio",
        "value": (spec["decode_s_per_token"]
                  / max(base["decode_s_per_token"], 1e-9)),
        "speculate_k": k,
        "pass_delay_s": pass_delay_s,
        "expected_ratio_1_over_mal": 1.0 / spec["mean_accepted_len"],
        "total_wall_ratio": (spec["wall_per_token_s"]
                             / base["wall_per_token_s"]),
        "spec": spec,
        "baseline": base,
    }
    with open(os.path.join(out_dir, "BENCH_serve_spec.json"), "w") as f:
        json.dump(rec, f, indent=1)
    return rec


# ---------------------------------------------------------------------------
# --fleet: router + replicated engines + SLO autoscaling A/B
# ---------------------------------------------------------------------------


def _fleet_config(replicas, *, min_replicas=1, max_replicas=None,
                  slots=4, slo_p99_s=30.0, up_window_s=1.0,
                  down_window_s=600.0):
    """One fleet ds_config: tiny deterministic model (every replica
    inits identical params from the shared seed), short hysteresis
    windows sized for a CPU bench, scale-down effectively off (the
    legs measure throughput/failover, not retirement)."""
    return {
        "serving": {"slots": slots, "max_seq_len": 64,
                    "prefill_len": 8, "queue_capacity": 512,
                    "flush_interval_ticks": 10},
        "telemetry": {"enabled": False},
        "fleet": {"replicas": replicas, "min_replicas": min_replicas,
                  "max_replicas": max_replicas or max(replicas, 1),
                  "slo_p99_s": slo_p99_s,
                  "scale_up_window_s": up_window_s,
                  "scale_down_window_s": down_window_s,
                  "spawn_timeout_s": 120.0, "backoff_base_s": 0.2,
                  "heartbeat_timeout_s": 60.0},
        "fleet_model": {"vocab_size": 256, "n_positions": 64,
                        "d_model": 64, "n_layer": 2, "n_head": 4,
                        "attn_impl": "dense", "seed": 0},
    }


def _fleet_prompts(n, prompt_len=6, seed=0):
    import numpy as np
    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(0, 256, (prompt_len,))]
            for _ in range(n)]


def _run_fleet_leg(n_replicas, n_requests, gen_tokens, tick_delay_s,
                   tag):
    """One scaling leg: spawn the fleet, warm every replica (compile
    happens off the clock), then serve the saturation workload (all
    requests submitted up front) under injected per-tick device time.
    Aggregate tokens/s comes from the router-side completion stream;
    the wall window starts at the first measured submit."""
    import shutil
    import tempfile
    from deepspeed_tpu.inference.fleet import FleetRouter
    d = tempfile.mkdtemp(prefix=f"bench_fleet_{tag}_")
    prompts = _fleet_prompts(n_requests)
    with _injected_delay(tick_delay_s):
        router = FleetRouter(_fleet_config(n_replicas), fleet_dir=d)
        try:
            router.start()
            # one warm request per replica: JSQ spreads them, so every
            # replica compiles prefill+decode before the clock starts
            for _ in range(n_replicas):
                router.submit(prompts[0], max_new_tokens=2)
            router.run_until_idle(max_s=180)
            t0 = time.perf_counter()
            reqs = [router.submit(p, max_new_tokens=gen_tokens)
                    for p in prompts]
            router.run_until_idle(max_s=600)
            wall = time.perf_counter() - t0
            assert all(r.error is None for r in reqs), \
                [repr(r.error) for r in reqs if r.error]
            tokens = sum(len(r.tokens) for r in reqs)
            p99 = router.queue_wait_p99(window_s=1e9)
        finally:
            router.close()
            shutil.rmtree(d, ignore_errors=True)
    return {"replicas": n_replicas, "requests": n_requests,
            "tokens": tokens, "wall_s": wall,
            "tokens_per_s": tokens / wall,
            "queue_wait_p99_s": p99}


def _read_fleet_records(fleet_dir):
    from deepspeed_tpu.telemetry.cli import _read_jsonl_tolerant
    records, _ = _read_jsonl_tolerant(
        os.path.join(fleet_dir, "events.jsonl"))
    return records


def _run_fleet_killtrace(slo_p99_s, n_requests, arrival_s, gen_tokens,
                         tick_delay_s, kill_after_s):
    """The replica-kill + autoscale-up trace: 2 replicas under open-
    loop load sized ABOVE one replica's capacity, one replica
    SIGKILLed mid-stream.  Queued-but-unstarted requests fail over
    (zero lost — asserted from the completion records), queue-wait p99
    breaches the SLO while one replica carries everything, the
    autoscaler spawns a replacement, and the tail-phase p99 lands back
    under the SLO."""
    import shutil
    import tempfile
    from deepspeed_tpu.inference.fleet import FleetRouter
    d = tempfile.mkdtemp(prefix="bench_fleet_kill_")
    prompts = _fleet_prompts(n_requests, seed=1)
    cfg = _fleet_config(2, min_replicas=1, max_replicas=3, slots=2,
                        slo_p99_s=slo_p99_s, up_window_s=0.5)
    with _injected_delay(tick_delay_s):
        router = FleetRouter(cfg, fleet_dir=d)
        try:
            router.start()
            initial_ids = sorted(router.replicas)
            for _ in range(2):
                router.submit(prompts[0], max_new_tokens=2)
            router.run_until_idle(max_s=180)
            t0 = time.perf_counter()
            reqs = []
            submit_ts = []
            killed = None
            recover_t = None
            nxt = 0
            while nxt < n_requests or not router.idle():
                now = time.perf_counter() - t0
                while nxt < n_requests and nxt * arrival_s <= now:
                    reqs.append(router.submit(
                        prompts[nxt], max_new_tokens=gen_tokens))
                    submit_ts.append(now)
                    nxt += 1
                if killed is None and now >= kill_after_s:
                    # kill the busier initial replica: guaranteed
                    # queued-but-unstarted work to fail over
                    victims = [r for r in router.replicas.values()
                               if r.id in initial_ids
                               and r.state == "ready"]
                    victims.sort(key=lambda r: -len(r.outstanding))
                    killed = victims[0].id
                    router.kill_replica(killed)
                if recover_t is None and any(
                        rid not in initial_ids
                        and router.replicas[rid].state == "ready"
                        for rid in router.replicas):
                    recover_t = time.perf_counter() - t0
                router.poll(0.01)
            wall = time.perf_counter() - t0
            records = _read_fleet_records(d)
        finally:
            router.close()
            shutil.rmtree(d, ignore_errors=True)

    # zero queued-but-unstarted requests lost: asserted from the
    # per-request completion records — every failed record must have
    # started=True (its tokens were already streaming: typed
    # ReplicaFailure, not silently-retriable work)
    completions = {r["rid"]: r for r in records
                   if r.get("kind") == "fleet_request"}
    submits = [r for r in records if r.get("kind") == "fleet_submit"]
    assert len(completions) == len(submits), \
        f"dangling requests: {len(submits) - len(completions)}"
    lost = [r for r in completions.values()
            if r.get("error") and not r.get("started")]
    assert not lost, f"queued-but-unstarted requests lost: {lost}"
    failovers = sum(int(r.get("failed_over") or 0) for r in records
                    if r.get("kind") == "replica_dead")
    assert failovers > 0, "the kill never hit queued work"
    midstream = [r for r in completions.values() if r.get("error")]
    # p99 attribution by phase: degraded = submitted after the kill
    # while only one replica served; recovered = submitted after the
    # autoscaled replacement came up.  The SLO claim is about the tail.
    assert recover_t is not None, "autoscale never spawned"

    from deepspeed_tpu.inference.fleet import _p99

    def _phase_p99(lo, hi):
        return _p99([
            completions[r.rid]["queue_wait_s"]
            for r, t in zip(reqs, submit_ts)
            if lo <= t < hi and r.rid in completions
            and completions[r.rid].get("queue_wait_s") is not None])

    p99_degraded = _phase_p99(kill_after_s, recover_t)
    # the recovered phase starts one backlog-drain grace after the
    # replacement came up (the surplus capacity needs a moment to eat
    # the degraded phase's queue); the claim is the TAIL holds the SLO
    drain_grace_s = min(2.0, (wall - recover_t) / 3)
    p99_recovered = _phase_p99(recover_t + drain_grace_s, 1e9)
    assert p99_recovered is not None and p99_recovered < slo_p99_s, \
        (p99_recovered, slo_p99_s)
    return {
        "slo_p99_s": slo_p99_s,
        "requests": n_requests,
        "arrival_s": arrival_s,
        "tick_delay_s": tick_delay_s,
        "killed_replica": killed,
        "kill_after_s": kill_after_s,
        "recover_after_s": recover_t,
        "wall_s": wall,
        "failovers": failovers,
        "midstream_failed": len(midstream),
        "unstarted_lost": 0,
        "queue_wait_p99_degraded_s": p99_degraded,
        "queue_wait_p99_recovered_s": p99_recovered,
    }


def run_fleet_ab(n_requests=16, gen_tokens=16, tick_delay_s=0.04,
                 slo_p99_s=1.5, out_dir="."):
    """The fleet A/B: aggregate tokens/s at 1 vs 2 replicas under
    identical injected per-tick device time (the headline, >= 1.8x
    expected — each replica is an independent slot pool paying its own
    ticks), plus the replica-kill + autoscale-up trace."""
    one = _run_fleet_leg(1, n_requests, gen_tokens, tick_delay_s,
                         "one")
    two = _run_fleet_leg(2, n_requests, gen_tokens, tick_delay_s,
                         "two")
    # 100 requests at 0.12s spacing = a 12s open-loop window: the kill
    # lands early, the autoscaled replacement comes up mid-window, and
    # the tail requests measure the RECOVERED fleet's queue wait
    kill = _run_fleet_killtrace(
        slo_p99_s=slo_p99_s, n_requests=100, arrival_s=0.12,
        gen_tokens=9, tick_delay_s=tick_delay_s, kill_after_s=1.2)
    rec = {
        "metric": "fleet_scaling_tokens_ratio",
        "value": two["tokens_per_s"] / one["tokens_per_s"],
        "tick_delay_s": tick_delay_s,
        "one_replica": one,
        "two_replicas": two,
        "killtrace": kill,
    }
    with open(os.path.join(out_dir, "BENCH_fleet.json"), "w") as f:
        json.dump(rec, f, indent=1)
    return rec


def main():
    import argparse
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--slots", type=int, default=None,
                        help="slot pool size (default 8); with --paged "
                             "this is the KV-byte budget in legacy-slot "
                             "strides (default 4 there)")
    parser.add_argument("--requests", type=int, default=None,
                        help="workload size (default 16; 24 with "
                             "--paged)")
    parser.add_argument("--prompt", type=int, default=None,
                        help="prompt length (unpaged and --spec A/Bs, "
                             "default 8 — the paged/quant legs drive a "
                             "fixed short/long mix)")
    parser.add_argument("--gen", type=int, default=None,
                        help="tokens per request (default 16; with "
                             "--spec, 4*(k+1)+1 — block-aligned for "
                             "the given --k)")
    parser.add_argument("--delay", type=float, default=None,
                        help="injected device time (s): per TICK for "
                             "the unpaged A/B (default 0.02), per "
                             "prefill PAGE for the --paged prefix leg "
                             "(default 0.03)")
    parser.add_argument("--paged", choices=("on", "off", "ab"),
                        default=None,
                        help="run the paged-KV A/B instead "
                             "(BENCH_serve_paged.json); 'ab' = both "
                             "arms (on/off are accepted for symmetry "
                             "with the other benches and also run the "
                             "full A/B — both arms are needed for the "
                             "ratio)")
    parser.add_argument("--spec", choices=("on", "off", "ab"),
                        default=None,
                        help="run the speculative-decoding A/B instead "
                             "(BENCH_serve_spec.json); both arms always "
                             "run — the headline is the spec/non-spec "
                             "wall-per-token ratio")
    parser.add_argument("--quant", choices=("on", "off", "ab"),
                        default=None,
                        help="run the quantized-serving A/B instead "
                             "(BENCH_serve_quant.json): admitted "
                             "concurrency at a fixed KV-byte budget, "
                             "int8 vs fp pages, plus the int8-weights "
                             "params-HBM leg; both arms always run — "
                             "the headline is a ratio")
    parser.add_argument("--k", type=int, default=4,
                        help="draft tokens per tick for --spec "
                             "(default 4)")
    parser.add_argument("--fleet", choices=("on", "off", "ab"),
                        default=None,
                        help="run the serving-fleet A/B instead "
                             "(BENCH_fleet.json): aggregate tokens/s "
                             "at 1 vs 2 replicas under identical "
                             "injected per-tick device time, plus the "
                             "replica-kill + autoscale-up trace; both "
                             "arms always run — the headline is the "
                             "2/1 tokens-per-second ratio")
    args = parser.parse_args()
    # one shared dispatch harness: every mode forwards ONLY the flags
    # the user gave (None sentinels), so each run_*_ab keeps its own
    # per-mode defaults — no more per-mode kwargs blocks to clone
    if args.fleet is not None:
        rec = run_fleet_ab(**_mode_kwargs(
            args, requests="n_requests", gen="gen_tokens",
            delay="tick_delay_s"))
    elif args.spec is not None:
        rec = run_spec_ab(**{"k": args.k}, **_mode_kwargs(
            args, delay="pass_delay_s", slots="slots",
            requests="n_requests", gen="gen_tokens",
            prompt="prompt_len"))
    elif args.quant is not None:
        rec = run_quant_ab(**_mode_kwargs(
            args, slots="kv_budget_slots", requests="n_requests"))
    elif args.paged is not None:
        rec = run_paged_ab(**_mode_kwargs(
            args, delay="tick_delay_s", slots="kv_budget_slots",
            requests="n_requests"))
    else:
        rec = run_ab(**_mode_kwargs(
            args, slots="slots", requests="n_requests",
            prompt="prompt_len", gen="gen_tokens",
            delay="tick_delay_s"))
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
