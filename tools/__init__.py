# Repo tooling namespace (not shipped in the wheel — see setup.py
# packages list).  Lets ``python -m tools.jaxlint`` work from a clean
# checkout.
