"""Build the real-text convergence corpus from prose already on the box.

The convergence tier (VERDICT round-4 #2 / round-5 #3) needs a few MB of
*real* natural-language text — the reference trains Megatron GPT-2 on
WebText-style corpora and diffs loss curves against checked-in baselines
(reference: tests/model/Megatron_GPT2/test_common.py:12+).  This image has
zero egress, so the corpus is harvested from genuine human-written English
that ships with the environment:

  * docstrings of every installed Python package + the stdlib (parsed with
    ``ast`` — technical English with natural Zipfian token statistics)
  * ``*.md`` / ``*.rst`` package documentation
  * ``/usr/share/common-licenses`` (legal prose, small)

Paragraph-level dedup, a printable-ASCII-ratio filter, and a seeded
shuffle produce a stable corpus.  Output: ``data/corpus.txt.gz``.

Usage:  python tools/build_corpus.py [--target-mb 6] [--out data/corpus.txt.gz]
"""
import argparse
import ast
import gzip
import hashlib
import io
import os
import random
import re
import sys
import sysconfig


def _iter_py_files(roots):
    for root in roots:
        for dirpath, dirnames, filenames in os.walk(root):
            # skip tests/vendored minified junk; keep walks bounded
            dirnames[:] = [d for d in dirnames
                           if d not in ("test", "tests", "__pycache__",
                                        "node_modules", ".git")]
            for fn in filenames:
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def _docstrings_from(path):
    try:
        with open(path, "r", encoding="utf-8", errors="ignore") as f:
            tree = ast.parse(f.read())
    except (SyntaxError, ValueError, OSError, RecursionError):
        return
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            doc = ast.get_docstring(node, clean=True)
            if doc:
                yield doc


_WORD_RE = re.compile(r"[A-Za-z]{2,}")


def _looks_english(par: str) -> bool:
    """Keep paragraphs that are mostly prose, not tables/signatures/code."""
    if len(par) < 120:
        return False
    printable = sum(c.isprintable() or c in "\n\t" for c in par)
    if printable / len(par) < 0.97:
        return False
    words = _WORD_RE.findall(par)
    # prose has a healthy density of alphabetic words
    return len(words) >= 12 and sum(len(w) for w in words) / len(par) > 0.45


def _paragraphs(text):
    for par in re.split(r"\n\s*\n", text):
        par = par.strip()
        if _looks_english(par):
            yield par


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--target-mb", type=float, default=6.0)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "data", "corpus.txt.gz"))
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    roots = []
    for p in sys.path:
        if p and os.path.isdir(p) and "repo" not in p:
            roots.append(p)
    roots.append(sysconfig.get_paths()["stdlib"])

    seen = set()
    pars = []
    total = 0
    budget = int(args.target_mb * 1e6)

    # documentation files first (highest prose density)
    doc_files = []
    for root in roots:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in filenames:
                if fn.endswith((".md", ".rst")) or (
                        "license" in fn.lower() and fn.endswith(".txt")):
                    doc_files.append(os.path.join(dirpath, fn))
    for lic_dir in ("/usr/share/common-licenses",):
        if os.path.isdir(lic_dir):
            doc_files += [os.path.join(lic_dir, f)
                          for f in os.listdir(lic_dir)
                          if os.path.isfile(os.path.join(lic_dir, f))]

    def add(par):
        nonlocal total
        h = hashlib.sha1(par.encode()).digest()[:8]
        if h in seen:
            return
        seen.add(h)
        pars.append(par)
        total += len(par) + 2

    for path in sorted(doc_files):
        try:
            with open(path, "r", encoding="utf-8", errors="ignore") as f:
                for par in _paragraphs(f.read()):
                    add(par)
        except OSError:
            continue

    # then docstrings until the budget fills
    for path in sorted(_iter_py_files(roots)):
        if total >= budget:
            break
        for doc in _docstrings_from(path):
            for par in _paragraphs(doc):
                add(par)

    rng = random.Random(args.seed)
    rng.shuffle(pars)

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    buf = io.StringIO()
    for par in pars:
        buf.write(par)
        buf.write("\n\n")
    text = buf.getvalue()
    # mtime=0 → byte-reproducible archive for a given corpus
    with open(args.out, "wb") as raw:
        with gzip.GzipFile(fileobj=raw, mode="wb", mtime=0) as f:
            f.write(text.encode("utf-8"))
    print(f"{len(pars)} paragraphs, {total / 1e6:.2f} MB raw, "
          f"{os.path.getsize(args.out) / 1e6:.2f} MB gzipped -> {args.out}")


if __name__ == "__main__":
    main()
