"""CLI: ``python -m tools.jaxlint [paths] [--format github] ...``.

Exit status: 0 when every finding is baselined or suppressed, 1 when new
findings exist, 2 on usage errors (including a missing or corrupt
baseline file — see :class:`~.core.BaselineError`).  Stdlib only — runs
on a clean checkout before any environment is built.

v2 surface: ``--contracts-only`` runs just the project-level
cross-artifact rules (JL102–JL104; the cheap CI pre-flight), and
``--registry-dump`` prints the pass-1 :class:`ProjectRegistry` as JSON
for tests and ``diagnose`` tooling.
"""
from __future__ import annotations

import argparse
import json
import sys

from .core import (RULE_REGISTRY, BaselineError, default_baseline_path,
                   lint_paths, load_baseline, write_baseline)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.jaxlint",
        description="Static analysis for JAX tracer-safety hazards "
                    "(host syncs, use-after-donation, sharding and "
                    "recompilation bugs) plus project-wide "
                    "cross-artifact contracts (stages, metrics, "
                    "fault points, config keys). See docs/jaxlint.md.")
    p.add_argument("paths", nargs="*", default=["deepspeed_tpu"],
                   help="files or directories to lint "
                        "(default: deepspeed_tpu)")
    p.add_argument("--format", choices=("text", "github"), default="text",
                   help="finding format; 'github' emits ::error workflow "
                        "commands (paths are project-root-relative "
                        "regardless of the invocation cwd)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help=f"baseline file (default: {default_baseline_path()})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report baselined findings too")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept all current findings into the baseline "
                        "file and exit 0")
    p.add_argument("--select", default=None, metavar="IDS",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    p.add_argument("--contracts-only", action="store_true",
                   help="run only the project-level contract rules "
                        "(JL102-JL104) — the fast CI pre-flight")
    p.add_argument("--registry-dump", action="store_true",
                   help="print the pass-1 project registry as JSON "
                        "and exit")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        from .contracts import PROJECT_RULE_REGISTRY
        table = dict(RULE_REGISTRY)
        table.update(PROJECT_RULE_REGISTRY)
        for rule_id, cls in sorted(table.items()):
            print(f"{rule_id}  {cls.summary}")
        return 0

    if args.registry_dump:
        from .registry import ProjectRegistry, find_project_root
        root = find_project_root(args.paths)
        if root is None:
            print("jaxlint: no project root (docs/ + tools/) found "
                  f"above {', '.join(args.paths)}", file=sys.stderr)
            return 2
        reg = ProjectRegistry.build(root)
        print(json.dumps(reg.dump(), indent=2, sort_keys=True))
        return 0

    select = None
    if args.select:
        from .contracts import PROJECT_RULE_REGISTRY
        select = [s.strip() for s in args.select.split(",") if s.strip()]
        unknown = [s for s in select if s not in RULE_REGISTRY
                   and s not in PROJECT_RULE_REGISTRY]
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    try:
        findings = lint_paths(args.paths, rules=select,
                              contracts_only=args.contracts_only)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2

    try:
        if args.write_baseline:
            write_baseline(findings, args.baseline)
            print(f"baseline written: {len(findings)} finding(s) accepted")
            return 0
        baseline = {} if args.no_baseline else load_baseline(args.baseline)
    except BaselineError as e:
        print(str(e), file=sys.stderr)
        return 2
    fresh = [f for f in findings if f.key() not in baseline]
    for f in fresh:
        print(f.render(args.format))
    baselined = len(findings) - len(fresh)
    tail = f" ({baselined} baselined)" if baselined else ""
    print(f"jaxlint: {len(fresh)} finding(s){tail}", file=sys.stderr)
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
