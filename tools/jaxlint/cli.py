"""CLI: ``python -m tools.jaxlint [paths] [--format github] ...``.

Exit status: 0 when every finding is baselined or suppressed, 1 when new
findings exist, 2 on usage errors.  Stdlib only — runs on a clean
checkout before any environment is built.
"""
from __future__ import annotations

import argparse
import sys

from .core import (RULE_REGISTRY, default_baseline_path, lint_paths,
                   load_baseline, write_baseline)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.jaxlint",
        description="Static analysis for JAX tracer-safety hazards "
                    "(host syncs, use-after-donation, sharding and "
                    "recompilation bugs). See docs/jaxlint.md.")
    p.add_argument("paths", nargs="*", default=["deepspeed_tpu"],
                   help="files or directories to lint "
                        "(default: deepspeed_tpu)")
    p.add_argument("--format", choices=("text", "github"), default="text",
                   help="finding format; 'github' emits ::error workflow "
                        "commands")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help=f"baseline file (default: {default_baseline_path()})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report baselined findings too")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept all current findings into the baseline "
                        "file and exit 0")
    p.add_argument("--select", default=None, metavar="IDS",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule_id, cls in sorted(RULE_REGISTRY.items()):
            print(f"{rule_id}  {cls.summary}")
        return 0

    select = None
    if args.select:
        select = [s.strip() for s in args.select.split(",") if s.strip()]
        unknown = [s for s in select if s not in RULE_REGISTRY]
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    try:
        findings = lint_paths(args.paths, rules=select)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(findings, args.baseline)
        print(f"baseline written: {len(findings)} finding(s) accepted")
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    fresh = [f for f in findings if f.key() not in baseline]
    for f in fresh:
        print(f.render(args.format))
    baselined = len(findings) - len(fresh)
    tail = f" ({baselined} baselined)" if baselined else ""
    print(f"jaxlint: {len(fresh)} finding(s){tail}", file=sys.stderr)
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
