"""jaxlint — AST static analysis for JAX tracer-safety hazards.

The failure modes this package exists to catch are the JAX mutations of
the classic DeepSpeed engine bugs (PAPER.md §L4): silent host syncs
inside the train loop, buffers read after ``donate_argnums`` donation,
``in_shardings`` without ``out_shardings`` (retrace-per-step on real
meshes), Python side effects baked in at trace time, and recompilation
hazards.  Every rule here started as a hand-found advisor finding; the
linter keeps the whole family out permanently.

Rules (see docs/jaxlint.md):
  JL001  host-sync call reachable from jit-traced code
  JL002  read of a buffer after it was donated to a jitted call
  JL003  in_shardings without out_shardings
  JL004  Python side effect under trace
  JL005  recompilation hazard (unhashable static arg, trace-time clock)
  JL101  config key not cross-checked against constants.py defaults

Zero dependencies beyond the stdlib: ``python -m tools.jaxlint`` must
run on a clean checkout before any environment is built.
"""
from .core import (Finding, ModuleContext, RULE_REGISTRY, lint_paths,
                   load_baseline, write_baseline)
from . import rules as _rules  # noqa: F401  (registers the rule classes)

__all__ = ["Finding", "ModuleContext", "RULE_REGISTRY", "lint_paths",
           "load_baseline", "write_baseline"]

__version__ = "0.1.0"
