"""Pass 1 of jaxlint v2: the project-wide cross-artifact registry.

One walk over the tree builds every registry the contract rules
(JL102/JL103/JL104, ``contracts.py``) and the interprocedural per-file
rules (JL008–JL010, ``rules.py``) reconcile:

- ``Stage(...)`` constructions and their literal names, plus the
  ``ENGINE_STAGES`` tuple and every ``StageGraph.register`` drain entry
  (``runtime/engine_stages.py``).
- Fault-point strings.  Besides direct ``fault_point(stage, point)``
  calls this resolves ONE level of wrapper indirection with a small
  fixpoint: a function whose body forwards a parameter into a known
  fault-point injector becomes an injector itself, so
  ``_write_bytes(..., point="manifest")`` and a ``point="leaf"``
  parameter default both register (checkpointing.py's style), as do
  ``stage.call("put", ...)`` / ``stage.check("job")`` sites resolved
  through in-module ``x = Stage("name")`` assignments.
- MetricsRegistry emissions (``.counter/.gauge/.histogram`` and the
  ``_count(name, help)`` module-function style) with HELP presence,
  plus the second metric plane: sync-scalar stores
  (``scalars["k"] = v`` and dict literals assigned to ``*scalars``
  names) and their ``scalars.get("k")`` readers.
- ``DS_*`` env-var reads.
- Config keys: every ``NAME = "literal"`` / ``NAME_DEFAULT`` pair in
  ``constants.py`` files and which uppercase constants each OTHER file
  references.
- benchgate's ``METRIC_DIRECTIONS`` pins + ``LOWER_BETTER_HINTS`` and
  the committed ``BENCH_*.json`` headline artifacts.
- The docs tables: docs/stages.md's stage/point contract table and
  drain-order fence, docs/observability.md's metric-naming bullets.

Purely syntactic, stdlib only — nothing is imported or executed.
"""
from __future__ import annotations

import ast
import dataclasses
import glob
import json
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import _SKIP_DIRS

#: fixture mini-projects live under this name; they must never leak
#: into the real tree's registry
_REGISTRY_SKIP = _SKIP_DIRS | {"jaxlint_fixtures"}

#: emissions (metrics, scalars, fault points, stages) are collected
#: from package code only — tests and tools CONSUME metric names,
#: they do not define the contract
_NON_PACKAGE_TOPDIRS = {"tests", "tools", "docs"}

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

_METRIC_SUFFIXES = ("_total", "_seconds", "_bytes", "_in_use", "_limit")

Site = Tuple[str, int]  # (relpath, line)


def find_project_root(paths) -> Optional[str]:
    """The nearest enclosing directory holding both ``docs/`` and
    ``tools/`` — the cross-artifact surfaces the contracts reconcile.
    Checked innermost-first so fixture mini-projects that carry their
    own docs/tools are their own root."""
    for p in paths:
        d = os.path.abspath(p if os.path.isdir(p)
                            else os.path.dirname(p) or ".")
        cur = d
        while True:
            if os.path.isdir(os.path.join(cur, "docs")) and \
                    os.path.isdir(os.path.join(cur, "tools")):
                return cur
            parent = os.path.dirname(cur)
            if parent == cur:
                return None
            cur = parent
    return None


def _dotted(node) -> Optional[str]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _is_package_path(relpath: str) -> bool:
    top = relpath.replace(os.sep, "/").split("/", 1)[0]
    return top not in _NON_PACKAGE_TOPDIRS


# ---------------------------------------------------------------------------
# fault-point wrapper fixpoint
# ---------------------------------------------------------------------------

#: a slot is ("const", value) or ("param", index); stage may also be
#: ("unknown",) when the wrapper cannot name its stage
_Slot = tuple


@dataclasses.dataclass
class _Injector:
    params: List[str]
    defaults: Dict[str, str]
    stage: _Slot
    point: _Slot


def _fn_params(fn) -> Tuple[List[str], Dict[str, str]]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args]
    defaults: Dict[str, str] = {}
    pos_defaults = args.defaults
    if pos_defaults:
        for name, d in zip(names[-len(pos_defaults):], pos_defaults):
            v = _const_str(d)
            if v is not None:
                defaults[name] = v
    for kwarg, d in zip(args.kwonlyargs, args.kw_defaults):
        names.append(kwarg.arg)
        v = _const_str(d) if d is not None else None
        if v is not None:
            defaults[kwarg.arg] = v
    return names, defaults


class _FaultPlane:
    """Resolves (stage, point) pairs through one-or-more levels of
    parameter-forwarding wrappers via a small fixpoint."""

    def __init__(self):
        # (module basename, function name) -> _Injector
        self.injectors: Dict[Tuple[str, str], _Injector] = {}
        self.sites: List[Tuple[Optional[str], str, str, int]] = []
        self._seen_sites: Set[Tuple] = set()

    def seed(self, modbase: str, fn):
        if fn.name != "fault_point":
            return
        params, defaults = _fn_params(fn)
        if len(params) >= 2 and params[0] == "stage" and params[1] == "point":
            self.injectors[(modbase, fn.name)] = _Injector(
                params, defaults, ("param", 0), ("param", 1))

    def _arg_for(self, call: ast.Call, inj: _Injector, idx: int):
        """The expression bound to the injector's idx-th parameter at
        this call, or its string default, or None."""
        if idx < len(call.args):
            return call.args[idx]
        name = inj.params[idx] if idx < len(inj.params) else None
        for kw in call.keywords:
            if kw.arg is not None and kw.arg == name:
                return kw.value
        if name is not None and name in inj.defaults:
            return inj.defaults[name]
        return None

    def _slot_value(self, slot: _Slot, call: ast.Call, inj: _Injector,
                    g_params: List[str]):
        """-> ("const", s) | ("param", caller index) | None."""
        if slot[0] == "const":
            return slot
        if slot[0] != "param":
            return ("unknown",)
        bound = self._arg_for(call, inj, slot[1])
        if bound is None:
            return None
        if isinstance(bound, str):  # a default already resolved
            return ("const", bound)
        s = _const_str(bound)
        if s is not None:
            return ("const", s)
        if isinstance(bound, ast.Name) and bound.id in g_params:
            return ("param", g_params.index(bound.id))
        return None

    def visit(self, modbase: str, relpath: str, g_name: str,
              g_params: List[str], g_defaults: Dict[str, str],
              body_nodes, alias_map: Dict[str, Tuple[str, str]]) -> bool:
        """Scan one function (or the module pseudo-function) for calls
        into known injectors; returns True when new facts appeared."""
        changed = False
        for node in body_nodes:
            if not isinstance(node, ast.Call):
                continue
            callee = node.func
            key = None
            if isinstance(callee, ast.Name):
                key = alias_map.get(callee.id)
            if key is None or key not in self.injectors:
                continue
            inj = self.injectors[key]
            stage_v = self._slot_value(inj.stage, node, inj, g_params)
            point_v = self._slot_value(inj.point, node, inj, g_params)
            if point_v is None:
                continue
            if stage_v is not None and stage_v[0] == "const" \
                    and point_v[0] == "const":
                site = (stage_v[1], point_v[1], relpath, node.lineno)
                if site not in self._seen_sites:
                    self._seen_sites.add(site)
                    self.sites.append(site)
                    changed = True
            elif stage_v == ("unknown",) and point_v[0] == "const":
                site = (None, point_v[1], relpath, node.lineno)
                if site not in self._seen_sites:
                    self._seen_sites.add(site)
                    self.sites.append(site)
                    changed = True
            elif point_v[0] == "param" and g_name is not None:
                new_stage = stage_v if stage_v is not None \
                    and stage_v[0] == "const" else ("unknown",)
                key2 = (modbase, g_name)
                if key2 not in self.injectors:
                    self.injectors[key2] = _Injector(
                        g_params, g_defaults, new_stage, point_v)
                    changed = True
        return changed


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ProjectRegistry:
    root: str
    files: List[str] = dataclasses.field(default_factory=list)
    sources: Dict[str, str] = dataclasses.field(default_factory=dict)
    # stage plane
    stages: Dict[str, List[Site]] = dataclasses.field(default_factory=dict)
    engine_stages: List[str] = dataclasses.field(default_factory=list)
    drain_orders: Dict[str, List[Tuple[str, int]]] = \
        dataclasses.field(default_factory=dict)
    fault_points: List[Tuple[Optional[str], str, str, int]] = \
        dataclasses.field(default_factory=list)
    # metric planes
    metrics: Dict[str, dict] = dataclasses.field(default_factory=dict)
    scalars: Dict[str, List[Site]] = dataclasses.field(default_factory=dict)
    scalar_reads: Dict[str, List[Site]] = \
        dataclasses.field(default_factory=dict)
    env_vars: Dict[str, List[Site]] = dataclasses.field(default_factory=dict)
    # config plane
    config_keys: Dict[str, Tuple[str, str, int]] = \
        dataclasses.field(default_factory=dict)
    config_defaults: Dict[str, Site] = dataclasses.field(default_factory=dict)
    upper_refs: Dict[str, Set[str]] = dataclasses.field(default_factory=dict)
    # bench plane
    bench_directions: Dict[str, Site] = dataclasses.field(default_factory=dict)
    bench_hints: Tuple[str, ...] = ()
    bench_artifacts: Dict[str, str] = dataclasses.field(default_factory=dict)
    # docs plane
    docs_stage_rows: List[Tuple[str, str, str, int]] = \
        dataclasses.field(default_factory=list)
    docs_drain: List[Tuple[str, str, int]] = \
        dataclasses.field(default_factory=list)
    docs_metrics: List[Tuple[str, str, int]] = \
        dataclasses.field(default_factory=list)

    # -- queries ---------------------------------------------------------
    def line_text(self, relpath: str, lineno: int) -> str:
        src = self.sources.get(relpath)
        if src is None:
            try:
                with open(os.path.join(self.root, relpath),
                          encoding="utf-8") as f:
                    src = f.read()
            except OSError:
                src = ""
            self.sources[relpath] = src
        lines = src.splitlines()
        if 1 <= lineno <= len(lines):
            return lines[lineno - 1]
        return ""

    def known_stage_names(self) -> Set[str]:
        """The stage NAMESPACE: ENGINE_STAGES + docs contract table +
        stage constants at resolved fault points (e.g. ``ckpt``, which
        is never a ``Stage(...)`` construction)."""
        names = set(self.engine_stages)
        names.update(s for s, _p, _f, _l in self.docs_stage_rows)
        names.update(s for s, _p, _f, _l in self.fault_points
                     if s is not None)
        return names

    def name_occurrences(self, name: str) -> List[str]:
        """Files whose text mentions ``name`` as a whole word."""
        pat = re.compile(r"(?<![A-Za-z0-9_])%s(?![A-Za-z0-9_])"
                         % re.escape(name))
        return [rp for rp, src in sorted(self.sources.items())
                if pat.search(src)]

    # -- construction ----------------------------------------------------
    @classmethod
    def build(cls, root: str) -> "ProjectRegistry":
        reg = cls(root=os.path.abspath(root))
        reg._scan_py_files()
        reg._scan_bench_artifacts()
        reg._scan_docs()
        return reg

    def _iter_files(self, suffix: str) -> List[str]:
        out = []
        for dirpath, dirs, names in os.walk(self.root):
            dirs[:] = sorted(d for d in dirs if d not in _REGISTRY_SKIP
                             and not d.startswith("."))
            for n in sorted(names):
                if n.endswith(suffix):
                    out.append(os.path.relpath(os.path.join(dirpath, n),
                                               self.root))
        return out

    def _scan_py_files(self):
        trees: Dict[str, ast.AST] = {}
        for rp in self._iter_files(".py"):
            try:
                with open(os.path.join(self.root, rp),
                          encoding="utf-8") as f:
                    src = f.read()
                tree = ast.parse(src)
            except (OSError, SyntaxError):
                continue
            self.files.append(rp)
            self.sources[rp] = src
            trees[rp] = tree
            self.upper_refs[rp] = set(
                re.findall(r"\b[A-Z][A-Z0-9_]{2,}\b", src))
        for rp, tree in trees.items():
            self._scan_module(rp, tree)
        self._resolve_fault_points(trees)

    # -- per-module extraction -------------------------------------------
    def _scan_module(self, rp: str, tree):
        in_pkg = _is_package_path(rp)
        is_constants = os.path.basename(rp) == "constants.py"
        if is_constants:
            self._scan_constants(rp, tree)
        if rp.replace(os.sep, "/").endswith("tools/benchgate/__init__.py"):
            self._scan_benchgate(rp, tree)
        stage_vars = self._stage_assignments(tree) if in_pkg else {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and in_pkg:
                self._scan_assign(rp, node)
            if isinstance(node, ast.Subscript):
                self._scan_subscript(rp, node, in_pkg)
            if not isinstance(node, ast.Call):
                continue
            if not in_pkg:
                continue
            self._scan_env_call(rp, node)
            self._scan_metric_call(rp, node)
            self._scan_scalar_get(rp, node)
            fn = node.func
            last = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if last == "Stage" and node.args:
                name = _const_str(node.args[0])
                if name is not None:
                    self.stages.setdefault(name, []).append(
                        (rp, node.lineno))
            elif last == "register" and isinstance(fn, ast.Attribute) \
                    and node.args and any(k.arg in ("close", "drain")
                                          for k in node.keywords):
                name = _const_str(node.args[0])
                if name is not None:
                    self.drain_orders.setdefault(rp, []).append(
                        (name, node.lineno))
            elif last in ("call", "check") and isinstance(fn, ast.Attribute) \
                    and node.args:
                point = _const_str(node.args[0])
                recv = _dotted(fn.value)
                if point is not None and recv is not None:
                    if recv in stage_vars:
                        self.fault_points.append(
                            (stage_vars[recv], point, rp, node.lineno))
                    elif "stage" in recv.lower():
                        self.fault_points.append(
                            (None, point, rp, node.lineno))
        if in_pkg:
            for stmt in tree.body:
                if isinstance(stmt, ast.Assign) \
                        and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name) \
                        and stmt.targets[0].id == "ENGINE_STAGES" \
                        and isinstance(stmt.value, (ast.Tuple, ast.List)):
                    for elt in stmt.value.elts:
                        if isinstance(elt, (ast.Tuple, ast.List)) \
                                and elt.elts:
                            name = _const_str(elt.elts[0])
                            if name is not None:
                                self.engine_stages.append(name)

    def _stage_assignments(self, tree) -> Dict[str, str]:
        """dotted assignment target -> stage name, for every assignment
        whose value subtree contains ``Stage("<literal>")`` (covers the
        ``x = given or Stage("n")`` ternary/boolean fallbacks)."""
        out: Dict[str, str] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            name = None
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Call):
                    last = sub.func.attr \
                        if isinstance(sub.func, ast.Attribute) else (
                            sub.func.id if isinstance(sub.func, ast.Name)
                            else None)
                    if last == "Stage" and sub.args:
                        name = _const_str(sub.args[0])
                        if name is not None:
                            break
            if name is None:
                continue
            for tgt in node.targets:
                text = _dotted(tgt)
                if text is not None:
                    out[text] = name
        return out

    def _scan_metric_call(self, rp: str, node: ast.Call):
        fn = node.func
        kind = None
        if isinstance(fn, ast.Attribute) and fn.attr in (
                "counter", "gauge", "histogram"):
            kind = fn.attr
        elif ((isinstance(fn, ast.Name) and fn.id == "_count")
              or (isinstance(fn, ast.Attribute) and fn.attr == "_count")):
            kind = "counter"
        if kind is None or not node.args:
            return
        name = _const_str(node.args[0])
        if name is None:
            return
        has_help = (len(node.args) > 1
                    and _const_str(node.args[1]) is not None) or any(
            kw.arg == "help" and _const_str(kw.value) is not None
            for kw in node.keywords)
        rec = self.metrics.setdefault(
            name, {"kind": kind, "has_help": False, "sites": []})
        rec["has_help"] = rec["has_help"] or has_help
        rec["sites"].append((rp, node.lineno))

    def _scan_assign(self, rp: str, node: ast.Assign):
        # scalars = {"name": value, ...}  (the dict-literal plane)
        if not isinstance(node.value, ast.Dict):
            return
        for tgt in node.targets:
            text = _dotted(tgt)
            if text is None or "scalar" not in text.split(".")[-1].lower():
                continue
            for k in node.value.keys:
                name = _const_str(k) if k is not None else None
                if name is not None:
                    self.scalars.setdefault(name, []).append(
                        (rp, node.lineno))

    def _scan_subscript(self, rp: str, node: ast.Subscript, in_pkg: bool):
        if not in_pkg:
            return
        recv = _dotted(node.value)
        if recv is None or "scalar" not in recv.split(".")[-1].lower():
            return
        name = _const_str(node.slice)
        if name is None:
            return
        if isinstance(node.ctx, ast.Store) and in_pkg:
            self.scalars.setdefault(name, []).append((rp, node.lineno))
        elif isinstance(node.ctx, ast.Load):
            self.scalar_reads.setdefault(name, []).append((rp, node.lineno))

    def _scan_scalar_get(self, rp: str, node: ast.Call):
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr == "get"
                and node.args):
            return
        recv = _dotted(fn.value)
        if recv is None or "scalar" not in recv.split(".")[-1].lower():
            return
        name = _const_str(node.args[0])
        if name is not None:
            self.scalar_reads.setdefault(name, []).append((rp, node.lineno))

    def _scan_env_call(self, rp: str, node: ast.Call):
        fn = node.func
        text = _dotted(fn) or ""
        name = None
        if text.endswith("getenv") and node.args:
            name = _const_str(node.args[0])
        elif isinstance(fn, ast.Attribute) and fn.attr in ("get", "pop") \
                and node.args and (_dotted(fn.value) or "").endswith(
                    "environ"):
            name = _const_str(node.args[0])
        if name is not None and name.startswith("DS_"):
            self.env_vars.setdefault(name, []).append((rp, node.lineno))

    def _scan_constants(self, rp: str, tree):
        for stmt in tree.body:
            if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                continue
            name = stmt.targets[0].id
            if not re.fullmatch(r"[A-Z][A-Z0-9_]*", name):
                continue
            if name.endswith("_DEFAULT"):
                self.config_defaults[name] = (rp, stmt.lineno)
            else:
                v = _const_str(stmt.value)
                if v is not None:
                    self.config_keys[name] = (v, rp, stmt.lineno)

    def _scan_benchgate(self, rp: str, tree):
        for stmt in tree.body:
            if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                continue
            tname = stmt.targets[0].id
            if tname == "METRIC_DIRECTIONS" and isinstance(stmt.value,
                                                           ast.Dict):
                for k in stmt.value.keys:
                    name = _const_str(k) if k is not None else None
                    if name is not None:
                        self.bench_directions[name] = (rp, k.lineno)
            elif tname == "LOWER_BETTER_HINTS" and isinstance(
                    stmt.value, (ast.Tuple, ast.List)):
                self.bench_hints = tuple(
                    e.value for e in stmt.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str))

    # -- fault-point fixpoint --------------------------------------------
    def _resolve_fault_points(self, trees: Dict[str, ast.AST]):
        plane = _FaultPlane()
        modules = []  # (modbase, rp, alias_map, functions)
        for rp, tree in trees.items():
            if not _is_package_path(rp):
                continue
            modbase = os.path.basename(rp)[:-3]
            alias: Dict[str, Tuple[str, str]] = {}
            for node in ast.walk(tree):
                if isinstance(node, ast.ImportFrom) and node.module \
                        is not None:
                    src = node.module.split(".")[-1]
                    for a in node.names:
                        alias[a.asname or a.name] = (src, a.name)
            funcs = []
            module_level: List[ast.AST] = []
            for stmt in tree.body:
                if isinstance(stmt, _FUNC_DEFS):
                    funcs.append(stmt)
                    plane.seed(modbase, stmt)
                    alias.setdefault(stmt.name, (modbase, stmt.name))
                elif isinstance(stmt, ast.ClassDef):
                    for sub in stmt.body:
                        if isinstance(sub, _FUNC_DEFS):
                            funcs.append(sub)
                else:
                    module_level.append(stmt)
            modules.append((modbase, rp, alias, funcs, module_level))
        for _ in range(6):
            changed = False
            for modbase, rp, alias, funcs, module_level in modules:
                for fn in funcs:
                    params, defaults = _fn_params(fn)
                    body = [n for n in ast.walk(fn)]
                    if plane.visit(modbase, rp, fn.name, params, defaults,
                                   body, alias):
                        changed = True
                flat = [n for stmt in module_level
                        for n in ast.walk(stmt)]
                if plane.visit(modbase, rp, None, [], {}, flat, alias):
                    changed = True
            if not changed:
                break
        self.fault_points.extend(plane.sites)
        self.fault_points.sort(key=lambda t: (t[2], t[3]))

    # -- non-python artifacts --------------------------------------------
    def _scan_bench_artifacts(self):
        for path in sorted(glob.glob(os.path.join(self.root,
                                                  "BENCH_*.json"))):
            try:
                with open(path, encoding="utf-8") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            if isinstance(doc, dict) and "metric" in doc and "value" in doc:
                self.bench_artifacts[str(doc["metric"])] = \
                    os.path.relpath(path, self.root)

    def _read_doc(self, relpath: str) -> Optional[List[str]]:
        path = os.path.join(self.root, relpath)
        if not os.path.isfile(path):
            return None
        with open(path, encoding="utf-8") as f:
            src = f.read()
        self.sources[relpath] = src
        return src.splitlines()

    def _scan_docs(self):
        stages_rp = os.path.join("docs", "stages.md")
        lines = self._read_doc(stages_rp)
        if lines is not None:
            self._scan_stage_table(stages_rp, lines)
            self._scan_drain_fence(stages_rp, lines)
        obs_rp = os.path.join("docs", "observability.md")
        lines = self._read_doc(obs_rp)
        if lines is not None:
            self._scan_metric_bullets(obs_rp, lines)
        # the rest of docs/ + README joins the consumer corpus
        for rp in self._iter_files(".md"):
            if rp not in self.sources:
                try:
                    with open(os.path.join(self.root, rp),
                              encoding="utf-8") as f:
                        self.sources[rp] = f.read()
                except OSError:
                    pass

    def _scan_stage_table(self, rp: str, lines: List[str]):
        in_table = False
        for i, line in enumerate(lines, 1):
            cells = [c.strip() for c in line.strip().strip("|").split("|")]
            if not line.lstrip().startswith("|"):
                in_table = False
                continue
            if len(cells) >= 2 and cells[0] == "stage" \
                    and cells[1] == "point":
                in_table = True
                continue
            if not in_table or len(cells) < 2:
                continue
            if set(cells[0]) <= {"-", " ", ":"}:
                continue
            m = re.findall(r"`([A-Za-z0-9_]+)`", cells[0])
            if not m:
                continue
            stage = m[0]
            for point in re.findall(r"`([A-Za-z0-9_]+)`", cells[1]):
                self.docs_stage_rows.append((stage, point, rp, i))

    def _scan_drain_fence(self, rp: str, lines: List[str]):
        in_section = False
        in_fence = False
        for i, line in enumerate(lines, 1):
            if line.startswith("#") and "drain order" in line.lower():
                in_section = True
                continue
            if in_section and line.startswith("#"):
                break
            if not in_section:
                continue
            if line.strip().startswith("```"):
                if in_fence:
                    break
                in_fence = True
                continue
            if in_fence and ("→" in line or "->" in line):
                for tok in re.split(r"→|->", line):
                    tok = " ".join(tok.split())
                    if tok:
                        self.docs_drain.append((tok, rp, i))

    def _scan_metric_bullets(self, rp: str, lines: List[str]):
        in_section = False
        for i, line in enumerate(lines, 1):
            if line.startswith("## "):
                in_section = "metric naming" in line.lower()
                continue
            if not in_section:
                continue
            for tok in re.findall(r"`([a-z][a-z0-9_]*)(?:\{[^`]*)?`", line):
                if tok.endswith(_METRIC_SUFFIXES):
                    self.docs_metrics.append((tok, rp, i))

    # -- dump ------------------------------------------------------------
    def dump(self) -> dict:
        """A JSON-stable snapshot (``--registry-dump``)."""
        return {
            "root": self.root,
            "stages": {k: sorted(v) for k, v in sorted(self.stages.items())},
            "engine_stages": list(self.engine_stages),
            "drain_orders": {k: v for k, v in
                             sorted(self.drain_orders.items())},
            "fault_points": [[s, p, f, l] for s, p, f, l in
                             sorted(self.fault_points,
                                    key=lambda t: (t[2], t[3]))],
            "metrics": {k: {"kind": v["kind"], "has_help": v["has_help"],
                            "sites": sorted(v["sites"])}
                        for k, v in sorted(self.metrics.items())},
            "scalars": {k: sorted(v) for k, v in
                        sorted(self.scalars.items())},
            "scalar_reads": {k: sorted(v) for k, v in
                             sorted(self.scalar_reads.items())},
            "env_vars": {k: sorted(v) for k, v in
                         sorted(self.env_vars.items())},
            "config_keys": {k: list(v) for k, v in
                            sorted(self.config_keys.items())},
            "config_defaults": {k: list(v) for k, v in
                                sorted(self.config_defaults.items())},
            "bench_directions": {k: list(v) for k, v in
                                 sorted(self.bench_directions.items())},
            "bench_hints": list(self.bench_hints),
            "bench_artifacts": dict(sorted(self.bench_artifacts.items())),
            "docs_stage_rows": [list(r) for r in self.docs_stage_rows],
            "docs_drain": [list(r) for r in self.docs_drain],
            "docs_metrics": [list(r) for r in self.docs_metrics],
        }
