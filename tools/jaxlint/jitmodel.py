"""Shared per-module jit analysis.

Answers the three questions every tracer-safety rule needs:

1. Which function bodies run under trace?  (``jitted_defs`` — wrapped
   directly by ``jit``/``pjit`` as a decorator, a call argument, or a
   ``partial(jax.jit, ...)`` — plus ``reachable_defs``, the transitive
   closure over local calls and ``self.method`` calls.)
2. Where are the ``jit`` wrapper call sites and what options do they
   carry?  (``sites`` — donate/static argnums+argnames, in/out
   shardings.)
3. Which *names* are known-jitted callables?  (``callables`` — a def
   decorated with jit, or the target of ``f = jax.jit(...)`` /
   ``self._step = jax.jit(...)``, so call sites of those names can be
   checked for donation misuse and unhashable static arguments.)

Purely syntactic — no imports are executed.  Aliases of jit through
other names (``from jax import jit as J``) and wrappers hidden behind
helper functions are out of scope by design: under-approximate, never
guess.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

#: last dotted component that marks a call as a jit wrapper
WRAPPER_LAST = {"jit", "pjit"}
#: accepted full spellings (guards against unrelated ``.jit`` methods)
WRAPPER_TEXTS = {"jit", "pjit", "jax.jit", "jax.pjit", "pjit.pjit",
                 "jax.experimental.pjit.pjit"}


def dotted(node) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _int_tuple(node) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


def _str_tuple(node) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


def is_wrapper_text(text: Optional[str]) -> bool:
    """jit-wrapper spelling check on a dotted string (the one
    definition; ``is_wrapper_ref`` is the AST-node view of it)."""
    if text is None:
        return False
    return text in WRAPPER_TEXTS or (text.split(".")[-1] in WRAPPER_LAST
                                     and text.startswith("jax."))


def is_wrapper_ref(node) -> bool:
    return is_wrapper_text(dotted(node))


@dataclasses.dataclass
class JitSite:
    node: ast.AST                      # the jit Call (or bare decorator ref)
    wrapped: Optional[ast.AST] = None  # resolved FunctionDef/Lambda
    donate_argnums: Tuple[int, ...] = ()
    donate_argnames: Tuple[str, ...] = ()
    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    has_in_shardings: bool = False
    has_out_shardings: bool = False
    is_decorator: bool = False

    @property
    def donates(self) -> bool:
        return bool(self.donate_argnums or self.donate_argnames)


class JitAnalysis:
    def __init__(self, ctx):
        self.ctx = ctx
        tree = ctx.tree
        self.defs: List[ast.AST] = [n for n in ast.walk(tree)
                                    if isinstance(n, _FUNC_DEFS)]
        self.sites: List[JitSite] = []
        self.callables: Dict[str, JitSite] = {}
        # same name bound in different scopes (every builder calls its
        # jitted closure 'step'...) — the scoped map disambiguates
        self.scoped_callables: Dict[Tuple[int, str], JitSite] = {}
        self.jitted_defs: Set[ast.AST] = set()
        self._collect_sites(tree)
        self.reachable_defs: Set[ast.AST] = self._close_over_calls()

    # -- scope helpers ---------------------------------------------------
    def enclosing_function(self, node) -> Optional[ast.AST]:
        n = self.ctx.parent(node)
        while n is not None and not isinstance(n, _FUNC_DEFS):
            n = self.ctx.parent(n)
        return n

    def enclosing_class(self, node) -> Optional[ast.ClassDef]:
        n = self.ctx.parent(node)
        while n is not None and not isinstance(n, ast.ClassDef):
            n = self.ctx.parent(n)
        return n

    def _resolve_name(self, name: str, from_def) -> Optional[ast.AST]:
        """A bare called name -> the def it refers to, lexically."""
        scope = from_def
        while scope is not None:
            for stmt in ast.walk(scope):
                if isinstance(stmt, _FUNC_DEFS) and stmt.name == name \
                        and stmt is not scope \
                        and self.enclosing_function(stmt) is scope:
                    return stmt
            scope = self.enclosing_function(scope)
        for stmt in self.ctx.tree.body:
            if isinstance(stmt, _FUNC_DEFS) and stmt.name == name:
                return stmt
        return None

    def _resolve_self_method(self, name: str, from_def) -> Optional[ast.AST]:
        cls = self.enclosing_class(from_def)
        if cls is None:
            return None
        for stmt in cls.body:
            if isinstance(stmt, _FUNC_DEFS) and stmt.name == name:
                return stmt
        return None

    def resolve_call(self, call: ast.Call, from_def) -> Optional[ast.AST]:
        text = dotted(call.func)
        if text is None:
            return None
        if "." not in text:
            return self._resolve_name(text, from_def)
        base, _, attr = text.rpartition(".")
        if base == "self":
            return self._resolve_self_method(attr, from_def)
        return None

    # -- site collection -------------------------------------------------
    def _parse_site(self, call: ast.Call, decorator: bool = False) -> JitSite:
        site = JitSite(node=call, is_decorator=decorator)
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                site.donate_argnums = _int_tuple(kw.value)
            elif kw.arg == "donate_argnames":
                site.donate_argnames = _str_tuple(kw.value)
            elif kw.arg == "static_argnums":
                site.static_argnums = _int_tuple(kw.value)
            elif kw.arg == "static_argnames":
                site.static_argnames = _str_tuple(kw.value)
            elif kw.arg == "in_shardings":
                site.has_in_shardings = True
            elif kw.arg == "out_shardings":
                site.has_out_shardings = True
        return site

    def _collect_sites(self, tree):
        # decorator expressions are handled by the decorator loop below;
        # the plain-call walk must skip them or @jax.jit(...) registers
        # twice (once without is_decorator, breaking JL003's skip)
        decorator_nodes = {id(dec) for fn in self.defs
                           for dec in fn.decorator_list}
        # plain jit calls: jax.jit(f, ...) anywhere in the module
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not is_wrapper_ref(node.func):
                continue
            if id(node) in decorator_nodes:
                continue
            site = self._parse_site(node)
            if node.args:
                first = node.args[0]
                if isinstance(first, ast.Lambda):
                    site.wrapped = first
                else:
                    name = dotted(first)
                    if name is not None:
                        site.wrapped = self._resolve_ref(
                            name, self.enclosing_function(node))
            self.sites.append(site)
            if site.wrapped is not None:
                self.jitted_defs.add(site.wrapped)
            self._bind_assignment(node, site)

        # decorators: @jax.jit / @partial(jax.jit, ...) / @jax.jit(...)
        for fn in self.defs:
            for dec in fn.decorator_list:
                site = self._decorator_site(dec)
                if site is None:
                    continue
                site.wrapped = fn
                self.jitted_defs.add(fn)
                self.sites.append(site)
                self.callables.setdefault(fn.name, site)

    def _resolve_ref(self, name: str, from_def) -> Optional[ast.AST]:
        if "." in name:
            base, _, attr = name.rpartition(".")
            if base == "self" and from_def is not None:
                return self._resolve_self_method(attr, from_def)
            return None
        if from_def is not None:
            return self._resolve_name(name, from_def)
        for stmt in self.ctx.tree.body:
            if isinstance(stmt, _FUNC_DEFS) and stmt.name == name:
                return stmt
        return None

    def _bind_assignment(self, call: ast.Call, site: JitSite):
        """Register ``x = jax.jit(...)`` / ``self.x = jax.jit(...)``."""
        parent = self.ctx.parent(call)
        scope = self.enclosing_function(call)
        targets = []
        if isinstance(parent, ast.Assign):
            targets = parent.targets
        elif isinstance(parent, ast.AnnAssign):
            targets = [parent.target]
        for tgt in targets:
            text = dotted(tgt)
            if text is not None:
                self.callables[text] = site
                self.scoped_callables[(id(scope), text)] = site

    def lookup_callable(self, name: str, scope) -> Optional[JitSite]:
        """The jit site a called name refers to, innermost scope first."""
        while scope is not None:
            site = self.scoped_callables.get((id(scope), name))
            if site is not None:
                return site
            scope = self.enclosing_function(scope)
        site = self.scoped_callables.get((id(None), name))
        if site is not None:
            return site
        return self.callables.get(name)

    def _decorator_site(self, dec) -> Optional[JitSite]:
        if is_wrapper_ref(dec):  # @jax.jit
            return JitSite(node=dec, is_decorator=True)
        if isinstance(dec, ast.Call):
            if is_wrapper_ref(dec.func):  # @jax.jit(static_argnums=...)
                return self._parse_site(dec, decorator=True)
            func_text = dotted(dec.func)
            if func_text in ("partial", "functools.partial") and dec.args \
                    and is_wrapper_ref(dec.args[0]):
                return self._parse_site(dec, decorator=True)
        return None

    # -- reachability ----------------------------------------------------
    def _close_over_calls(self) -> Set[ast.AST]:
        seen = set(self.jitted_defs)
        work = list(self.jitted_defs)
        while work:
            fn = work.pop()
            if isinstance(fn, ast.Lambda):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                target = self.resolve_call(node, fn)
                if target is not None and target not in seen:
                    seen.add(target)
                    work.append(target)
        return seen

    # -- convenience for rules -------------------------------------------
    def traced_bodies(self):
        """(def, is_root) for every function whose body runs under trace."""
        for fn in sorted(self.reachable_defs,
                         key=lambda n: getattr(n, "lineno", 0)):
            yield fn, fn in self.jitted_defs
