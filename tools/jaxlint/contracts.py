"""Pass 2 (project level): cross-artifact contract rules.

These rules run once per project over the :class:`ProjectRegistry`
(pass 1, ``registry.py``) instead of once per file, and their findings
may anchor in ``.md`` files — the docs tables are artifacts under the
same zero-findings discipline as the code.

JL102 — metric contracts: every registry metric needs HELP text and a
consumer (summarize/diagnose row, docs mention, or test reference);
every sync scalar needs a consumer; a ``scalars.get`` read needs an
emitter; every benchgate ``METRIC_DIRECTIONS`` pin needs a committed
``BENCH_*.json`` headline; every docs metric-naming bullet needs an
emission.

JL103 — fault-point registry: the docs/stages.md stage/point contract
table and drain-order fence must match the code-side registries (both
directions).

JL104 — config-key contracts across ALL blocks: a ``*_DEFAULT``
without its key constant, a key constant nothing reads (dead schema
key), a default nothing routes.
"""
from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional

from .core import Finding, suppressed_in_lines
from .registry import ProjectRegistry

PROJECT_RULE_REGISTRY: Dict[str, type] = {}


def project_register(cls):
    PROJECT_RULE_REGISTRY[cls.id] = cls
    return cls


class ProjectRule:
    id = "JL100"
    summary = "base project rule"

    def finding(self, reg: ProjectRegistry, path: str, line: int,
                message: str) -> Finding:
        return Finding(path=path, line=line, col=0, rule=self.id,
                       message=message,
                       line_text=reg.line_text(path, line))

    def check(self, reg: ProjectRegistry) -> Iterable[Finding]:
        raise NotImplementedError


@project_register
class MetricContracts(ProjectRule):
    id = "JL102"
    summary = ("metric contract: emissions need HELP text and a "
               "consumer; benchgate pins and docs bullets need a "
               "real metric behind them")

    def _unconsumed(self, reg, name: str, sites) -> bool:
        emitting = {p for p, _l in sites}
        return not any(occ not in emitting
                       for occ in reg.name_occurrences(name))

    def check(self, reg):
        for name, rec in sorted(reg.metrics.items()):
            path, line = rec["sites"][0]
            if not rec["has_help"]:
                yield self.finding(
                    reg, path, line,
                    f"metric '{name}' is emitted without HELP text "
                    "(pass it at the registry call site)")
            if self._unconsumed(reg, name, rec["sites"]):
                yield self.finding(
                    reg, path, line,
                    f"metric '{name}' is emitted here but consumed "
                    "nowhere — no summarize/diagnose row, docs "
                    "mention, or test reference in the tree")
        for name, sites in sorted(reg.scalars.items()):
            if self._unconsumed(reg, name, sites):
                path, line = sites[0]
                yield self.finding(
                    reg, path, line,
                    f"sync scalar '{name}' is emitted here but "
                    "consumed nowhere — no summarize row, docs "
                    "mention, or test reference in the tree")
        for name, sites in sorted(reg.scalar_reads.items()):
            if name not in reg.scalars:
                path, line = sites[0]
                yield self.finding(
                    reg, path, line,
                    f"sync scalar '{name}' is read here but no "
                    "engine ever emits it")
        for name, (path, line) in sorted(reg.bench_directions.items()):
            if name not in reg.bench_artifacts:
                yield self.finding(
                    reg, path, line,
                    f"benchgate METRIC_DIRECTIONS pins '{name}' but "
                    "no committed BENCH_*.json artifact carries that "
                    "headline metric")
        known = set(reg.metrics) | set(reg.scalars)
        for name, path, line in reg.docs_metrics:
            if name not in known:
                yield self.finding(
                    reg, path, line,
                    f"documented metric '{name}' does not exist — no "
                    "registry metric or sync scalar emission has "
                    "this name")


@project_register
class FaultPointContracts(ProjectRule):
    id = "JL103"
    summary = ("fault-point registry: docs/stages.md table and "
               "drain-order fence must match the Stage/StageGraph "
               "code registries, both directions")

    def check(self, reg):
        code_pairs = {(s, p) for s, p, _f, _l in reg.fault_points
                      if s is not None}
        code_points = {p for _s, p, _f, _l in reg.fault_points}
        doc_pairs = {(s, p) for s, p, _f, _l in reg.docs_stage_rows}
        doc_points = {p for _s, p, _f, _l in reg.docs_stage_rows}

        if reg.docs_stage_rows:
            for stage, point, path, line in reg.docs_stage_rows:
                if (stage, point) not in code_pairs \
                        and point not in code_points:
                    yield self.finding(
                        reg, path, line,
                        f"documented fault point `{stage}`:`{point}` "
                        "does not exist in code — stale row vs the "
                        "stage runtime")
            for stage, point, path, line in reg.fault_points:
                if stage is not None and (stage, point) not in doc_pairs:
                    yield self.finding(
                        reg, path, line,
                        f"fault point ('{stage}', '{point}') is live "
                        "here but missing from the docs/stages.md "
                        "contract table")
                elif stage is None and point not in doc_points:
                    yield self.finding(
                        reg, path, line,
                        f"fault point '{point}' is live here but no "
                        "docs/stages.md row documents it")

        drain_names = {n for entries in reg.drain_orders.values()
                       for n, _l in entries}
        tokens = [t for t, _f, _l in reg.docs_drain]
        all_known = True
        for tok, path, line in reg.docs_drain:
            if tok not in drain_names:
                all_known = False
                yield self.finding(
                    reg, path, line,
                    f"drain-order fence token '{tok}' is not a "
                    "StageGraph.register entry (registered: "
                    f"{', '.join(sorted(drain_names)) or 'none'})")
        if tokens and all_known:
            for file, entries in sorted(reg.drain_orders.items()):
                names = [n for n, _l in entries]
                if set(tokens) <= set(names):
                    got = [n for n in names if n in set(tokens)]
                    if got != tokens:
                        path, line = (reg.docs_drain[0][1],
                                      reg.docs_drain[0][2])
                        yield self.finding(
                            reg, path, line,
                            "drain-order fence order "
                            f"{' -> '.join(tokens)} does not match "
                            f"the registration order in {file} "
                            f"({' -> '.join(got)})")
                    break
            else:
                path, line = reg.docs_drain[0][1], reg.docs_drain[0][2]
                yield self.finding(
                    reg, path, line,
                    "drain-order fence names no single "
                    "StageGraph registration sequence containing "
                    "all of: " + ", ".join(tokens))


@project_register
class ConfigKeyContracts(ProjectRule):
    id = "JL104"
    summary = ("config-key contract (all blocks): *_DEFAULT without a "
               "key constant, dead schema keys, defaults nothing "
               "routes")

    def _referenced_elsewhere(self, reg, name: str, own_file: str) -> bool:
        return any(name in refs for rp, refs in reg.upper_refs.items()
                   if rp != own_file)

    def check(self, reg):
        for dname, (path, line) in sorted(reg.config_defaults.items()):
            base = dname[: -len("_DEFAULT")]
            defined_somehow = base in reg.config_keys or \
                base in reg.upper_refs.get(path, set())
            if not defined_somehow:
                yield self.finding(
                    reg, path, line,
                    f"'{dname}' has no matching key constant "
                    f"'{base}' — a default the config schema can "
                    "never route")
                continue
            if not self._referenced_elsewhere(reg, dname, path):
                yield self.finding(
                    reg, path, line,
                    f"'{dname}' is never referenced outside "
                    f"{path} — its key is read without this default")
        for name, (value, path, line) in sorted(reg.config_keys.items()):
            if not self._referenced_elsewhere(reg, name, path):
                yield self.finding(
                    reg, path, line,
                    f"config key constant '{name}' (\"{value}\") is "
                    f"never referenced outside {path} — dead schema "
                    "key or missing validation wiring")


def run_project_rules(reg: ProjectRegistry,
                      rules: Optional[List[str]] = None) -> List[Finding]:
    out: List[Finding] = []
    for rule_id, cls in sorted(PROJECT_RULE_REGISTRY.items()):
        if rules is not None and rule_id not in rules:
            continue
        for f in cls().check(reg):
            src = reg.sources.get(f.path)
            if src is not None and f.path.endswith(".py") and \
                    suppressed_in_lines(src.splitlines(), f.line, f.rule):
                continue
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out
