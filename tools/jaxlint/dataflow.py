"""Interprocedural def-use helpers shared by JL008/JL009/JL010.

All per-module and purely syntactic (module-local call resolution via
the shared jit model's resolver — bare names and ``self.method``):
enough to chain a donated ``self.attr`` from the donating method to a
reader method (JL009), a jitted closure to the enclosing scope's later
rebinding of a captured scalar (JL010), and a ``Channel.put`` to the
worker-body closure it must live in (JL008).  Under-approximate, never
guess: unresolvable receivers and dynamic dispatch are out of scope.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .jitmodel import dotted

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

#: call last-components that hand a callable to the stage runtime as a
#: worker body (stages.spawn / StageWorker)
_WORKER_WRAPPERS = {"spawn", "StageWorker"}


def _callable_refs(call: ast.Call) -> List[ast.AST]:
    """Name/Attribute arguments of a worker-wrapper call — the
    candidate worker-body references."""
    out = []
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(arg, (ast.Name, ast.Attribute)):
            out.append(arg)
    return out


def worker_body_defs(ctx) -> Set[ast.AST]:
    """Defs whose bodies run on a stage-runtime worker thread: passed
    to ``spawn(...)``/``StageWorker(...)`` (by bare name or
    ``self.method``), plus everything they call transitively in this
    module."""
    jit = ctx.jit
    roots: Set[ast.AST] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        last = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if last not in _WORKER_WRAPPERS:
            continue
        scope = jit.enclosing_function(node)
        for ref in _callable_refs(node):
            text = dotted(ref)
            if text is None:
                continue
            target = jit._resolve_ref(text, scope)
            if target is not None:
                roots.add(target)
        # inline worker bodies: spawn(lambda: ...) keeps its lambda
        for ref in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(ref, ast.Lambda):
                roots.add(ref)
    seen = set(roots)
    work = list(roots)
    while work:
        fn = work.pop()
        if isinstance(fn, ast.Lambda):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            target = jit.resolve_call(node, fn)
            if target is not None and target not in seen:
                seen.add(target)
                work.append(target)
    return seen


def channel_targets(ctx) -> Set[str]:
    """Dotted assignment targets bound to a ``Channel(...)``
    construction anywhere in the module (including ternary/boolean
    fallbacks whose value subtree contains the construction)."""
    out: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign):
            continue
        has_channel = any(
            isinstance(sub, ast.Call) and (
                (isinstance(sub.func, ast.Attribute)
                 and sub.func.attr == "Channel")
                or (isinstance(sub.func, ast.Name)
                    and sub.func.id == "Channel"))
            for sub in ast.walk(node.value))
        if not has_channel:
            continue
        for tgt in node.targets:
            text = dotted(tgt)
            if text is not None:
                out.add(text)
    return out


# ---------------------------------------------------------------------------
# attribute def-use across methods (JL009)
# ---------------------------------------------------------------------------

def _self_attr(node) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _assign_targets(stmt) -> Iterable[ast.AST]:
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            yield from ast.walk(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        yield from ast.walk(stmt.target)


def attr_assigned_after(method, attr: str, lineno: int) -> bool:
    """True when ``self.<attr>`` is (re)bound anywhere in ``method``
    strictly after ``lineno`` — the donated buffer was replaced before
    anyone else can read it."""
    for stmt in ast.walk(method):
        if getattr(stmt, "lineno", 0) <= lineno:
            continue
        for t in _assign_targets(stmt):
            if _self_attr(t) == attr:
                return True
    return False


def assigned_attr_of_call(ctx, call: ast.Call) -> Set[str]:
    """``self.<attr>`` names the call's result is assigned to
    (``self.p = f(self.p)`` republishes the donated buffer)."""
    parent = ctx.parent(call)
    out: Set[str] = set()
    if isinstance(parent, (ast.Assign, ast.AnnAssign)):
        for t in _assign_targets(parent):
            a = _self_attr(t)
            if a is not None:
                out.add(a)
    return out


def methods_reading_attr(cls: ast.ClassDef, attr: str,
                         exclude) -> List[Tuple[ast.AST, ast.AST]]:
    """(method, read node) pairs for every OTHER method of ``cls``
    loading ``self.<attr>``."""
    out = []
    for stmt in cls.body:
        if not isinstance(stmt, _FUNC_DEFS) or stmt is exclude:
            continue
        for node in ast.walk(stmt):
            if _self_attr(node) == attr and \
                    isinstance(getattr(node, "ctx", None), ast.Load):
                out.append((stmt, node))
                break
    return out


# ---------------------------------------------------------------------------
# closure capture of Python scalars (JL010)
# ---------------------------------------------------------------------------

def bound_names(fn) -> Set[str]:
    """Names bound inside ``fn``: parameters plus every local store."""
    names: Set[str] = set()
    if not isinstance(fn, ast.Lambda):
        a = fn.args
        for arg in (a.posonlyargs + a.args + a.kwonlyargs):
            names.add(arg.arg)
        if a.vararg:
            names.add(a.vararg.arg)
        if a.kwarg:
            names.add(a.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            names.add(node.id)
        elif isinstance(node, _FUNC_DEFS) and node is not fn:
            names.add(node.name)
    return names


def free_reads(fn) -> Dict[str, ast.AST]:
    """name -> first Load node for names read in ``fn`` but never
    bound there (closure candidates)."""
    bound = bound_names(fn)
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and node.id not in bound and node.id not in out:
            out[node.id] = node
    return out


def _is_scalar_const(node) -> bool:
    return isinstance(node, ast.Constant) and \
        isinstance(node.value, (int, float, bool))


def _binding_names(target) -> Set[str]:
    """Names a target BINDS.  ``self.x = v`` binds no name (the base
    is only loaded), so it must not count as rebinding ``self``."""
    if isinstance(target, ast.Name):
        return {target.id}
    if isinstance(target, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for e in target.elts:
            out |= _binding_names(e)
        return out
    if isinstance(target, ast.Starred):
        return _binding_names(target.value)
    return set()


def scalar_rebindings_after(enclosing, inner, name: str,
                            jit) -> List[ast.AST]:
    """Statements in ``enclosing`` (but not inside ``inner`` or any
    other nested def) that rebind ``name`` AFTER ``inner`` is defined,
    where some binding of ``name`` in the scope is Python-scalar-ish
    (a scalar constant or an AugAssign) — the captured value is frozen
    at trace time and these rebindings never reach the compiled code."""
    first_line = inner.lineno
    rebinds: List[ast.AST] = []
    scalarish = False
    for stmt in ast.walk(enclosing):
        inside_nested = False
        # skip statements owned by nested defs (their locals shadow)
        parent = getattr(stmt, "_jaxlint_parent", None)
        while parent is not None and parent is not enclosing:
            if isinstance(parent, _FUNC_DEFS + (ast.Lambda,)):
                inside_nested = True
                break
            parent = getattr(parent, "_jaxlint_parent", None)
        if inside_nested:
            continue
        if isinstance(stmt, ast.Assign):
            hit = any(name in _binding_names(tgt)
                      for tgt in stmt.targets)
            if hit:
                if _is_scalar_const(stmt.value):
                    scalarish = True
                if stmt.lineno > first_line:
                    rebinds.append(stmt)
        elif isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.target, ast.Name) and \
                    stmt.target.id == name:
                scalarish = True
                if stmt.lineno > first_line:
                    rebinds.append(stmt)
        elif isinstance(stmt, ast.For):
            if name in _binding_names(stmt.target):
                if stmt.lineno > first_line:
                    rebinds.append(stmt)
    return rebinds if scalarish else []
