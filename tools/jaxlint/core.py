"""Rule engine: Finding, ModuleContext, suppression, baseline, walking.

Rules are classes with a string ``id``, a one-line ``summary`` and a
``check(ctx) -> iterable[Finding]``; ``@register`` adds them to
``RULE_REGISTRY``.  The engine parses each file once into a
:class:`ModuleContext` and hands it to every rule, then filters the
findings through per-line suppressions (``# jaxlint: disable=JL003``)
and the baseline file.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional

RULE_REGISTRY: Dict[str, type] = {}

# `# jaxlint: disable` silences every rule on the line; `=JL001,JL002`
# silences only those ids.  The comment can sit on the flagged line or
# alone on the line directly above it.
_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable(?:=(?P<ids>[A-Za-z0-9,\s]+))?")


def register(cls):
    RULE_REGISTRY[cls.id] = cls
    return cls


class BaselineError(RuntimeError):
    """A baseline file that is missing or unparseable.  Typed (and
    naming the file) so a misconfigured gate fails loudly instead of
    silently linting against an empty baseline."""


def suppressed_in_lines(lines, lineno: int, rule: str) -> bool:
    """The one suppression definition (``# jaxlint: disable[=IDs]`` on
    the flagged line, or comment-only on the line above), shared by the
    per-file pass and the project-level contract pass."""
    for ln in (lineno, lineno - 1):
        text = lines[ln - 1] if 1 <= ln <= len(lines) else ""
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        if ln != lineno and text.lstrip()[:1] != "#":
            continue  # line above counts only when comment-only
        ids = m.group("ids")
        if ids is None:
            return True
        if rule in {i.strip() for i in ids.split(",")}:
            return True
    return False


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str
    line_text: str = ""

    def key(self) -> str:
        # Baseline identity deliberately omits the line NUMBER: unrelated
        # edits above a baselined finding must not un-baseline it.
        return f"{self.path}::{self.rule}::{self.line_text.strip()}"

    def render(self, fmt: str = "text") -> str:
        if fmt == "github":
            return (f"::error file={self.path},line={self.line},"
                    f"col={self.col},title={self.rule}::{self.message}")
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class Rule:
    """Base class so rules share the finding constructor."""

    id = "JL000"
    summary = "base rule"

    def finding(self, ctx: "ModuleContext", node, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(path=ctx.path, line=line, col=col, rule=self.id,
                       message=message, line_text=ctx.line_text(line))

    def check(self, ctx: "ModuleContext") -> Iterable[Finding]:
        raise NotImplementedError


class ModuleContext:
    """One parsed file plus the lazily-built jit analysis shared by rules."""

    def __init__(self, path: str, source: str, project=None):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._jaxlint_parent = node  # type: ignore[attr-defined]
        self._jit = None
        #: the ProjectRegistry when linting inside a project tree (set
        #: by lint_paths); rules needing interprocedural project
        #: context (JL008's stage namespace) skip when it is None
        self.project = project

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def parent(self, node):
        return getattr(node, "_jaxlint_parent", None)

    @property
    def jit(self):
        if self._jit is None:
            from .jitmodel import JitAnalysis
            self._jit = JitAnalysis(self)
        return self._jit

    def suppressed(self, finding: Finding) -> bool:
        return suppressed_in_lines(self.lines, finding.line, finding.rule)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: Optional[str] = None, *,
                  missing_ok: bool = False) -> Dict[str, str]:
    """Baseline keys -> justification strings ('' when none recorded).

    A missing or corrupt baseline raises :class:`BaselineError` naming
    the file — treating it as empty would silently re-report every
    baselined finding (or worse, pass a gate that was meant to read a
    baseline that a bad path argument skipped)."""
    path = path or default_baseline_path()
    if not os.path.exists(path):
        if missing_ok:
            return {}
        raise BaselineError(f"jaxlint: baseline file not found: {path}")
    try:
        with open(path) as f:
            data = json.load(f)
    except ValueError as e:
        raise BaselineError(
            f"jaxlint: corrupt baseline file {path}: {e}") from e
    if not isinstance(data, dict) or not isinstance(
            data.get("findings", []), list):
        raise BaselineError(
            f"jaxlint: corrupt baseline file {path}: expected an object "
            "with a 'findings' list")
    entries = data.get("findings", [])
    out: Dict[str, str] = {}
    for e in entries:
        if isinstance(e, str):
            out[e] = ""
        else:
            out[e["key"]] = e.get("why", "")
    return out


def write_baseline(findings: List[Finding], path: Optional[str] = None):
    path = path or default_baseline_path()
    existing = load_baseline(path, missing_ok=True)  # keep justifications
    payload = {
        "version": 1,
        "comment": ("Accepted pre-existing findings. Every entry needs a "
                    "'why'; prefer fixing over baselining (docs/jaxlint.md)."),
        "findings": [{"key": f.key(), "why": existing.get(f.key(), "")}
                     for f in findings],
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


# ---------------------------------------------------------------------------
# walking + running
# ---------------------------------------------------------------------------

_SKIP_DIRS = {".git", "__pycache__", ".mypy_cache", ".pytest_cache",
              "build", "dist", "node_modules"}


def iter_python_files(paths: Iterable[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
            continue
        if not os.path.isdir(p):
            # a typoed path must not silently turn the gate into a no-op
            raise FileNotFoundError(f"jaxlint: no such file or directory: {p}")
        for root, dirs, names in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d not in _SKIP_DIRS and not d.startswith("."))
            for n in sorted(names):
                if n.endswith(".py"):
                    files.append(os.path.join(root, n))
    return files


def lint_file(path: str, rules: Optional[List[str]] = None,
              project=None) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    return lint_source(source, path=path, rules=rules, project=project)


def lint_source(source: str, path: str = "<string>",
                rules: Optional[List[str]] = None,
                project=None) -> List[Finding]:
    try:
        ctx = ModuleContext(path, source, project=project)
    except SyntaxError as e:
        return [Finding(path=path, line=e.lineno or 1, col=e.offset or 0,
                        rule="JL000", message=f"syntax error: {e.msg}",
                        line_text="")]
    out: List[Finding] = []
    for rule_id, cls in sorted(RULE_REGISTRY.items()):
        if rules is not None and rule_id not in rules:
            continue
        for f in cls().check(ctx):
            if not ctx.suppressed(f):
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def lint_paths(paths: Iterable[str],
               rules: Optional[List[str]] = None,
               contracts_only: bool = False) -> List[Finding]:
    """The two-pass entry point.

    Pass 1 builds the :class:`~.registry.ProjectRegistry` for the
    enclosing project root (the nearest ancestor carrying ``docs/`` +
    ``tools/``); pass 2 runs the per-file rules with that project
    context plus the project-level contract rules (JL102–JL104).
    Without a discoverable root the per-file pass still runs alone.
    Finding paths are normalized project-root-relative so baselines
    and ``--format=github`` output are invocation-cwd independent.
    ``contracts_only`` skips the per-file pass (the cheap CI
    pre-flight).
    """
    paths = list(paths)
    files = iter_python_files(paths)
    from .registry import ProjectRegistry, find_project_root
    root = find_project_root(paths)
    reg = ProjectRegistry.build(root) if root is not None else None

    findings: List[Finding] = []
    if not contracts_only:
        for fp in files:
            with open(fp, encoding="utf-8") as f:
                source = f.read()
            display = fp
            if root is not None:
                ap = os.path.abspath(fp)
                if ap.startswith(root + os.sep):
                    display = os.path.relpath(ap, root)
            findings.extend(lint_source(source, path=display,
                                        rules=rules, project=reg))
    if reg is not None:
        from .contracts import run_project_rules
        findings.extend(run_project_rules(reg, rules=rules))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
