"""Rule engine: Finding, ModuleContext, suppression, baseline, walking.

Rules are classes with a string ``id``, a one-line ``summary`` and a
``check(ctx) -> iterable[Finding]``; ``@register`` adds them to
``RULE_REGISTRY``.  The engine parses each file once into a
:class:`ModuleContext` and hands it to every rule, then filters the
findings through per-line suppressions (``# jaxlint: disable=JL003``)
and the baseline file.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional

RULE_REGISTRY: Dict[str, type] = {}

# `# jaxlint: disable` silences every rule on the line; `=JL001,JL002`
# silences only those ids.  The comment can sit on the flagged line or
# alone on the line directly above it.
_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable(?:=(?P<ids>[A-Za-z0-9,\s]+))?")


def register(cls):
    RULE_REGISTRY[cls.id] = cls
    return cls


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str
    line_text: str = ""

    def key(self) -> str:
        # Baseline identity deliberately omits the line NUMBER: unrelated
        # edits above a baselined finding must not un-baseline it.
        return f"{self.path}::{self.rule}::{self.line_text.strip()}"

    def render(self, fmt: str = "text") -> str:
        if fmt == "github":
            return (f"::error file={self.path},line={self.line},"
                    f"col={self.col},title={self.rule}::{self.message}")
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class Rule:
    """Base class so rules share the finding constructor."""

    id = "JL000"
    summary = "base rule"

    def finding(self, ctx: "ModuleContext", node, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(path=ctx.path, line=line, col=col, rule=self.id,
                       message=message, line_text=ctx.line_text(line))

    def check(self, ctx: "ModuleContext") -> Iterable[Finding]:
        raise NotImplementedError


class ModuleContext:
    """One parsed file plus the lazily-built jit analysis shared by rules."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._jaxlint_parent = node  # type: ignore[attr-defined]
        self._jit = None

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def parent(self, node):
        return getattr(node, "_jaxlint_parent", None)

    @property
    def jit(self):
        if self._jit is None:
            from .jitmodel import JitAnalysis
            self._jit = JitAnalysis(self)
        return self._jit

    def suppressed(self, finding: Finding) -> bool:
        for lineno in (finding.line, finding.line - 1):
            text = self.line_text(lineno)
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            if lineno != finding.line and text.lstrip()[:1] != "#":
                continue  # line above counts only when comment-only
            ids = m.group("ids")
            if ids is None:
                return True
            if finding.rule in {i.strip() for i in ids.split(",")}:
                return True
        return False


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: Optional[str] = None) -> Dict[str, str]:
    """Baseline keys -> justification strings ('' when none recorded)."""
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    entries = data.get("findings", [])
    out: Dict[str, str] = {}
    for e in entries:
        if isinstance(e, str):
            out[e] = ""
        else:
            out[e["key"]] = e.get("why", "")
    return out


def write_baseline(findings: List[Finding], path: Optional[str] = None):
    path = path or default_baseline_path()
    existing = load_baseline(path)  # keep recorded justifications
    payload = {
        "version": 1,
        "comment": ("Accepted pre-existing findings. Every entry needs a "
                    "'why'; prefer fixing over baselining (docs/jaxlint.md)."),
        "findings": [{"key": f.key(), "why": existing.get(f.key(), "")}
                     for f in findings],
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


# ---------------------------------------------------------------------------
# walking + running
# ---------------------------------------------------------------------------

_SKIP_DIRS = {".git", "__pycache__", ".mypy_cache", ".pytest_cache",
              "build", "dist", "node_modules"}


def iter_python_files(paths: Iterable[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
            continue
        if not os.path.isdir(p):
            # a typoed path must not silently turn the gate into a no-op
            raise FileNotFoundError(f"jaxlint: no such file or directory: {p}")
        for root, dirs, names in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d not in _SKIP_DIRS and not d.startswith("."))
            for n in sorted(names):
                if n.endswith(".py"):
                    files.append(os.path.join(root, n))
    return files


def lint_file(path: str, rules: Optional[List[str]] = None) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    return lint_source(source, path=path, rules=rules)


def lint_source(source: str, path: str = "<string>",
                rules: Optional[List[str]] = None) -> List[Finding]:
    try:
        ctx = ModuleContext(path, source)
    except SyntaxError as e:
        return [Finding(path=path, line=e.lineno or 1, col=e.offset or 0,
                        rule="JL000", message=f"syntax error: {e.msg}",
                        line_text="")]
    out: List[Finding] = []
    for rule_id, cls in sorted(RULE_REGISTRY.items()):
        if rules is not None and rule_id not in rules:
            continue
        for f in cls().check(ctx):
            if not ctx.suppressed(f):
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def lint_paths(paths: Iterable[str],
               rules: Optional[List[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for fp in iter_python_files(paths):
        findings.extend(lint_file(fp, rules=rules))
    return findings
