"""``python -m tools.jaxlint`` entry point."""
import sys

from .cli import main

sys.exit(main())
