"""The jaxlint rules: JL001-JL005 (tracer safety) and JL101 (config schema).

Each rule documents the TPU failure mode it prevents; docs/jaxlint.md
is the user-facing version of the same list.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Finding, ModuleContext, Rule, register
from .jitmodel import _FUNC_DEFS, dotted, is_wrapper_ref, is_wrapper_text


def scope_walk(root):
    """Walk ``root`` without descending into nested function/class defs —
    the statement-level view of ONE scope."""
    stack = [root]
    first = True
    while stack:
        node = stack.pop()
        if not first and isinstance(node, _FUNC_DEFS + (ast.Lambda,
                                                        ast.ClassDef)):
            continue
        first = False
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _local_names(fn) -> Set[str]:
    """Parameter and locally-assigned names of a def."""
    names: Set[str] = set()
    if isinstance(fn, _FUNC_DEFS + (ast.Lambda,)):
        a = fn.args
        for arg in (a.posonlyargs + a.args + a.kwonlyargs
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            names.add(arg.arg)
    for node in scope_walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        elif isinstance(node, _FUNC_DEFS) and node is not fn:
            names.add(node.name)
    return names


def _call_text(node: ast.Call) -> Optional[str]:
    return dotted(node.func)


# ---------------------------------------------------------------------------
# JL001 — host syncs under trace
# ---------------------------------------------------------------------------

_SYNC_CALLS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
               "jax.device_get"}
_SYNC_METHODS = {"item", "block_until_ready", "tolist"}
_SYNC_BUILTINS = {"float", "int", "bool"}


@register
class HostSyncRule(Rule):
    id = "JL001"
    summary = "host-sync call reachable from jit-traced code"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        jit = ctx.jit
        for fn, is_root in jit.traced_bodies():
            if isinstance(fn, ast.Lambda):
                continue
            where = (f"'{fn.name}' (jitted)" if is_root
                     else f"'{fn.name}' (called from jit-traced code)")
            for node in scope_walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                text = _call_text(node)
                if text in _SYNC_CALLS:
                    yield self.finding(
                        ctx, node,
                        f"host sync '{text}' inside {where}: forces a "
                        "device->host transfer every step (or a tracer "
                        "leak); hoist it out of the traced region")
                elif text in _SYNC_BUILTINS and len(node.args) == 1 \
                        and not isinstance(node.args[0], ast.Constant) \
                        and not node.keywords:
                    yield self.finding(
                        ctx, node,
                        f"'{text}(...)' on a non-literal inside {where}: "
                        "concretizes a traced array (host sync / "
                        "ConcretizationTypeError on TPU)")
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _SYNC_METHODS \
                        and not node.args and not node.keywords:
                    yield self.finding(
                        ctx, node,
                        f"'.{node.func.attr}()' inside {where}: blocks on "
                        "device results under trace")


# ---------------------------------------------------------------------------
# JL002 — use after donation
# ---------------------------------------------------------------------------

def _store_events(scope_root) -> List[Tuple[str, int]]:
    """(dotted-target-text, lineno) for every assignment in the scope."""
    out: List[Tuple[str, int]] = []

    def add_target(t):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                add_target(e)
            return
        if isinstance(t, ast.Starred):
            add_target(t.value)
            return
        text = dotted(t)
        if text is not None:
            out.append((text, t.lineno))

    for node in scope_walk(scope_root):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                add_target(t)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            add_target(node.target)
        elif isinstance(node, ast.For):
            add_target(node.target)
        elif isinstance(node, ast.withitem) and node.optional_vars is not None:
            add_target(node.optional_vars)
    return out


def _alias_map(scope_root) -> Dict[str, Set[str]]:
    """Bidirectional alias pairs from simple ``a = self.b`` assignments."""
    aliases: Dict[str, Set[str]] = {}
    for node in scope_walk(scope_root):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            lhs, rhs = dotted(node.targets[0]), dotted(node.value)
            if lhs and rhs:
                aliases.setdefault(lhs, set()).add(rhs)
                aliases.setdefault(rhs, set()).add(lhs)
    return aliases


@register
class UseAfterDonationRule(Rule):
    id = "JL002"
    summary = "buffer read after being donated to a jitted call"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        jit = ctx.jit
        scopes = [ctx.tree] + [fn for fn in jit.defs]
        for scope in scopes:
            yield from self._check_scope(ctx, jit, scope)

    def _donation_site(self, jit, call: ast.Call, scope):
        """Donation info for a call, by callee name or inline jit(...)()."""
        text = _call_text(call)
        if text is not None:
            site = jit.lookup_callable(
                text, scope if not isinstance(scope, ast.Module) else None)
            if site is not None:
                return site if site.donates else None
        # inline form: jax.jit(f, donate_argnums=...)(x)
        if isinstance(call.func, ast.Call) and is_wrapper_ref(call.func.func):
            site = jit._parse_site(call.func)
            return site if site.donates else None
        return None

    def _check_scope(self, ctx, jit, scope):
        stores = _store_events(scope)
        aliases = _alias_map(scope)
        for call in scope_walk(scope):
            if not isinstance(call, ast.Call):
                continue
            site = self._donation_site(jit, call, scope)
            if site is None:
                continue
            donated: List[ast.AST] = []
            for i in site.donate_argnums:
                if i < len(call.args):
                    donated.append(call.args[i])
            for kw in call.keywords:
                if kw.arg in site.donate_argnames:
                    donated.append(kw.value)
            callee = _call_text(call) or "<jitted call>"
            end = getattr(call, "end_lineno", call.lineno)
            for arg in donated:
                text = dotted(arg)
                if text is None:
                    continue  # expression result: nothing to alias-track
                tainted = {text} | aliases.get(text, set())
                yield from self._reads_after(
                    ctx, scope, tainted, end, stores, callee, call.lineno)

    def _reads_after(self, ctx, scope, tainted, after_line, stores,
                     callee, call_line):
        reported: Set[str] = set()
        loads = []
        for node in scope_walk(scope):
            if isinstance(node, (ast.Name, ast.Attribute)) \
                    and isinstance(getattr(node, "ctx", None), ast.Load):
                text = dotted(node)
                if text in tainted and node.lineno > after_line:
                    loads.append((node.lineno, node, text))
        for lineno, node, text in sorted(loads, key=lambda t: t[0]):
            if text in reported:
                continue
            # a reassignment between the donating call and the read
            # revives the name (e.g. ``state = step(state)``)
            if any(s_text == text and call_line <= s_line <= lineno
                   for s_text, s_line in stores):
                continue
            reported.add(text)
            yield self.finding(
                ctx, node,
                f"'{text}' is read after being donated to '{callee}' "
                f"(line {call_line}): donated buffers are deleted by XLA; "
                "rebind the name from the call's result first")


# ---------------------------------------------------------------------------
# JL003 — in_shardings without out_shardings
# ---------------------------------------------------------------------------

@register
class OutShardingsRule(Rule):
    id = "JL003"
    summary = "jit with in_shardings but no out_shardings"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        jit = ctx.jit
        for site in jit.sites:
            if site.has_in_shardings and not site.has_out_shardings:
                yield self.finding(
                    ctx, site.node,
                    "jit call passes in_shardings but no out_shardings: "
                    "outputs fall back to default placement, so the next "
                    "step sees different avals and retraces/recompiles "
                    "every call on a multi-device mesh")
        # second check, the engine.py:1685 bug class: inside ONE builder
        # function, some jit sites pin out_shardings and a sibling site
        # does not — its outputs ride default placement while the rest of
        # the state is pinned, which diverges on a multi-device mesh
        by_scope: Dict = {}
        for site in jit.sites:
            if site.is_decorator:
                continue
            scope = jit.enclosing_function(site.node)
            if scope is not None:
                by_scope.setdefault(scope, []).append(site)
        for scope, sites in by_scope.items():
            pinned = [s for s in sites if s.has_out_shardings]
            bare = [s for s in sites if not s.has_out_shardings]
            if pinned and bare:
                for s in bare:
                    yield self.finding(
                        ctx, s.node,
                        f"jit site without out_shardings in "
                        f"'{scope.name}' while sibling jit sites pin "
                        "theirs: this program's outputs fall back to "
                        "default placement and diverge from the pinned "
                        "state on a multi-device mesh")


# ---------------------------------------------------------------------------
# JL004 — Python side effects under trace
# ---------------------------------------------------------------------------

# 'update' and 'pop' are deliberately absent: tx.update(...) is the
# (pure) optax GradientTransformation idiom and .pop shows up on plenty
# of non-container objects — too ambiguous without type information
_MUTATORS = {"append", "extend", "insert", "add", "setdefault",
             "remove", "discard", "clear", "popitem"}


@register
class SideEffectRule(Rule):
    id = "JL004"
    summary = "Python side effect inside a jit-traced body"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        jit = ctx.jit
        for fn, is_root in jit.traced_bodies():
            if isinstance(fn, ast.Lambda):
                continue
            local = _local_names(fn)
            where = (f"'{fn.name}'" if is_root
                     else f"'{fn.name}' (called from jit-traced code)")
            for node in scope_walk(fn):
                yield from self._check_node(ctx, node, local, where)

    def _check_node(self, ctx, node, local, where):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                base = t
                while isinstance(base, (ast.Attribute, ast.Subscript)):
                    base = base.value
                if isinstance(t, (ast.Attribute, ast.Subscript)) \
                        and isinstance(base, ast.Name):
                    if base.id == "self":
                        yield self.finding(
                            ctx, t,
                            f"assignment to '{dotted(t) or base.id + '[...]'}' "
                            f"inside jit-traced {where}: runs once at trace "
                            "time, not per step — the object mutation is a "
                            "silent no-op on later calls")
                    elif isinstance(t, ast.Subscript) \
                            and base.id not in local:
                        yield self.finding(
                            ctx, t,
                            f"subscript store to closed-over '{base.id}' "
                            f"inside jit-traced {where}: mutates a Python "
                            "object at trace time only")
        elif isinstance(node, ast.Global):
            yield self.finding(
                ctx, node,
                f"'global' inside jit-traced {where}: global mutation "
                "happens at trace time only")
        elif isinstance(node, ast.Call):
            text = _call_text(node)
            if text == "print":
                yield self.finding(
                    ctx, node,
                    f"'print' inside jit-traced {where}: prints tracers "
                    "once at trace time; use jax.debug.print for runtime "
                    "values")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id not in local \
                    and node.func.value.id != "self":
                yield self.finding(
                    ctx, node,
                    f"'.{node.func.attr}' on closed-over "
                    f"'{node.func.value.id}' inside jit-traced {where}: "
                    "mutates a Python container at trace time only")


# ---------------------------------------------------------------------------
# JL005 — recompilation hazards
# ---------------------------------------------------------------------------

_CLOCK_CALLS = {"time.time", "time.time_ns", "time.perf_counter",
                "time.monotonic", "datetime.now", "datetime.utcnow",
                "datetime.datetime.now", "datetime.datetime.utcnow",
                "date.today", "datetime.date.today"}
_NONDET_PREFIXES = ("np.random.", "numpy.random.", "random.")


@register
class RecompilationRule(Rule):
    id = "JL005"
    summary = "recompilation hazard (unhashable static arg, trace-time clock)"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        jit = ctx.jit
        # (a) unhashable / per-call-varying values in static positions of
        # known jitted callables: every call re-traces (dict/list) or
        # re-specializes (f-string) the program
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            text = _call_text(node)
            site = jit.callables.get(text) if text else None
            if site is None or not (site.static_argnums
                                    or site.static_argnames):
                continue
            static_args = [(i, node.args[i]) for i in site.static_argnums
                           if i < len(node.args)]
            static_args += [(kw.arg, kw.value) for kw in node.keywords
                            if kw.arg in site.static_argnames]
            for pos, arg in static_args:
                if isinstance(arg, (ast.Dict, ast.List, ast.Set)):
                    yield self.finding(
                        ctx, arg,
                        f"unhashable literal passed for static argument "
                        f"{pos!r} of jitted '{text}': static args must be "
                        "hashable and stable or every call recompiles")
                elif isinstance(arg, ast.JoinedStr):
                    yield self.finding(
                        ctx, arg,
                        f"f-string passed for static argument {pos!r} of "
                        f"jitted '{text}': a fresh string per call defeats "
                        "the jit cache (one recompile per distinct value)")
        # (b) trace-time clocks / RNG inside traced bodies: each trace
        # bakes a different constant, so shapes or cache keys derived from
        # them force retraces (and silently freeze otherwise)
        for fn, is_root in jit.traced_bodies():
            if isinstance(fn, ast.Lambda):
                continue
            for node in scope_walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                text = _call_text(node)
                if text is None:
                    continue
                if text in _CLOCK_CALLS or \
                        any(text.startswith(p) for p in _NONDET_PREFIXES):
                    yield self.finding(
                        ctx, node,
                        f"'{text}' inside jit-traced '{fn.name}': evaluated "
                        "once at trace time — a frozen constant at best, a "
                        "shape-varying recompile trigger at worst; pass the "
                        "value in as an argument")


# ---------------------------------------------------------------------------
# JL006 — dispatch-only timing
# ---------------------------------------------------------------------------

_JL006_CLOCKS = {"time.time", "time.time_ns", "time.perf_counter",
                 "time.monotonic"}
# calls that drain (or materialize) device work, bounding a timed
# section — JL001's sync sets plus the drain-only spellings that are
# fine under trace but DO bound a host-side timed window (derived, not
# re-listed, so a new sync spelling teaches both rules)
_JL006_SYNC_CALLS = _SYNC_CALLS | {"jax.block_until_ready",
                                   "jax.effects_barrier"}
_JL006_SYNC_METHODS = _SYNC_METHODS | {"synchronize_all_activity"}
# jax.* namespaces that never enqueue device work worth timing
_JL006_JAX_EXCLUDE = ("jax.tree", "jax.tree_util", "jax.profiler",
                      "jax.config", "jax.debug", "jax.monitoring",
                      "jax.sharding", "jax.eval_shape")


@register
class DispatchOnlyTimingRule(Rule):
    id = "JL006"
    summary = "wall-clock delta brackets async jax dispatch with no sync"

    # Under jax's async dispatch, ``t0 = time.time(); y = step(x);
    # dt = time.time() - t0`` measures ENQUEUE latency, not device step
    # time — samples/sec derived from it inflates by orders of magnitude
    # (the engine documents exactly this bug class for ``_step_times``).
    # The timed section is bounded only if something between the two
    # clock reads drains the device (block_until_ready / device_get /
    # np.asarray / a synchronize helper).

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        jit = ctx.jit
        scopes = [ctx.tree] + list(jit.defs)
        for scope in scopes:
            # traced bodies are JL005's territory (clocks there freeze at
            # trace time; "dispatch-only" timing is a host-side bug)
            if scope in jit.reachable_defs:
                continue
            yield from self._check_scope(ctx, jit, scope)

    # -- classification --------------------------------------------------
    @staticmethod
    def _is_clock_call(node) -> bool:
        return (isinstance(node, ast.Call)
                and _call_text(node) in _JL006_CLOCKS)

    def _is_sync(self, text: Optional[str], node: ast.Call) -> bool:
        if text in _JL006_SYNC_CALLS:
            return True
        last = text.split(".")[-1] if text else ""
        if last in _JL006_SYNC_METHODS:
            return True
        if "synchronize" in last.lower():
            return True  # _synchronize()-style helpers
        if text in _SYNC_BUILTINS and len(node.args) == 1 \
                and not isinstance(node.args[0], ast.Constant):
            return True  # float(x)/int(x) materializes
        return False

    def _is_dispatch(self, jit, text: Optional[str], scope) -> bool:
        if text is None:
            return False
        fn_scope = scope if not isinstance(scope, ast.Module) else None
        if jit.lookup_callable(text, fn_scope) is not None:
            return True  # known jitted callable
        last = text.split(".")[-1]
        if last.endswith("_step") or last in ("step_fn",) \
                or last.endswith("_jit"):
            return True  # compiled-step driver naming convention
        if text.startswith("jax.") and not is_wrapper_text(text) \
                and not any(text.startswith(p) for p in _JL006_JAX_EXCLUDE):
            return True  # direct jax op/dispatch
        return False

    # -- the scan --------------------------------------------------------
    def _check_scope(self, ctx, jit, scope):
        clock_stores: Dict[str, List[int]] = {}
        syncs: List[int] = []
        dispatches: List[Tuple[int, str]] = []
        deltas: List[ast.BinOp] = []
        for node in scope_walk(scope):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = dotted(node.targets[0])
                if tgt is not None and self._is_clock_call(node.value):
                    clock_stores.setdefault(tgt, []).append(node.lineno)
            if isinstance(node, ast.Call):
                text = _call_text(node)
                if text in _JL006_CLOCKS:
                    continue
                if self._is_sync(text, node):
                    syncs.append(node.lineno)
                elif self._is_dispatch(jit, text, scope):
                    dispatches.append((node.lineno, text))
            elif isinstance(node, ast.BinOp) and isinstance(node.op,
                                                            ast.Sub):
                deltas.append(node)
        if not clock_stores or not dispatches:
            return
        for delta in deltas:
            rhs = dotted(delta.right)
            starts = clock_stores.get(rhs, []) if rhs else []
            starts = [ln for ln in starts if ln < delta.lineno]
            if not starts:
                continue
            start = max(starts)
            # left side must read a clock: a direct call, or a name the
            # scope stored a later clock read into
            if self._is_clock_call(delta.left):
                end = delta.lineno
            else:
                lhs = dotted(delta.left)
                ends = [ln for ln in clock_stores.get(lhs, [])
                        if start < ln <= delta.lineno] if lhs else []
                if not ends:
                    continue
                end = max(ends)
            window = [(ln, t) for ln, t in dispatches if start < ln <= end]
            if not window:
                continue
            if any(start < ln <= end for ln in syncs):
                continue
            _, first_dispatch = min(window)
            yield self.finding(
                ctx, delta,
                f"wall-clock delta over '{rhs}' (line {start}) brackets "
                f"the async dispatch '{first_dispatch}' with no "
                "intervening sync: under jax async dispatch this measures "
                "ENQUEUE latency, not device time — block_until_ready (or "
                "materialize a result) before reading the clock, or time "
                "a synced interval instead")


# ---------------------------------------------------------------------------
# JL007 — raw daemon-thread construction outside the stage runtime
# ---------------------------------------------------------------------------

#: the one module allowed to construct threads — the shared async-stage
#: runtime every runtime subsystem builds its workers from
#: (docs/stages.md).  Matched as the FULL package path suffix, not a
#: basename: neither a future serving/stages.py nor a nested
#: .../something/runtime/stages.py inherits the exemption.
_JL007_EXEMPT_SUFFIX = ("deepspeed_tpu", "runtime", "stages.py")


@register
class RawDaemonThreadRule(Rule):
    id = "JL007"
    summary = ("raw threading.Thread(daemon=True) outside the stage "
               "runtime (runtime/stages.py)")

    # Every hand-rolled daemon worker re-invents the same queue/poison/
    # drain/watchdog semantics, and each copy drifts (the PR 3/PR 5
    # half-swapped-tree and writer-drain bugs were both instances).
    # stages.spawn() is the sanctioned constructor: restart-on-crash
    # policy, JL007-visible, and one place to audit shutdown behavior.

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        parts = os.path.normpath(ctx.path).split(os.sep)
        if tuple(parts[-3:]) == _JL007_EXEMPT_SUFFIX:
            return
        thread_aliases = {"threading.Thread"}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and \
                    node.module == "threading":
                for alias in node.names:
                    if alias.name == "Thread":
                        thread_aliases.add(alias.asname or "Thread")
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "threading" and alias.asname:
                        thread_aliases.add(f"{alias.asname}.Thread")
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_text(node) not in thread_aliases:
                continue
            daemon = next((kw for kw in node.keywords
                           if kw.arg == "daemon"), None)
            if daemon is None or not (
                    isinstance(daemon.value, ast.Constant)
                    and daemon.value.value is True):
                continue
            yield self.finding(
                ctx, node,
                "raw threading.Thread(daemon=True): build workers from "
                "the shared stage runtime (deepspeed_tpu.runtime.stages."
                "spawn) so poison/drain/restart semantics stay one "
                "tested plane")


# ---------------------------------------------------------------------------
# JL101 — config keys cross-checked against constants.py
# ---------------------------------------------------------------------------

def _constants_alias(tree) -> Optional[str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name.split(".")[-1] == "constants":
                    return a.asname or a.name
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.split(".")[-1] == "constants":
                    return a.asname or a.name.split(".")[-1]
    return None


def _constants_names(path: str) -> Optional[Set[str]]:
    const_path = os.path.join(os.path.dirname(os.path.abspath(path)),
                              "constants.py")
    if not os.path.exists(const_path):
        return None
    with open(const_path, encoding="utf-8") as f:
        try:
            tree = ast.parse(f.read(), filename=const_path)
        except SyntaxError:
            return None
    names: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                            ast.Name):
            names.add(node.target.id)
    return names


@register
class ConfigSchemaRule(Rule):
    id = "JL101"
    summary = "config key not cross-checked against constants.py defaults"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        alias = _constants_alias(ctx.tree)
        if alias is None:
            return
        names = _constants_names(ctx.path)
        if names is None:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            text = _call_text(node)
            if text is not None and text.split(".")[-1] == "get_scalar_param":
                if len(node.args) >= 2:
                    yield from self._check_read(
                        ctx, alias, names, node.args[1],
                        node.args[2] if len(node.args) > 2 else None,
                        explicit_default=len(node.args) > 2)
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "get" and node.args:
                key = node.args[0]
                if self._const_name(alias, key) is not None:
                    yield from self._check_read(
                        ctx, alias, names, key,
                        node.args[1] if len(node.args) > 1 else None,
                        explicit_default=len(node.args) > 1)

    @staticmethod
    def _const_name(alias: str, node) -> Optional[str]:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == alias:
            return node.attr
        return None

    def _check_read(self, ctx, alias, names, key, default, explicit_default):
        key_name = self._const_name(alias, key)
        if key_name is None:
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                yield self.finding(
                    ctx, key,
                    f"string-literal config key {key.value!r} bypasses "
                    f"constants.py: define a constant (and a _DEFAULT) so "
                    "the schema stays checkable")
            return
        if key_name not in names:
            yield self.finding(
                ctx, key,
                f"unknown config key constant {alias}.{key_name}: not "
                "defined in constants.py")
            return
        default_name = self._const_name(alias, default) if default is not None \
            else None
        if default is not None and default_name is not None:
            if default_name not in names:
                yield self.finding(
                    ctx, default,
                    f"unknown default constant {alias}.{default_name}: not "
                    "defined in constants.py")
            elif default_name.endswith("_DEFAULT") \
                    and default_name != key_name + "_DEFAULT":
                yield self.finding(
                    ctx, default,
                    f"default {alias}.{default_name} is cross-wired: key "
                    f"{alias}.{key_name} expects "
                    f"{key_name + '_DEFAULT'}")
        elif not explicit_default and (key_name + "_DEFAULT") in names:
            yield self.finding(
                ctx, key,
                f"defaultless read of {alias}.{key_name}: constants.py "
                f"defines {key_name}_DEFAULT — pass it so the schema has "
                "one source of truth")


# ---------------------------------------------------------------------------
# JL008 — Stage/Channel protocol (interprocedural, project-aware)
# ---------------------------------------------------------------------------

@register
class StageChannelProtocolRule(Rule):
    id = "JL008"
    summary = ("Stage/Channel protocol: unregistered Stage name, "
               "blocking Channel.put outside a worker body, "
               "assignment-aliased raw threads")

    # Three ways the stage plane drifts out from under docs/stages.md:
    # a Stage(...) whose literal name is in no registry (ENGINE_STAGES
    # or the docs contract table) has no drain entry, no chaos spec,
    # no degradation row; a blocking Channel.put outside a worker body
    # deadlocks the step loop the moment the stage degrades (workers
    # gone, nobody drains); and `T = threading.Thread` assignment
    # aliases walk straight past JL007's import-alias tracking.

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        from . import dataflow
        parts = os.path.normpath(ctx.path).split(os.sep)
        exempt_runtime = tuple(parts[-3:]) == _JL007_EXEMPT_SUFFIX

        # (a) Stage("<name>") not in the project's stage namespace
        if ctx.project is not None:
            known = ctx.project.known_stage_names()
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                fn = node.func
                last = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None)
                if last != "Stage":
                    continue
                name = node.args[0]
                if isinstance(name, ast.Constant) and \
                        isinstance(name.value, str) and \
                        name.value not in known:
                    yield self.finding(
                        ctx, node,
                        f"Stage({name.value!r}) is not in the stage "
                        "registry: no ENGINE_STAGES entry and no "
                        "docs/stages.md contract row — it has no "
                        "drain order, chaos spec, or degradation "
                        "fallback")

        # (b) blocking Channel.put outside a worker body
        if not exempt_runtime:
            channels = dataflow.channel_targets(ctx)
            workers = dataflow.worker_body_defs(ctx) if channels else set()
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if not (isinstance(fn, ast.Attribute)
                        and fn.attr == "put"):
                    continue
                recv = dotted(fn.value)
                if recv is None or recv not in channels:
                    continue
                forced = any(
                    kw.arg == "force" and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True for kw in node.keywords) or (
                    len(node.args) > 1
                    and isinstance(node.args[1], ast.Constant)
                    and node.args[1].value is True)
                if forced:
                    continue
                scope = ctx.jit.enclosing_function(node)
                in_worker = False
                while scope is not None:
                    if scope in workers:
                        in_worker = True
                        break
                    scope = ctx.jit.enclosing_function(scope)
                if not in_worker:
                    yield self.finding(
                        ctx, node,
                        f"blocking Channel.put on '{recv}' outside a "
                        "worker body: when the stage degrades its "
                        "workers are gone and nothing drains the "
                        "channel — this put wedges the caller; use "
                        "force=True (drop/overflow policy) or move it "
                        "into the worker closure")

        # (c) raw daemon threads behind assignment aliases (JL007's gap)
        if not exempt_runtime:
            aliases: Set[str] = set()
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    text = dotted(node.value)
                    if text is not None and \
                            text.split(".")[-1] == "Thread" and (
                            text == "threading.Thread"
                            or text == "Thread"):
                        aliases.add(node.targets[0].id)
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                if not (isinstance(node.func, ast.Name)
                        and node.func.id in aliases):
                    continue
                daemon = next((kw for kw in node.keywords
                               if kw.arg == "daemon"), None)
                if daemon is not None and isinstance(
                        daemon.value, ast.Constant) and \
                        daemon.value.value is True:
                    yield self.finding(
                        ctx, node,
                        "raw threading.Thread(daemon=True) behind an "
                        "assignment alias: build workers from the "
                        "shared stage runtime (deepspeed_tpu.runtime."
                        "stages.spawn) — aliasing the class does not "
                        "exempt it")


# ---------------------------------------------------------------------------
# JL009 — interprocedural use-after-donation (cross-method self.attr)
# ---------------------------------------------------------------------------

@register
class CrossMethodDonationRule(Rule):
    id = "JL009"
    summary = ("donated self.<attr> read from another method without "
               "a post-call rebind (cross-function use-after-donation)")

    # JL002 catches donated-buffer reads in the SAME scope; the
    # engine.py:1709 class of bug is the cross-function version: step()
    # donates self.params into the jitted update and snapshot()/save()
    # later reads self.params — a deleted-buffer error only on real
    # TPU (CPU jit ignores donation), i.e. invisible in CI.

    def _donated_args(self, site, call: ast.Call) -> List[ast.AST]:
        out = []
        for idx in site.donate_argnums:
            if idx < len(call.args):
                out.append(call.args[idx])
        if site.donate_argnames:
            params: List[str] = []
            wrapped = site.wrapped
            if wrapped is not None and not isinstance(wrapped, ast.Lambda):
                a = wrapped.args
                params = [x.arg for x in a.posonlyargs + a.args]
            for name in site.donate_argnames:
                for kw in call.keywords:
                    if kw.arg == name:
                        out.append(kw.value)
                if name in params:
                    i = params.index(name)
                    if i < len(call.args):
                        out.append(call.args[i])
        return out

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        from . import dataflow
        jit = ctx.jit
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for method in cls.body:
                if not isinstance(method, _FUNC_DEFS):
                    continue
                for node in ast.walk(method):
                    if not isinstance(node, ast.Call):
                        continue
                    text = dotted(node.func)
                    if text is None:
                        continue
                    site = jit.lookup_callable(
                        text, jit.enclosing_function(node))
                    if site is None or not site.donates:
                        continue
                    republished = dataflow.assigned_attr_of_call(ctx, node)
                    for arg in self._donated_args(site, node):
                        attr = dataflow._self_attr(arg)
                        if attr is None:
                            continue
                        if attr in republished:
                            continue
                        if dataflow.attr_assigned_after(
                                method, attr, node.lineno):
                            continue
                        readers = dataflow.methods_reading_attr(
                            cls, attr, exclude=method)
                        if readers:
                            reader, read = readers[0]
                            yield self.finding(
                                ctx, node,
                                f"self.{attr} is donated here and "
                                f"never rebound in {method.name}(); "
                                f"{reader.name}() (line "
                                f"{read.lineno}) still reads it — a "
                                "deleted-buffer error on TPU; rebind "
                                "self attributes to the jitted "
                                "call's result before returning")


# ---------------------------------------------------------------------------
# JL010 — frozen Python scalars closed over by jitted callables
# ---------------------------------------------------------------------------

@register
class FrozenClosureScalarRule(Rule):
    id = "JL010"
    summary = ("Python scalar closed over by a jitted callable and "
               "rebound afterwards — the traced value is frozen")

    # Extends JL005 with a def-use chain: jit bakes closed-over Python
    # scalars into the compiled program as constants at trace time.
    # Rebinding the scalar afterwards (a schedule loop, a warmup
    # counter) silently does nothing — no recompile, no error, the
    # stale constant runs forever.  Pass the value as an argument
    # (retrace per value via static_argnums, or a traced operand).

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        from . import dataflow
        jit = ctx.jit
        for fn in sorted(jit.jitted_defs,
                         key=lambda n: getattr(n, "lineno", 0)):
            enclosing = jit.enclosing_function(fn)
            if enclosing is None:
                continue
            enclosing_locals = _local_names(enclosing)
            for name, read in dataflow.free_reads(fn).items():
                if name not in enclosing_locals:
                    continue
                rebinds = dataflow.scalar_rebindings_after(
                    enclosing, fn, name, jit)
                if rebinds:
                    yield self.finding(
                        ctx, rebinds[0],
                        f"'{name}' was captured by jitted "
                        f"'{getattr(fn, 'name', '<lambda>')}' (line "
                        f"{fn.lineno}) at trace time; this rebinding "
                        "never reaches the compiled function — pass "
                        "it as an argument (static_argnums for "
                        "shape-like values) instead of a closure")
