"""Convert public LLM-serving traces into the loadgen ``trace`` shape.

``python -m tools.loadgen convert <src> <dst>`` turns one row of a
published trace into one JSONL line of the replayable shape
:func:`tools.loadgen.workload.load_trace` reads::

    {"at_s": <seconds from the first row>, "prompt_len": N,
     "gen_tokens": M}

Two source dialects, auto-detected (``--format`` overrides):

``azure``
    The Azure LLM inference trace CSVs: a header row naming (at
    least) ``TIMESTAMP``, ``ContextTokens``, ``GeneratedTokens``.
    Timestamps are ISO datetimes (any fractional precision) or plain
    epoch seconds.

``mooncake``
    The Mooncake open-trace JSONL: one object per line with
    ``timestamp`` (milliseconds), ``input_length``,
    ``output_length``.  Lines already in the native ``at_s`` shape
    pass through normalized, so converting a converted file is
    idempotent.

The reader is TOLERANT, matching the summarize idiom: torn lines,
missing timestamps, and unparseable fields are skipped (counted, not
fatal) — public traces ship with ragged tails.  Rows are re-sorted by
time and rebased so the first kept row lands at ``at_s == 0.0``.
"""
import argparse
import csv
import datetime
import json
import re
from typing import List, Optional, Tuple

__all__ = ["convert_trace", "detect_format", "main"]

#: (at_s, prompt_len-or-None, gen_tokens-or-None)
Row = Tuple[float, Optional[int], Optional[int]]

_FRACTION = re.compile(r"\.(\d+)")


def _clamp_fraction(m: "re.Match") -> str:
    # fromisoformat (py3.10) wants exactly 3 or 6 fractional digits;
    # traces ship anything from 1 to 7 — normalize to microseconds
    return "." + m.group(1)[:6].ljust(6, "0")


def _parse_timestamp(raw) -> Optional[float]:
    """Seconds from a trace timestamp cell: plain numbers are epoch
    seconds; anything else is tried as an ISO datetime with the
    fraction clamped to microseconds (Azure ships 7 digits, which
    ``fromisoformat`` rejects)."""
    if raw is None:
        return None
    text = str(raw).strip()
    if not text:
        return None
    try:
        return float(text)
    except ValueError:
        pass
    try:
        dt = datetime.datetime.fromisoformat(
            _FRACTION.sub(_clamp_fraction, text.replace("Z", "+00:00")))
    except ValueError:
        return None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return dt.timestamp()


def _parse_len(raw) -> Optional[int]:
    try:
        n = int(float(raw))
    except (TypeError, ValueError):
        return None
    return n if n >= 0 else None


def detect_format(path: str) -> str:
    """``azure`` | ``mooncake`` by sniffing the first non-empty line:
    a JSON object is mooncake-dialect JSONL, anything else is tried as
    headered CSV."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            return "mooncake" if line.startswith("{") else "azure"
    return "azure"


def _read_azure(path: str) -> Tuple[List[Row], int]:
    rows: List[Row] = []
    skipped = 0
    with open(path, newline="") as f:
        for rec in csv.DictReader(f):
            # header names vary across trace releases in case only
            low = {(k or "").strip().lower(): v
                   for k, v in rec.items()}
            at = _parse_timestamp(low.get("timestamp"))
            if at is None:
                skipped += 1
                continue
            rows.append((at, _parse_len(low.get("contexttokens")),
                         _parse_len(low.get("generatedtokens"))))
    return rows, skipped


def _read_mooncake(path: str) -> Tuple[List[Row], int]:
    rows: List[Row] = []
    skipped = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if not isinstance(rec, dict):
                skipped += 1
                continue
            if rec.get("at_s") is not None:       # already native
                at = _parse_timestamp(rec["at_s"])
                plen = _parse_len(rec.get("prompt_len"))
                gen = _parse_len(rec.get("gen_tokens"))
            else:
                ms = _parse_timestamp(rec.get("timestamp"))
                at = None if ms is None else ms / 1000.0
                plen = _parse_len(rec.get("input_length"))
                gen = _parse_len(rec.get("output_length"))
            if at is None:
                skipped += 1
                continue
            rows.append((at, plen, gen))
    return rows, skipped


_READERS = {"azure": _read_azure, "mooncake": _read_mooncake}


def convert_trace(src: str, dst: str, fmt: str = "auto",
                  limit: Optional[int] = None) -> dict:
    """Convert ``src`` → ``dst`` (loadgen trace JSONL).  Returns a
    summary dict: rows written, rows skipped, detected format, span."""
    if fmt == "auto":
        fmt = detect_format(src)
    if fmt not in _READERS:
        raise ValueError(f"unknown trace format {fmt!r}; "
                         f"expected one of {sorted(_READERS)}")
    rows, skipped = _READERS[fmt](src)
    rows.sort(key=lambda r: r[0])
    if limit is not None:
        rows = rows[:limit]
    t0 = rows[0][0] if rows else 0.0
    with open(dst, "w") as f:
        for at, plen, gen in rows:
            rec = {"at_s": round(at - t0, 6)}
            if plen is not None:
                rec["prompt_len"] = plen
            if gen is not None:
                rec["gen_tokens"] = gen
            f.write(json.dumps(rec) + "\n")
    return {"format": fmt, "rows": len(rows), "skipped": skipped,
            "span_s": round(rows[-1][0] - t0, 6) if rows else 0.0}


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m tools.loadgen convert",
        description="convert a public serving trace into the loadgen "
                    "trace JSONL shape (load_trace format)")
    ap.add_argument("src", help="source trace (Azure CSV or Mooncake "
                                "JSONL)")
    ap.add_argument("dst", help="output JSONL path")
    ap.add_argument("--format", default="auto",
                    choices=("auto", "azure", "mooncake"),
                    help="source dialect (default: sniff the file)")
    ap.add_argument("--limit", type=int, default=None,
                    help="keep only the first N rows after sorting")
    args = ap.parse_args(argv)
    print(json.dumps(convert_trace(args.src, args.dst,
                                   fmt=args.format,
                                   limit=args.limit)))


if __name__ == "__main__":
    main()
