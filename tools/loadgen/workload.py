"""Open-loop workload generation (docs/serving.md "workload plane").

One declarative spec — arrival process x prompt/output length
distributions x template/prefix mix x session idle gaps — compiled by
:meth:`Workload.build` into a flat arrival schedule of
:class:`WorkloadItem` (``at_s`` offset, prompt token ids, generation
budget).  The schedule is what the harness replays OPEN-LOOP: arrivals
fire on the clock regardless of completions, which is what makes
queueing (and therefore goodput) measurable at all.

Determinism is a hard contract: ``build(seed)`` uses two independent
``numpy`` generators — one for the arrival process, one for the
payload (lengths, token ids, template choice) — so two workloads that
differ ONLY in arrival shape serve byte-identical prompts, and the
same seed reproduces the same schedule byte for byte across runs
(pinned in tests/test_loadgen.py).
"""
from __future__ import annotations

import dataclasses
import json
from typing import List, Optional, Sequence, Tuple

import numpy as np

ARRIVAL_KINDS = ("uniform", "poisson", "gamma_burst", "trace")
LENGTH_KINDS = ("fixed", "choice", "lognormal")


@dataclasses.dataclass(frozen=True)
class WorkloadItem:
    """One scheduled request: arrive at ``t0 + at_s``, submit
    ``prompt``, generate up to ``max_new_tokens``."""
    at_s: float
    prompt: Tuple[int, ...]
    max_new_tokens: int
    session: int = 0
    #: tenant LoRA adapter id (0 = base model) — forwarded to
    #: ``submit(adapter_id=...)`` by the harness
    tenant: int = 0


@dataclasses.dataclass
class ArrivalSpec:
    """The arrival process.

    ``uniform``      one request every ``period`` seconds (period 0 =
                     the saturation snapshot: everything due at t0)
    ``poisson``      exponential inter-arrivals at mean ``rate``/s
    ``gamma_burst``  gamma inter-arrivals at mean ``rate``/s with
                     coefficient of variation ``cv`` > 1 — the
                     heavy-tailed clumping of production traces
                     (Mooncake/Splitwise, PAPERS.md): most gaps ~0
                     (a burst), occasional long quiets
    ``trace``        replay explicit offsets (seconds from t0), e.g.
                     from :func:`load_trace`
    """
    kind: str = "uniform"
    period: float = 0.0          # uniform: seconds between arrivals
    rate: float = 10.0           # poisson/gamma_burst: mean requests/s
    cv: float = 4.0              # gamma_burst: inter-arrival CV (>1)
    trace: Tuple[float, ...] = ()

    def offsets(self, n: int, rng: np.random.Generator) -> List[float]:
        if self.kind == "uniform":
            return [i * self.period for i in range(n)]
        if self.kind == "poisson":
            gaps = rng.exponential(1.0 / self.rate, n)
        elif self.kind == "gamma_burst":
            # shape < 1 clumps arrivals: var = cv^2 / rate^2
            shape = 1.0 / (self.cv ** 2)
            gaps = rng.gamma(shape, self.cv ** 2 / self.rate, n)
        elif self.kind == "trace":
            if len(self.trace) < n:
                raise ValueError(
                    f"trace has {len(self.trace)} offsets but the "
                    f"workload asks for {n} requests")
            t0 = self.trace[0]
            return [float(t) - t0 for t in self.trace[:n]]
        else:
            raise ValueError(f"unknown arrival kind {self.kind!r} "
                             f"(one of {ARRIVAL_KINDS})")
        # first request arrives at t0 (like every bench leg so far);
        # the remaining gaps carry the process's shape
        offs = np.cumsum(gaps) - gaps[0]
        return [float(t) for t in offs]


@dataclasses.dataclass
class LengthSpec:
    """A token-count distribution: ``fixed`` (always ``value``),
    ``choice`` (weighted discrete ``choices`` of (length, weight)),
    or ``lognormal`` (heavy-tailed around ``median``, clamped to
    [``lo``, ``hi``])."""
    kind: str = "fixed"
    value: int = 8
    choices: Tuple[Tuple[int, float], ...] = ()
    median: float = 8.0
    sigma: float = 0.8
    lo: int = 1
    hi: int = 64

    def sample(self, rng: np.random.Generator) -> int:
        if self.kind == "fixed":
            return int(self.value)
        if self.kind == "choice":
            lens = [c[0] for c in self.choices]
            w = np.array([c[1] for c in self.choices], dtype=float)
            return int(lens[rng.choice(len(lens), p=w / w.sum())])
        if self.kind == "lognormal":
            v = rng.lognormal(mean=float(np.log(self.median)),
                              sigma=self.sigma)
            return int(min(max(round(v), self.lo), self.hi))
        raise ValueError(f"unknown length kind {self.kind!r} "
                         f"(one of {LENGTH_KINDS})")


@dataclasses.dataclass
class TenantSpec:
    """The multi-tenant dimension: each request draws a tenant (LoRA
    adapter id ``1..n_tenants``) from a Zipf-like power law of
    exponent ``s`` — a few hot tenants and a long cold tail, the
    S-LoRA/Punica serving regime (PAPERS.md).  Adapter id 0 (the base
    model) is expressed by leaving ``Workload.tenants`` unset, never
    drawn."""
    n_tenants: int = 8
    s: float = 1.2

    def sample(self, rng: np.random.Generator) -> int:
        ranks = np.arange(1, self.n_tenants + 1, dtype=float)
        w = ranks ** -float(self.s)
        return int(rng.choice(self.n_tenants, p=w / w.sum())) + 1


@dataclasses.dataclass
class Workload:
    """The full spec.  ``mix`` (when non-empty) overrides the two
    LengthSpecs with a deterministic per-index cycle of
    ``(prompt_len, gen_tokens)`` classes — how the paged/quant legs
    express their exact short/long geometry.  ``template_ratio`` of
    requests share one of ``templates`` random prefixes of
    ``template_len`` tokens (unique suffix fills the sampled prompt
    length) — the prefix-cache mix.  ``session_len`` > 0 groups
    consecutive arrivals into sessions and inserts ``idle_gap_s`` of
    think-time between them (the schedule shifts; the process's gaps
    within a session are untouched)."""
    n_requests: int
    arrival: ArrivalSpec = dataclasses.field(default_factory=ArrivalSpec)
    prompt_len: LengthSpec = dataclasses.field(
        default_factory=lambda: LengthSpec(value=8))
    gen_tokens: LengthSpec = dataclasses.field(
        default_factory=lambda: LengthSpec(value=16))
    mix: Tuple[Tuple[int, int], ...] = ()
    vocab: int = 256
    template_ratio: float = 0.0
    template_len: int = 0
    templates: int = 1
    session_len: int = 0
    idle_gap_s: float = 0.0
    tenants: Optional[TenantSpec] = None

    def build(self, seed: int = 0) -> List[WorkloadItem]:
        arr_rng = np.random.default_rng([int(seed), 0])
        pay_rng = np.random.default_rng([int(seed), 1])
        # the tenant draw rides its own payload-side stream: arrival
        # shape never changes the tenant sequence, and enabling tenants
        # leaves lengths/prompts (pay_rng's draws) bitwise unchanged
        ten_rng = np.random.default_rng([int(seed), 2])
        offs = self.arrival.offsets(self.n_requests, arr_rng)
        tmpl = [
            [int(t) for t in pay_rng.integers(0, self.vocab,
                                              (self.template_len,))]
            for _ in range(self.templates)
        ] if self.template_len > 0 else []
        items: List[WorkloadItem] = []
        gap = 0.0
        for i, at in enumerate(offs):
            session = i // self.session_len if self.session_len else 0
            if self.session_len and i and i % self.session_len == 0:
                gap += self.idle_gap_s
            if self.mix:
                p_len, gen = self.mix[i % len(self.mix)]
            else:
                p_len = self.prompt_len.sample(pay_rng)
                gen = self.gen_tokens.sample(pay_rng)
            if tmpl and pay_rng.random() < self.template_ratio:
                base = tmpl[int(pay_rng.integers(self.templates))]
                tail = max(int(p_len) - len(base), 1)
                prompt = base + [int(t) for t in pay_rng.integers(
                    0, self.vocab, (tail,))]
            else:
                prompt = [int(t) for t in pay_rng.integers(
                    0, self.vocab, (int(p_len),))]
            items.append(WorkloadItem(
                at_s=round(float(at) + gap, 6),
                prompt=tuple(prompt),
                max_new_tokens=int(gen),
                session=session,
                tenant=(self.tenants.sample(ten_rng)
                        if self.tenants else 0)))
        return items


def schedule_fingerprint(items: Sequence[WorkloadItem]) -> str:
    """Canonical JSON of a built schedule — the byte-identity handle
    the determinism tests (and any trace export) compare."""
    return json.dumps([dataclasses.asdict(it) for it in items],
                      sort_keys=True)


def load_trace(path: str) -> Tuple[ArrivalSpec, List[dict]]:
    """Read a replayable trace (one JSON object per line:
    ``{"at_s": ..., "prompt_len": ..., "gen_tokens": ...}`` — the
    Mooncake-style shape, lengths optional) tolerantly: torn lines are
    skipped, matching the summarize idiom.  Returns the trace-replay
    ArrivalSpec plus the raw records for length overrides."""
    offsets: List[float] = []
    records: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("at_s") is None:
                continue
            offsets.append(float(rec["at_s"]))
            records.append(rec)
    return ArrivalSpec(kind="trace", trace=tuple(offsets)), records
